"""NYCTaxi with XGBoostEstimator — the reference's xgboost_ray_nyctaxi.py
(examples/xgboost_ray_nyctaxi.py:41-47) on this framework: distributed GBDT
over SPMD rank actors. Runs on xgboost's collective when installed, otherwise
on the built-in distributed histogram GBDT (estimator/gbdt_native.py)."""
# raydp-lint: disable-file=print-diagnostics  (examples narrate to stdout by design — they run standalone, before any obs plane exists)

import os

import numpy as np

import raydp_tpu
from raydp_tpu.estimator import XGBoostEstimator
from raydp_tpu.etl import functions as F

from nyctaxi_jax import synthetic_taxi


def main():
    session = raydp_tpu.init_etl(
        "nyctaxi-xgb", num_executors=2, executor_cores=1, executor_memory="500M"
    )
    rows = int(os.environ.get("EXAMPLE_ROWS", 100_000))
    df = session.from_pandas(synthetic_taxi(rows), num_partitions=4)
    df = (
        df.with_column("hour", F.hour("pickup_ts").cast("float32"))
        .with_column("dow", F.dayofweek("pickup_ts").cast("float32"))
        .with_column("pc", F.col("passenger_count").cast("float32"))
        .with_column("label", F.col("fare_amount").cast("float32"))
        .select("hour", "dow", "pc", "label")
    )

    est = XGBoostEstimator(
        params={"objective": "reg:squarederror", "eta": 0.3, "max_depth": 5},
        num_boost_round=int(os.environ.get("EXAMPLE_ROUNDS", 10)),
        feature_columns=["hour", "dow", "pc"],
        label_column="label",
        num_workers=2,
    )
    est.fit_on_etl(df)
    model = est.get_model()
    print("backend:", est.backend)
    sample = np.array([[12.0, 3.0, 2.0]])
    print("prediction for noon/wed/2pax:", float(np.asarray(model.predict(sample)).reshape(-1)[0]))


if __name__ == "__main__":
    main()
