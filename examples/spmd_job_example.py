"""SPMD job launcher — the reference's doc/mpi.md example reshaped: ship a
function to a gang of rank actors and gather results (no mpirun, no gRPC)."""
# raydp-lint: disable-file=print-diagnostics  (examples narrate to stdout by design — they run standalone, before any obs plane exists)

import raydp_tpu


def main():
    job = raydp_tpu.create_spmd_job("demo", world_size=4).start()
    try:
        results = job.run(lambda ctx: f"hello from rank {ctx.rank}/{ctx.world_size}")
        for line in results:
            print(line)

        # numeric allreduce-style aggregation via gather
        partials = job.run(lambda ctx: sum(range(ctx.rank * 100, (ctx.rank + 1) * 100)))
        print("sum over ranks:", sum(partials))
    finally:
        job.stop()


if __name__ == "__main__":
    main()
