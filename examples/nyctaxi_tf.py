"""NYCTaxi with TFEstimator — the reference's tensorflow_nyctaxi.py
(examples/tensorflow_nyctaxi.py:20-22) on this framework: keras MLP trained
with MultiWorkerMirroredStrategy ranks on the SPMD launcher."""
# raydp-lint: disable-file=print-diagnostics  (examples narrate to stdout by design — they run standalone, before any obs plane exists)

import os

import raydp_tpu
from raydp_tpu.estimator import TFEstimator
from raydp_tpu.etl import functions as F

from nyctaxi_jax import synthetic_taxi


def make_model():
    import tensorflow as tf

    return tf.keras.Sequential(
        [
            tf.keras.layers.Input(shape=(4,)),
            tf.keras.layers.Dense(64, activation="relu"),
            tf.keras.layers.Dense(32, activation="relu"),
            tf.keras.layers.Dense(1),
        ]
    )


def main():
    import tensorflow as tf

    session = raydp_tpu.init_etl(
        "nyctaxi-tf", num_executors=2, executor_cores=1, executor_memory="500M"
    )
    rows = int(os.environ.get("EXAMPLE_ROWS", 100_000))
    df = session.from_pandas(synthetic_taxi(rows), num_partitions=4)
    df = (
        df.with_column("hour", F.hour("pickup_ts").cast("float32"))
        .with_column("dow", F.dayofweek("pickup_ts").cast("float32"))
        .with_column("dx", F.col("dropoff_longitude") - F.col("pickup_longitude"))
        .with_column("dy", F.col("dropoff_latitude") - F.col("pickup_latitude"))
        .with_column(
            "dist",
            F.sqrt(F.col("dx") * F.col("dx") + F.col("dy") * F.col("dy")).cast("float32"),
        )
        .with_column("pc", F.col("passenger_count").cast("float32"))
        .with_column("label", F.col("fare_amount").cast("float32"))
        .select("hour", "dow", "dist", "pc", "label")
    )

    est = TFEstimator(
        model=make_model,
        optimizer=tf.keras.optimizers.Adam(0.01),
        loss="mse",
        metrics=["mae"],
        feature_columns=["hour", "dow", "dist", "pc"],
        label_column="label",
        batch_size=64,
        num_epochs=int(os.environ.get("EXAMPLE_EPOCHS", 5)),
        num_workers=2,
        seed=0,
    )
    history = est.fit_on_etl(df)
    print("losses:", [round(v, 4) for v in history["loss"]])


if __name__ == "__main__":
    main()
