"""Pure-ETL pipeline — the reference's data_process.py: load, feature
engineering, groupby aggregation, join, sorted report — exercising the
distributed DataFrame engine with no training stage."""
# raydp-lint: disable-file=print-diagnostics  (examples narrate to stdout by design — they run standalone, before any obs plane exists)

import os

import raydp_tpu
from raydp_tpu.etl import functions as F

from nyctaxi_jax import synthetic_taxi


def main():
    session = raydp_tpu.init_etl(
        "data-process", num_executors=2, executor_cores=2, executor_memory="1G"
    )
    rows = int(os.environ.get("EXAMPLE_ROWS", 100_000))
    df = session.from_pandas(synthetic_taxi(rows), num_partitions=8)

    trips = (
        df.with_column("hour", F.hour("pickup_ts"))
        .with_column("dow", F.dayofweek("pickup_ts"))
        .with_column("fare", F.col("fare_amount").cast("float64"))
        .select("hour", "dow", "passenger_count", "fare")
        .filter(F.col("fare") > 0)
    )

    by_hour = trips.groupby("hour").agg(
        trips=("count", "*"), avg_fare=("mean", "fare")
    )
    by_dow = trips.groupby("dow").agg(dow_trips=("count", "*"))

    # join hourly stats against day-of-week volume and report the busiest
    report = (
        trips.groupby("hour", "dow")
        .agg(n=("count", "*"), fare_sum=("sum", "fare"))
        .join(by_hour, on="hour")
        .join(by_dow, on="dow")
        .sort("n", ascending=False)
        .limit(10)
        .to_pandas()
    )
    print(report.to_string(index=False))
    print("total trips:", trips.count())
    raydp_tpu.stop_etl()


if __name__ == "__main__":
    main()
