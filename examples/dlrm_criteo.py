"""DLRM on Criteo-shaped data — the reference's pytorch_dlrm.ipynb pipeline,
TPU-native: categorical hashing runs on the ETL engine (F.hash = the
notebook's category→id step), embedding tables are vocab-sharded over the
"model" mesh axis, the dot interaction is the fused MXU op.

Synthetic Criteo-shaped data by default; argv[1] = path to a Criteo tsv
sample to run the real preprocessing (13 int + 26 categorical columns).
"""
# raydp-lint: disable-file=print-diagnostics  (examples narrate to stdout by design — they run standalone, before any obs plane exists)

import os
import sys

import numpy as np
import pandas as pd

import raydp_tpu
from raydp_tpu.estimator import JaxEstimator
from raydp_tpu.etl import functions as F
from raydp_tpu.models import DLRM, dlrm_optimizer, dlrm_sharding_rules
from raydp_tpu.parallel import make_mesh

NUM_DENSE = 4
CAT_VOCABS = [1000, 1000, 500, 100]


def synthetic_criteo(n_rows: int) -> pd.DataFrame:
    rng = np.random.default_rng(3)
    data = {"label": rng.integers(0, 2, n_rows).astype(np.float32)}
    for i in range(NUM_DENSE):
        data[f"i{i}"] = rng.integers(0, 100, n_rows).astype(np.float32)
    for j, vocab in enumerate(CAT_VOCABS):
        data[f"c{j}"] = [f"cat{v}" for v in rng.integers(0, vocab, n_rows)]
    return pd.DataFrame(data)


def main():
    import jax

    session = raydp_tpu.init_etl(
        "dlrm", num_executors=2, executor_cores=2, executor_memory="1G"
    )
    rows = int(os.environ.get("EXAMPLE_ROWS", 50_000))
    df = session.from_pandas(synthetic_criteo(rows), num_partitions=8)

    # preprocessing (notebook parity): log1p the dense ints, hash categories.
    # Ids stay INTEGER end to end: the estimator's categorical_columns stage
    # them as a separate int32 matrix, exact at ANY vocab size (a float32
    # matrix would silently collapse ids beyond 2^24 — real Criteo vocabs
    # are tens of millions)
    for i in range(NUM_DENSE):
        df = df.with_column(f"i{i}", F.log1p(F.col(f"i{i}")).cast("float32"))
    for j, vocab in enumerate(CAT_VOCABS):
        df = df.with_column(f"c{j}", F.hash(f"c{j}", vocab).cast("int32"))

    dense_cols = [f"i{i}" for i in range(NUM_DENSE)]
    cat_cols = [f"c{j}" for j in range(len(CAT_VOCABS))]
    train_df, test_df = df.random_split([0.9, 0.1], seed=0)

    n_dev = len(jax.devices())
    model_axis = 2 if n_dev % 2 == 0 else 1
    mesh = make_mesh({"data": n_dev // model_axis, "model": model_axis})

    est = JaxEstimator(
        model=DLRM(
            vocab_sizes=CAT_VOCABS, num_dense=NUM_DENSE, embed_dim=16,
            bottom_mlp=(64, 32), top_mlp=(64, 32),
        ),
        # Adafactor on the tables, Adam on the MLPs: dense Adam's two
        # full-table moment copies OOM a chip at real Criteo vocabs
        optimizer=dlrm_optimizer(),
        loss="bce",
        metrics=["accuracy"],
        feature_columns=dense_cols + cat_cols,
        categorical_columns=cat_cols,  # (dense f32, ids i32) mixed staging
        label_column="label",
        batch_size=512,
        num_epochs=int(os.environ.get("EXAMPLE_EPOCHS", 3)),
        learning_rate=1e-3,
        mesh=mesh,
        param_sharding_rules=dlrm_sharding_rules(),
    )
    history = est.fit_on_etl(train_df, test_df)
    for record in history:
        print(record)
    raydp_tpu.stop_etl()


if __name__ == "__main__":
    main()
