"""NYCTaxi with TorchEstimator — the reference's pytorch_nyctaxi.py
(examples/pytorch_nyctaxi.py:22-24,71-75) on this framework: same ETL
pipeline, torch MLP trained with DDP (gloo) ranks on the SPMD launcher."""
# raydp-lint: disable-file=print-diagnostics  (examples narrate to stdout by design — they run standalone, before any obs plane exists)

import os

import raydp_tpu
from raydp_tpu.estimator import TorchEstimator
from raydp_tpu.etl import functions as F

from nyctaxi_jax import synthetic_taxi  # same feature pipeline source


def make_model():
    import torch

    return torch.nn.Sequential(
        torch.nn.Linear(4, 64),
        torch.nn.ReLU(),
        torch.nn.Linear(64, 32),
        torch.nn.ReLU(),
        torch.nn.Linear(32, 1),
    )


def main():
    import torch

    session = raydp_tpu.init_etl(
        "nyctaxi-torch", num_executors=2, executor_cores=1, executor_memory="500M"
    )
    rows = int(os.environ.get("EXAMPLE_ROWS", 100_000))
    df = session.from_pandas(synthetic_taxi(rows), num_partitions=4)
    df = (
        df.with_column("hour", F.hour("pickup_ts").cast("float32"))
        .with_column("dow", F.dayofweek("pickup_ts").cast("float32"))
        .with_column("dx", F.col("dropoff_longitude") - F.col("pickup_longitude"))
        .with_column("dy", F.col("dropoff_latitude") - F.col("pickup_latitude"))
        .with_column(
            "dist",
            F.sqrt(F.col("dx") * F.col("dx") + F.col("dy") * F.col("dy")).cast("float32"),
        )
        .with_column("pc", F.col("passenger_count").cast("float32"))
        .with_column("label", F.col("fare_amount").cast("float32"))
        .select("hour", "dow", "dist", "pc", "label")
    )

    est = TorchEstimator(
        model=make_model,
        optimizer="Adam",
        loss=torch.nn.MSELoss,
        feature_columns=["hour", "dow", "dist", "pc"],
        label_column="label",
        batch_size=64,
        num_epochs=int(os.environ.get("EXAMPLE_EPOCHS", 5)),
        num_workers=2,
        learning_rate=1e-2,
        seed=0,
    )
    history = est.fit_on_etl(df)
    for record in history:
        print(record)
    print("final train_loss", history[-1]["train_loss"])


if __name__ == "__main__":
    main()
