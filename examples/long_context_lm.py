"""Long-context LM training with ring attention: the sequence is sharded over
every device; each device holds T/N tokens and K/V blocks rotate over ICI.
Nothing like this exists in the reference — long context is first-class here.

Run under a CPU mesh for demonstration:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/long_context_lm.py
"""
# raydp-lint: disable-file=print-diagnostics  (examples narrate to stdout by design — they run standalone, before any obs plane exists)

import dataclasses

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from raydp_tpu.models import TransformerLM, sequence_parallel_apply
    from raydp_tpu.parallel import make_mesh

    n_dev = len(jax.devices())
    mesh = make_mesh({"sp": n_dev})
    seq = 128 * n_dev  # a sequence n_dev× longer than one device's share

    model = TransformerLM(
        vocab_size=256, d_model=128, num_heads=n_dev, num_layers=2,
        max_len=seq, attn_impl="ring", dtype=jnp.float32,
    )
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 256, size=(2, seq)), jnp.int32)

    params = dataclasses.replace(model, attn_impl="full").init(
        jax.random.PRNGKey(0), tokens[:, :16]
    )
    tx = optax.adam(3e-4)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, tokens):
        def loss_fn(p):
            logits = sequence_parallel_apply(model, p, tokens, mesh)
            shifted = jnp.roll(tokens, -1, axis=1)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(logits, shifted)
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    for i in range(5):
        params, opt_state, loss = step(params, opt_state, tokens)
        print(f"step {i}: loss {float(loss):.4f} (seq={seq} over {n_dev} devices)")


if __name__ == "__main__":
    main()
