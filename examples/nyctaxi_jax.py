"""NYCTaxi fare regression, end to end — the reference's flagship example
(examples/pytorch_nyctaxi.py) reshaped: ETL feature engineering on the
distributed DataFrame engine, exchange into the object store, JaxEstimator MLP
trained data-parallel on the device mesh.

Uses synthetic taxi-shaped data by default; pass a parquet directory of real
NYCTaxi data as argv[1] to run on it.
"""
# raydp-lint: disable-file=print-diagnostics  (examples narrate to stdout by design — they run standalone, before any obs plane exists)

import os
import sys

import numpy as np
import pandas as pd

import raydp_tpu
from raydp_tpu.estimator import JaxEstimator
from raydp_tpu.etl import functions as F
from raydp_tpu.models import MLPRegressor


def synthetic_taxi(n_rows: int) -> pd.DataFrame:
    rng = np.random.default_rng(7)
    base = pd.Timestamp("2020-01-01").value // 10**9
    duration = rng.integers(120, 3600, n_rows)
    return pd.DataFrame(
        {
            "pickup_ts": pd.to_datetime(
                base + rng.integers(0, 30 * 24 * 3600, n_rows), unit="s"
            ),
            "passenger_count": rng.integers(1, 6, n_rows).astype(np.int64),
            "pickup_longitude": -74.0 + rng.random(n_rows) * 0.1,
            "pickup_latitude": 40.7 + rng.random(n_rows) * 0.1,
            "dropoff_longitude": -74.0 + rng.random(n_rows) * 0.1,
            "dropoff_latitude": 40.7 + rng.random(n_rows) * 0.1,
            "fare_amount": 2.5 + duration / 240.0 + rng.random(n_rows),
        }
    )


def main():
    session = raydp_tpu.init_etl(
        "nyctaxi", num_executors=2, executor_cores=2, executor_memory="1G"
    )
    if len(sys.argv) > 1:
        df = session.read_parquet(sys.argv[1])
    else:
        rows = int(os.environ.get("EXAMPLE_ROWS", 100_000))
        df = session.from_pandas(synthetic_taxi(rows), num_partitions=8)

    df = (
        df.with_column("hour", F.hour("pickup_ts").cast("float32"))
        .with_column("dow", F.dayofweek("pickup_ts").cast("float32"))
        .with_column("dx", F.col("dropoff_longitude") - F.col("pickup_longitude"))
        .with_column("dy", F.col("dropoff_latitude") - F.col("pickup_latitude"))
        .with_column(
            "dist",
            F.sqrt(F.col("dx") * F.col("dx") + F.col("dy") * F.col("dy")).cast("float32"),
        )
        .with_column("pc", F.col("passenger_count").cast("float32"))
        .with_column("label", F.col("fare_amount").cast("float32"))
        .select("hour", "dow", "dist", "pc", "label")
        .dropna()
    )
    train_df, test_df = df.random_split([0.9, 0.1], seed=0)

    est = JaxEstimator(
        model=MLPRegressor(),
        optimizer="adam",
        loss="mse",
        metrics=["mse", "mae"],
        feature_columns=["hour", "dow", "dist", "pc"],
        label_column="label",
        batch_size=256,
        num_epochs=int(os.environ.get("EXAMPLE_EPOCHS", 5)),
        learning_rate=1e-3,
        # for datasets larger than host memory, pass streaming=True
        # (O(block) memory) or streaming="hybrid" (epoch 1 streams, later
        # epochs scan device-pinned segments — no host IO, ~5x faster)
    )
    history = est.fit_on_etl(train_df, test_df, stop_etl_after_conversion=True)
    for record in history:
        print(record)


if __name__ == "__main__":
    main()
