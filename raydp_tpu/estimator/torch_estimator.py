"""TorchEstimator — parity estimator for torch users.

The reference's TorchEstimator (torch/estimator.py:73-377) delegates to Ray
Train's TorchTrainer, which spawns DDP workers whose gradients all-reduce over
Gloo/NCCL. Here the worker group is this framework's SPMD job launcher
(raydp_tpu.spmd): one rank actor per worker, ``torch.distributed`` process
group over gloo, and each rank reads its equal-share dataset shard straight
from the shared-memory object store (zero extra copies — the blocks were
written once by the ETL executors).

Kept from the reference: model/optimizer/loss as instances *or* creator fns
(:88-136), per-epoch train/eval, shuffle, ``fit_on_etl`` conversion flow,
``max_retries``; the trained ``state_dict`` ships back and ``get_model``
reloads it (:365-377).

This is the CPU/GPU-parity path; the TPU-native flagship is JaxEstimator.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from raydp_tpu.estimator.base import EstimatorInterface, EtlEstimatorInterface


class _TorchWorkerFn:
    """Picklable per-rank training closure (shipped via the SPMD job)."""

    def __init__(self, estimator: "TorchEstimator", shards, eval_shards, addr: str):
        self.est_config = {
            "model": estimator._model_arg,
            "optimizer": estimator._optimizer_arg,
            "loss": estimator._loss_arg,
            "feature_columns": estimator.feature_columns,
            "label_column": estimator.label_column,
            "batch_size": estimator.batch_size,
            "num_epochs": estimator.num_epochs,
            "learning_rate": estimator.learning_rate,
            "shuffle": estimator.shuffle,
            "seed": estimator.seed,
        }
        self.shards = shards
        self.eval_shards = eval_shards
        self.addr = addr

    def __call__(self, ctx):
        import torch
        import torch.distributed as dist

        cfg = self.est_config
        # the gloo store binds on RANK 0's node (job.rendezvous_address),
        # so ranks the SPREAD placement lands on other hosts can join —
        # the reference gets this from Ray Train's cross-host rendezvous
        # (torch/estimator.py:311-327)
        dist.init_process_group(
            "gloo",
            init_method=f"tcp://{self.addr}",
            rank=ctx.rank,
            world_size=ctx.world_size,
        )
        try:
            torch.manual_seed(cfg["seed"])
            model = cfg["model"]
            if callable(model) and not isinstance(model, torch.nn.Module):
                model = model()
            model = torch.nn.parallel.DistributedDataParallel(model)

            optimizer = _build_optimizer(cfg["optimizer"], model, cfg["learning_rate"])
            loss_fn = cfg["loss"]
            if isinstance(loss_fn, type):  # class (e.g. torch.nn.MSELoss)
                loss_fn = loss_fn()
            # else: an nn.Module instance or a plain callable(pred, target)

            shard = self.shards[ctx.rank]
            features, labels = shard.to_numpy(
                cfg["feature_columns"], cfg["label_column"]
            )
            x = torch.from_numpy(features)
            y = torch.from_numpy(labels)

            history = []
            n = len(x)
            batch = cfg["batch_size"]
            for epoch in range(cfg["num_epochs"]):
                model.train()
                order = np.arange(n)
                if cfg["shuffle"]:
                    np.random.default_rng(cfg["seed"] + epoch).shuffle(order)
                total, steps = 0.0, 0
                for s in range(0, (n // batch) * batch, batch):
                    idx = order[s : s + batch]
                    optimizer.zero_grad()
                    pred = model(x[idx])
                    loss = loss_fn(pred.reshape(y[idx].shape), y[idx])
                    loss.backward()  # DDP all-reduces gradients here
                    optimizer.step()
                    total += float(loss.detach())
                    steps += 1
                record = {"epoch": epoch, "train_loss": total / max(steps, 1)}
                if self.eval_shards is not None:
                    record.update(
                        self._evaluate(model, loss_fn, cfg, ctx.rank)
                    )
                history.append(record)

            state = {
                k: v.cpu().numpy()
                for k, v in model.module.state_dict().items()
            }
            return {"history": history, "state": state if ctx.rank == 0 else None}
        finally:
            dist.destroy_process_group()

    def _evaluate(self, model, loss_fn, cfg, rank) -> Dict[str, float]:
        import torch
        import torch.distributed as dist

        shard = self.eval_shards[rank]
        features, labels = shard.to_numpy(
            cfg["feature_columns"], cfg["label_column"]
        )
        model.eval()
        batch = cfg["batch_size"]
        total = torch.zeros(1)
        count = torch.zeros(1)
        with torch.no_grad():
            for s in range(0, len(features), batch):
                xb = torch.from_numpy(features[s : s + batch])
                yb = torch.from_numpy(labels[s : s + batch])
                loss = loss_fn(model(xb).reshape(yb.shape), yb)
                total += float(loss) * len(xb)
                count += len(xb)
        # mean over ALL ranks' shards (the reference's Ray Train reporting)
        dist.all_reduce(total)
        dist.all_reduce(count)
        return {"eval_loss": float(total) / max(float(count), 1.0)}


def _build_optimizer(opt, model, lr: float):
    import torch

    if opt is None:
        return torch.optim.Adam(model.parameters(), lr=lr)
    if isinstance(opt, str):
        return getattr(torch.optim, opt)(model.parameters(), lr=lr)
    if isinstance(opt, type):
        return opt(model.parameters(), lr=lr)
    if isinstance(opt, torch.optim.Optimizer):
        # instance given: re-instantiate on the (DDP) model's params with the
        # same hyperparams (reference rebuilds from the given instance, :176-188)
        defaults = dict(opt.defaults)
        return type(opt)(model.parameters(), **defaults)
    if callable(opt):
        return opt(model)
    raise TypeError(f"cannot build optimizer from {type(opt)}")


class TorchEstimator(EstimatorInterface, EtlEstimatorInterface):
    def __init__(
        self,
        model: Any = None,
        optimizer: Any = None,
        loss: Any = None,
        feature_columns: Optional[Sequence[str]] = None,
        label_column: Optional[str] = None,
        batch_size: int = 64,
        num_epochs: int = 10,
        num_workers: int = 1,
        learning_rate: float = 1e-3,
        shuffle: bool = True,
        seed: int = 0,
    ):
        import torch

        self._model_arg = model
        self._optimizer_arg = optimizer
        self._loss_arg = loss if loss is not None else torch.nn.MSELoss
        self.feature_columns = list(feature_columns or [])
        self.label_column = label_column
        self.batch_size = batch_size
        self.num_epochs = num_epochs
        self.num_workers = num_workers
        self.learning_rate = learning_rate
        self.shuffle = shuffle
        self.seed = seed
        self._state: Optional[Dict[str, np.ndarray]] = None
        self._history: List[Dict[str, float]] = []

    def fit(self, train_ds, evaluate_ds=None, max_retries: int = 0):
        from raydp_tpu.spmd import create_spmd_job

        attempts = 0
        while True:
            try:
                shards = train_ds.split(self.num_workers, equal=True)
                eval_shards = (
                    evaluate_ds.split(self.num_workers, equal=True)
                    if evaluate_ds is not None
                    else None
                )
                job = create_spmd_job(
                    world_size=self.num_workers, placement_strategy="SPREAD"
                ).start()
                try:
                    # resolve AFTER start: the rendezvous must live where
                    # rank 0 actually landed, not on the driver's host
                    worker_fn = _TorchWorkerFn(
                        self, shards, eval_shards, job.rendezvous_address()
                    )
                    results = job.run(worker_fn, timeout=600.0)
                finally:
                    job.stop()
                self._history = results[0]["history"]
                self._state = results[0]["state"]
                return self._history
            except Exception:
                attempts += 1
                if attempts > max_retries:
                    raise

    # fit_on_etl (incl. the fs_directory parquet staging path) is inherited
    # from EtlEstimatorInterface — shared by every estimator

    def get_model(self):
        import torch

        if self._state is None:
            raise RuntimeError("call fit() first")
        model = self._model_arg
        if callable(model) and not isinstance(model, torch.nn.Module):
            model = model()
        model.load_state_dict(
            {k: torch.from_numpy(np.asarray(v)) for k, v in self._state.items()}
        )
        return model

    @property
    def history(self) -> List[Dict[str, float]]:
        return self._history
