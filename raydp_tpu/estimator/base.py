"""Estimator interfaces.

Parity: reference ``EstimatorInterface`` (estimator.py:23-43) and
``SparkEstimatorInterface._check_and_convert`` (spark/interfaces.py:27-39) —
the sklearn-style fit/get_model contract plus the ETL-DataFrame adapter mixin.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Optional


class EstimatorInterface(ABC):
    """sklearn-style distributed estimator: fit on Datasets, export a model."""

    @abstractmethod
    def fit(self, train_ds, evaluate_ds=None, max_retries: int = 0) -> Any:
        ...

    @abstractmethod
    def get_model(self) -> Any:
        ...


class EtlEstimatorInterface(ABC):
    """Adds fit_on_etl: accepts ETL DataFrames directly and converts through
    the exchange layer (reference fit_on_spark, torch/estimator.py:332-363)."""

    def _check_and_convert(self, df):
        """Adopt the input as an ETL DataFrame. A plain pandas DataFrame is
        distributed through the running session transparently — the
        reference accepts pandas-on-Spark frames the same way
        (spark/interfaces.py:27-39, utils.py:116-122)."""
        from raydp_tpu.etl.dataframe import DataFrame

        if isinstance(df, DataFrame):
            return df
        try:
            import pandas as pd
        except ImportError:  # pragma: no cover
            pd = None
        if pd is not None and isinstance(df, pd.DataFrame):
            from raydp_tpu.etl.session import active_session

            session = active_session()
            if session is None:
                raise RuntimeError(
                    "fit_on_etl received a pandas DataFrame but no ETL "
                    "session is running; call raydp_tpu.init_etl first"
                )
            return session.from_pandas(df)
        raise TypeError(
            f"expected raydp_tpu.etl.DataFrame or pandas.DataFrame, "
            f"got {type(df).__name__}"
        )

    def fit_on_etl(
        self,
        train_df,
        evaluate_df=None,
        fs_directory: Optional[str] = None,
        stop_etl_after_conversion: bool = False,
        max_retries: int = 0,
    ) -> Any:
        """Convert ETL DataFrames and fit. Both exchange paths of the
        reference (torch/estimator.py:342-359) are supported by EVERY
        estimator: ``fs_directory`` stages through parquet on a shared
        filesystem; otherwise blocks go through the object store, with
        ``stop_etl_after_conversion`` transferring ownership so the data
        outlives the ETL engine."""
        import os

        from raydp_tpu.exchange.dataset import (
            dataframe_to_dataset,
            dataset_from_parquet,
        )

        train_df = self._check_and_convert(train_df)
        if evaluate_df is not None:
            evaluate_df = self._check_and_convert(evaluate_df)

        if fs_directory is not None:
            train_dir = os.path.join(fs_directory, "train")
            train_df.write_parquet(train_dir)
            train_ds = dataset_from_parquet(train_dir)
            evaluate_ds = None
            if evaluate_df is not None:
                eval_dir = os.path.join(fs_directory, "eval")
                evaluate_df.write_parquet(eval_dir)
                evaluate_ds = dataset_from_parquet(eval_dir)
        else:
            train_ds = dataframe_to_dataset(
                train_df, _use_owner=stop_etl_after_conversion
            )
            evaluate_ds = None
            if evaluate_df is not None:
                evaluate_ds = dataframe_to_dataset(
                    evaluate_df, _use_owner=stop_etl_after_conversion
                )
        if stop_etl_after_conversion:
            from raydp_tpu.etl.session import stop_etl

            stop_etl(cleanup_data=False, del_obj_holder=False)
        return self.fit(train_ds, evaluate_ds, max_retries=max_retries)

    # migration-friendly alias for users of the reference API
    def fit_on_spark(self, *args, **kwargs):
        return self.fit_on_etl(*args, **kwargs)
