"""Estimator interfaces.

Parity: reference ``EstimatorInterface`` (estimator.py:23-43) and
``SparkEstimatorInterface._check_and_convert`` (spark/interfaces.py:27-39) —
the sklearn-style fit/get_model contract plus the ETL-DataFrame adapter mixin.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Optional


class EstimatorInterface(ABC):
    """sklearn-style distributed estimator: fit on Datasets, export a model."""

    @abstractmethod
    def fit(self, train_ds, evaluate_ds=None, max_retries: int = 0) -> Any:
        ...

    @abstractmethod
    def get_model(self) -> Any:
        ...


class EtlEstimatorInterface(ABC):
    """Adds fit_on_etl: accepts ETL DataFrames directly and converts through
    the exchange layer (reference fit_on_spark, torch/estimator.py:332-363)."""

    def _check_and_convert(self, df):
        from raydp_tpu.etl.dataframe import DataFrame

        if not isinstance(df, DataFrame):
            raise TypeError(
                f"expected raydp_tpu.etl.DataFrame, got {type(df).__name__}"
            )
        return df

    @abstractmethod
    def fit_on_etl(
        self,
        train_df,
        evaluate_df=None,
        fs_directory: Optional[str] = None,
        stop_etl_after_conversion: bool = False,
        max_retries: int = 0,
    ) -> Any:
        ...

    # migration-friendly alias for users of the reference API
    def fit_on_spark(self, *args, **kwargs):
        return self.fit_on_etl(*args, **kwargs)
