"""JaxEstimator — the flagship distributed trainer.

Re-architects the reference's ``TorchEstimator`` (torch/estimator.py:73-377)
for TPU: instead of Ray Train spawning DDP worker processes whose gradients
all-reduce over Gloo/NCCL (train_func at :166-250, prepare_model at :232), the
train step is ONE jitted function over a ``jax.sharding.Mesh`` — the batch is
sharded over the ``data`` axis, params are replicated (or sharded by explicit
rules for model-parallel layers), and XLA compiles the gradient all-reduce
into the step itself, riding ICI on a pod. Structure kept from the reference:
model/optimizer/loss given as instances *or* creator fns (:88-136), metrics by
name, per-epoch eval, checkpointing, ``fit_on_etl`` with the
parquet-vs-object-store path and ``stop_etl_after_conversion`` (:332-363),
``max_retries`` (FailureConfig parity at :313).
"""

from __future__ import annotations

import os
import tempfile
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from raydp_tpu.estimator.base import EstimatorInterface, EtlEstimatorInterface
from raydp_tpu.estimator.metrics import Metrics

# ---------------------------------------------------------------------------
# loss registry
# ---------------------------------------------------------------------------


def _loss_mse(pred, target):
    import jax.numpy as jnp

    return jnp.mean((pred.reshape(target.shape) - target) ** 2)


def _loss_mae(pred, target):
    import jax.numpy as jnp

    return jnp.mean(jnp.abs(pred.reshape(target.shape) - target))


def _loss_bce(pred, target):
    import jax.numpy as jnp
    import optax

    return jnp.mean(
        optax.sigmoid_binary_cross_entropy(pred.reshape(target.shape), target)
    )


def _loss_softmax_ce(pred, target):
    import jax.numpy as jnp
    import optax

    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(pred, target.astype("int32"))
    )


_LOSSES = {
    "mse": _loss_mse,
    "mae": _loss_mae,
    "bce": _loss_bce,
    "binary_cross_entropy": _loss_bce,
    "softmax_cross_entropy": _loss_softmax_ce,
    "cross_entropy": _loss_softmax_ce,
}


def enable_persistent_compilation_cache() -> None:
    """Point XLA's persistent compilation cache at a local dir so cold-compile
    costs (tens of seconds on TPU) are paid once per program, not per run.
    No-op if the user already configured a cache dir."""
    import jax

    try:
        if jax.config.jax_compilation_cache_dir is None:
            cache_dir = os.path.join(
                os.path.expanduser("~"), ".cache", "raydp_tpu", "xla"
            )
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception:  # raydp-lint: disable=swallowed-exceptions (compile cache is an optimization; never fail training over it)
        pass  # cache is an optimization; never fail training over it


def partial_jit(donate_argnums=()):
    """jax.jit with optional buffer donation (params/opt_state are dead after
    each step, so donating them halves their device-memory footprint).

    Routed through :func:`raydp_tpu.sanitize.checked_jit`: with
    ``RAYDP_TPU_SANITIZE=donation`` every dispatch first verifies the donated
    args don't alias externally-owned host memory (the PR 2 streaming-NaN
    use-after-free class); disabled (the default) this IS a plain jax.jit."""
    from raydp_tpu.sanitize import checked_jit

    def wrap(fn):
        return checked_jit(fn, donate_argnums=donate_argnums)

    return wrap


# feature containers (one array, or a tuple of arrays in the mixed-dtype
# path): the ONE shared convention lives in exchange/features.py
from raydp_tpu.exchange.features import f0 as _f0
from raydp_tpu.exchange.features import f_nbytes as _f_nbytes
from raydp_tpu.exchange.features import f_stack as _f_stack
from raydp_tpu.exchange.features import fmap as _fmap


def _put_stacked_batch(mesh, arr, shard_direct=True):
    """Upload recipe shared by the scan and stream runners — delegates to
    the exchange layer's one implementation of the placement rules
    (Partitioner.shard_stacked via jax_io)."""
    from raydp_tpu.exchange.jax_io import device_put_stacked

    return _fmap(
        lambda a: device_put_stacked(a, mesh, shard_direct=shard_direct), arr
    )


def _compile_span(what):
    """The one timer for AOT compile sites: the span's duration feeds
    ``compile_seconds_`` and (when tracing ships) the trace timeline — no
    parallel perf_counter bookkeeping."""
    from raydp_tpu import obs

    return obs.span("estimator.compile", what=str(what))


def _scan_over_batches(step_impl, params, opt_state, xb, yb):
    """Run the train step over stacked batches [S, B, ...] with ONE
    ``lax.scan`` — the shared core of the whole-epoch and segment-stream
    runners (one dispatch per call instead of one per step)."""
    import jax.numpy as jnp
    from jax import lax

    def body(carry, xy):
        p, o, ls = carry
        p, o, ls = step_impl(p, o, ls, xy[0], xy[1])
        return (p, o, ls), None

    (params, opt_state, loss_sum), _ = lax.scan(
        body, (params, opt_state, jnp.zeros((), jnp.float32)), (xb, yb)
    )
    return params, opt_state, loss_sum


class _HostArrays:
    """Staged (features, labels) host arrays; epochs reshuffle indices only.
    ``features`` is one array or a tuple of arrays (mixed-dtype path)."""

    def __init__(self, features, labels: Optional[np.ndarray]):
        self.features = features
        self.labels = labels

    def iter(self, batch_size: int, shuffle: bool, seed: Optional[int]):
        # the segment_rows == batch_size case of iter_segments — one
        # implementation, so the coalesced and per-batch streaming paths
        # can never drift apart on shuffling or the drop-last bound
        return self.iter_segments(batch_size, batch_size, shuffle, seed)

    def iter_segments(
        self, batch_size: int, segment_rows: int, shuffle: bool,
        seed: Optional[int],
    ):
        """Segment-sized slices for the coalesced stream producer: every
        yield covers whole batches only (``stop`` bounds at the last full
        batch, so the final segment is a smaller multiple of batch_size —
        identical rows to the per-batch iterator)."""
        n = len(_f0(self.features))
        order = np.arange(n)
        if shuffle:
            np.random.default_rng(seed).shuffle(order)
        stop = (n // batch_size) * batch_size  # static shapes: drop last
        for start in range(0, stop, segment_rows):
            idx = order[start : min(start + segment_rows, stop)]
            yield _fmap(lambda a: a[idx], self.features), (
                self.labels[idx] if self.labels is not None else None
            )


@dataclass
class JaxModel:
    """What ``get_model`` returns: module + trained params, callable on host
    or device arrays."""

    module: Any
    params: Any

    def __call__(self, x):
        return self.module.apply(self.params, x)


class JaxEstimator(EstimatorInterface, EtlEstimatorInterface):
    def __init__(
        self,
        model: Any = None,  # flax Module instance or zero-arg creator fn
        optimizer: Any = "adam",  # optax tx, creator fn, or name
        loss: Union[str, Callable] = "mse",
        metrics: Optional[Sequence[str]] = None,
        feature_columns: Optional[Sequence[str]] = None,
        categorical_columns: Optional[Sequence[str]] = None,
        label_column: Optional[str] = None,
        batch_size: int = 64,
        num_epochs: int = 10,
        learning_rate: float = 1e-3,
        mesh: Any = None,  # jax Mesh; default 1-D data mesh over all devices
        shuffle: bool = True,
        seed: int = 0,
        checkpoint_dir: Optional[str] = None,
        feature_dtype=np.float32,
        categorical_dtype=np.int32,
        label_dtype=np.float32,
        param_sharding_rules: Optional[Callable] = None,
        donate_state: bool = True,
        profile_dir: Optional[str] = None,
        resume_from_epoch: Optional[int] = None,
        streaming: Union[bool, str] = False,
        stream_cache_memory_limit: Optional[int] = None,
        sync_every_steps: int = 32,
        scan_epochs: Optional[bool] = None,
        scan_memory_limit: int = 1 << 30,
        save_every_steps: Optional[int] = None,
        stream_scan_steps: int = 32,
        stream_prefetch_segments: int = 3,
        keep_checkpoints: Optional[int] = None,
        shard_direct: bool = True,
        stream_wire_quant: Union[bool, str] = False,
        stream_executor_decode: bool = True,
    ):
        self._model_arg = model
        self._optimizer_arg = optimizer
        self._loss_arg = loss
        self._metrics = Metrics(metrics)
        self.feature_columns = list(feature_columns or [])
        # mixed-dtype staging (DLRM/Criteo): the named subset of
        # feature_columns is staged as a SECOND array in categorical_dtype
        # (int32 by default) and the model receives (dense, ids) — integer
        # ids stay exact at ANY vocab size (a single float32 matrix silently
        # collapses ids beyond 2^24; float64 staging doubles the H2D bytes).
        # Reference examples/pytorch_dlrm.ipynb feeds int64 ids through
        # torch tensors; this is the jax-native equivalent.
        self.categorical_columns = list(categorical_columns or [])
        unknown = [
            c for c in self.categorical_columns if c not in (feature_columns or [])
        ]
        if unknown:
            raise ValueError(
                f"categorical_columns {unknown} not in feature_columns"
            )
        if self.categorical_columns and not np.issubdtype(
            np.dtype(categorical_dtype), np.integer
        ):
            # a float categorical_dtype would silently reintroduce the id-
            # collision class this path exists to eliminate (floats are exact
            # only to 2^mantissa)
            raise ValueError(
                f"categorical_dtype must be an integer dtype, got "
                f"{np.dtype(categorical_dtype)}"
            )
        self.categorical_dtype = categorical_dtype
        self.label_column = label_column
        self.batch_size = batch_size
        self.num_epochs = num_epochs
        self.learning_rate = learning_rate
        self._mesh_arg = mesh
        self.shuffle = shuffle
        self.seed = seed
        self.checkpoint_dir = checkpoint_dir
        self.feature_dtype = feature_dtype
        self.label_dtype = label_dtype
        self.param_sharding_rules = param_sharding_rules
        self.donate_state = donate_state
        self.profile_dir = profile_dir
        self.resume_from_epoch = resume_from_epoch
        # streaming=True: epochs iterate the dataset block-by-block with
        # double-buffered staging — host memory O(block) instead of
        # O(dataset); shuffle becomes block-order + within-block.
        # streaming="hybrid": epoch 1 streams AND pins its uploaded segments
        # in device memory; later epochs scan them from HBM (no host IO, no
        # re-upload) while they fit the device budget — host stays
        # O(segment), device becomes O(dataset). Segment order reshuffles
        # per epoch; batch composition is epoch-1's (the block-scoped
        # streaming shuffle trade, one step further). Cached epochs write no
        # MID-epoch step checkpoints (their replay order differs from a
        # streamed epoch's, so a step-resume could not replay the right
        # tail); epoch-boundary checkpoints are unaffected.
        self.streaming = streaming
        # device-byte budget for hybrid pinning. None = scan_memory_limit,
        # additionally capped at half the device's reported HBM when the
        # backend exposes memory_stats (params/activations need the rest);
        # overflow falls back to pure streaming mid-epoch.
        self.stream_cache_memory_limit = stream_cache_memory_limit
        # cap the async dispatch queue: drain every N steps. Unbounded
        # queues of distinct-input steps permanently degrade dispatch ~25x
        # on tunneled PJRT transports (measured: >~100 undrained steps);
        # on local hardware the periodic drain costs one pipeline bubble
        # per N steps (<1%). 0 disables.
        self.sync_every_steps = sync_every_steps
        # scan_epochs: drive a whole epoch with ONE jitted lax.scan instead
        # of a Python dispatch per step — removes the per-step framework
        # overhead entirely (the 13-16% train-only gap vs a raw jit loop).
        # None = auto: on when the staged arrays fit scan_memory_limit.
        # Single-device additionally keeps the dataset resident on device and
        # gathers shuffled batches there, so H2D happens once per fit.
        self.scan_epochs = scan_epochs
        self.scan_memory_limit = scan_memory_limit
        # step-cadence checkpointing: every K completed steps write
        # epoch_N_step_K (a long epoch on a pod must not lose everything
        # since the last epoch boundary). resume_from_epoch accepts either
        # an int (epoch complete) or an (epoch, step) tuple to continue
        # mid-epoch — batch order is deterministic per (seed, epoch), so the
        # resumed run replays exactly the tail steps.
        self.save_every_steps = save_every_steps
        # streaming (and oversized-staging) fits run SEGMENTS of this many
        # batches through one jitted lax.scan each: O(segment) host memory
        # with ~N× fewer dispatches than a per-step loop. 0 restores the
        # per-step path.
        self.stream_scan_steps = stream_scan_steps
        # streaming upload pipeline depth: the producer keeps up to this
        # many segments staged-and-uploading ahead of the consumer's scan
        # (device_put is async, so uploads overlap compute). Deeper absorbs
        # bursty block IO at the cost of that many extra device-resident
        # segments; 1 = classic double buffering.
        self.stream_prefetch_segments = max(1, int(stream_prefetch_segments))
        # retention: keep only the newest N epoch checkpoints (each is a full
        # params+opt_state copy). None keeps everything.
        self.keep_checkpoints = keep_checkpoints
        # shard-direct feeds (Partitioner.shard_inputs): batches reach the
        # mesh via make_array_from_process_local_data — each process uploads
        # only its shard. False restores the legacy driver-staged sharded
        # device_put (the A/B arm; byte-identical results, but multi-host it
        # stages the global batch per process).
        self.shard_direct = bool(shard_direct)
        # mixed-dtype ON-WIRE staging for streaming fits: float feature
        # leaves are staged int8 with per-row scales and widened back to
        # float INSIDE the jitted segment scan (~3.2x fewer H2D bytes per
        # dense leaf; integer id leaves always ride exact int32 — any vocab
        # size). Lossy by construction (int8 rounding), so OFF by default;
        # accepts True (alias for "int8") or "int8".
        self.stream_wire_quant = stream_wire_quant
        # streaming segment decode (Arrow block -> numpy) runs in the etl
        # EXECUTOR processes when the dataset's session is still alive —
        # the consumer thread only sequences uploads. Falls back to
        # driver-side decode when the session is stopped or an executor
        # call fails.
        self.stream_executor_decode = bool(stream_executor_decode)

        self._module = None
        self._params = None
        self._history: List[Dict[str, float]] = []
        self.compile_seconds_: float = 0.0

    # ------------------------------------------------------------------
    # component resolution (instance-or-creator, reference :88-136)
    # ------------------------------------------------------------------

    def _resolve_model(self):
        model = self._model_arg
        if model is None:
            raise ValueError("JaxEstimator needs a model (flax Module or creator fn)")
        if callable(model) and not hasattr(model, "apply"):
            model = model()
        return model

    def _resolve_optimizer(self):
        import optax

        opt = self._optimizer_arg
        if isinstance(opt, str):
            factory = getattr(optax, opt, None)
            if factory is None:
                raise ValueError(f"unknown optax optimizer {opt!r}")
            return factory(self.learning_rate)
        if callable(opt) and not hasattr(opt, "update"):
            return opt()
        return opt

    def _resolve_loss(self):
        if callable(self._loss_arg):
            return self._loss_arg
        if self._loss_arg in _LOSSES:
            return _LOSSES[self._loss_arg]
        raise ValueError(
            f"unknown loss {self._loss_arg!r}; available: {sorted(_LOSSES)}"
        )

    def _resolve_mesh(self):
        import jax
        from jax.sharding import Mesh

        if self._mesh_arg is not None:
            return self._mesh_arg
        devices = jax.devices()
        return Mesh(np.array(devices), ("data",))

    def _feature_groups(self):
        """None, or the ``[(dense_cols, feature_dtype), (cat_cols,
        categorical_dtype)]`` staging spec when categorical columns are
        configured — features then flow as a (dense, ids) tuple end to end.
        An all-categorical model drops the empty dense group (features are
        then a 1-tuple of the id matrix)."""
        if not self.categorical_columns:
            return None
        cat_set = set(self.categorical_columns)
        dense = [c for c in self.feature_columns if c not in cat_set]
        groups = []
        if dense:
            groups.append((dense, self.feature_dtype))
        groups.append((list(self.categorical_columns), self.categorical_dtype))
        return groups

    def _effective_batch(self, mesh) -> int:
        """Round the batch up to a multiple of the data axis so every device
        gets an equal static shard."""
        data_size = int(mesh.shape.get("data", 1))
        batch = self.batch_size
        if batch % max(1, data_size):
            batch = ((batch // data_size) + 1) * data_size
        return batch

    def _stage_host(self, ds) -> "_HostArrays":
        """Arrow → host numpy exactly once; epochs reshuffle indices only.
        Re-fitting the same Dataset (retries, hyperparameter sweeps, repeated
        benchmarking) reuses the staged arrays — keyed by dataset identity +
        column selection, invalidated when the block list changes. The cache
        holds up to 4 dataset-sized host copies for the estimator's lifetime
        (LRU-evicted); fitting several large datasets through one estimator
        retains multiples of dataset memory — call ``clear_staging_cache()``
        to release them.

        Multi-process (one process per TPU host): each process stages only its
        equal-share shard — ``device_put_batch`` then assembles the global
        batch from per-process rows (make_array_from_process_local_data)."""
        import jax

        key = (
            getattr(ds, "uuid", None),
            tuple(getattr(b, "object_id", id(b)) for b in getattr(ds, "blocks", [])),
            tuple(self.feature_columns),
            tuple(self.categorical_columns),
            self.label_column,
            np.dtype(self.feature_dtype).str,
            np.dtype(self.categorical_dtype).str,
            np.dtype(self.label_dtype).str,
            jax.process_index(),
            jax.process_count(),
        )
        cache = getattr(self, "_stage_cache", None)
        if cache is None:
            cache = self._stage_cache = {}
        if key in cache:
            # LRU: re-insert on hit so eviction drops the least-recently-used
            # entry, not the oldest-staged one
            staged = cache.pop(key)
            cache[key] = staged
            return staged
        groups = self._feature_groups()
        if groups is not None:
            features, labels = ds.to_numpy_grouped(
                groups, self.label_column, label_dtype=self.label_dtype
            )
        else:
            features, labels = ds.to_numpy(
                self.feature_columns,
                self.label_column,
                feature_dtype=self.feature_dtype,
                label_dtype=self.label_dtype,
            )
        p = jax.process_count()
        if p > 1:
            # slice this process's equal share in memory (no object-store
            # round trip); wraparound oversampling keeps counts identical so
            # every process runs the same step count
            n = len(_f0(features))
            per = -(-n // p)
            idx = (np.arange(per) + jax.process_index() * per) % n
            features = _fmap(lambda a: a[idx], features)
            labels = labels[idx] if labels is not None else None
        staged = _HostArrays(features, labels)
        while len(cache) >= 4:  # bounded: train + eval + headroom
            cache.pop(next(iter(cache)))
        cache[key] = staged
        return staged

    def clear_staging_cache(self) -> None:
        """Release the staged host arrays AND the device-resident copy of
        the most recent training set (both can be dataset-sized; they
        otherwise live as long as the estimator)."""
        self._stage_cache = {}
        self._device_stage = None
        self._eval_device_stage = None

    # ------------------------------------------------------------------
    # fit
    # ------------------------------------------------------------------

    def fit(self, train_ds, evaluate_ds=None, max_retries: int = 0) -> List[Dict[str, float]]:
        import jax

        attempts = 0
        # Snapshot the pre-existing newest checkpoint so retries only resume
        # from epochs saved by THIS run — a stale checkpoint from a prior fit
        # in a reused dir must not short-circuit training. Multi-process runs
        # are excluded: only process 0 writes, so a node-local dir would make
        # ranks disagree on the resume epoch and desync the collectives (the
        # SPMD watchdog coordinates multi-host resume instead).
        retry_resume = (
            max_retries > 0
            and self.checkpoint_dir
            and jax.process_count() == 1
        )

        def _key(es):
            return (es[0], float("inf") if es[1] is None else es[1])

        baseline = latest_checkpoint(self.checkpoint_dir) if retry_resume else None
        saved_resume = self.resume_from_epoch
        from raydp_tpu import obs

        try:
            while True:
                try:
                    # the collector forces REAL spans on this thread even
                    # with trace shipping off: epoch/compile wall times in
                    # history and compile_seconds_ are read from the same
                    # span records the trace timeline shows — the obs layer
                    # is the single timing source, not a parallel one. The
                    # records are kept as ``last_fit_records_`` so
                    # ``explain_last_fit()`` can attribute the fit's wall
                    # time the way queries get ``explain_last_query()``.
                    with obs.collect() as fit_records:
                        try:
                            with obs.span(
                                "estimator.fit",
                                epochs=self.num_epochs,
                                streaming=str(self.streaming),
                                attempt=attempts,
                            ):
                                return self._fit_once(train_ds, evaluate_ds)
                        finally:
                            self.last_fit_records_ = fit_records
                except Exception:
                    attempts += 1
                    if attempts > max_retries:
                        raise
                    if retry_resume:
                        latest = latest_checkpoint(self.checkpoint_dir)
                        if latest is not None and (
                            baseline is None or _key(latest) > _key(baseline)
                        ):
                            epoch, step = latest
                            if step is not None:
                                # mid-epoch checkpoint: replay only the tail
                                self.resume_from_epoch = (epoch, step)
                            else:
                                # never resume past the end: a crash after
                                # the final epoch's checkpoint would start at
                                # num_epochs and return an empty history —
                                # re-run at least the final epoch instead
                                resume = min(epoch, self.num_epochs - 2)
                                if resume >= 0:
                                    self.resume_from_epoch = resume
                    time.sleep(1.0)
        finally:
            # retries must not leak resume state into a later fit() call
            self.resume_from_epoch = saved_resume

    def _latest_checkpoint_epoch(self) -> Optional[int]:
        return latest_checkpoint_epoch(self.checkpoint_dir)

    def _fit_once(self, train_ds, evaluate_ds) -> List[Dict[str, float]]:
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import NamedSharding, PartitionSpec

        from raydp_tpu.exchange.jax_io import PrefetchingDeviceIterator

        mesh = self._resolve_mesh()
        batch_size = self._effective_batch(mesh)

        module = self._resolve_model()
        tx = self._resolve_optimizer()
        loss_fn = self._resolve_loss()

        if self.streaming:
            # O(block) memory: no up-front staging; each epoch streams blocks
            # with double buffering (multi-process shards are block-span
            # plans — nothing is materialized here). The init sample comes
            # straight from the first non-empty block: shapes are all that
            # matter, and this avoids spinning up a producer thread.
            from raydp_tpu.exchange.dataset import (
                _table_to_numpy,
                _table_to_numpy_grouped,
            )

            if train_ds.count() == 0:
                raise ValueError("streaming fit on an empty dataset")
            train_source = train_ds
            eval_source = evaluate_ds
            first = next(i for i, c in enumerate(train_ds.counts) if c > 0)
            groups = self._feature_groups()
            if groups is not None:
                feats, _ = _table_to_numpy_grouped(
                    train_ds.get_block(first), groups,
                    self.label_column, self.label_dtype,
                )
            else:
                feats, _ = _table_to_numpy(
                    train_ds.get_block(first), self.feature_columns,
                    self.label_column, self.feature_dtype, self.label_dtype,
                )
            sample_np = _fmap(
                lambda a: np.resize(a, (batch_size,) + a.shape[1:]), feats
            )
        else:
            # Arrow → host numpy exactly once; epochs only reshuffle indices
            train_source = self._stage_host(train_ds)
            eval_source = (
                self._stage_host(evaluate_ds) if evaluate_ds is not None else None
            )
            sample_np = _fmap(lambda a: a[:batch_size], train_source.features)

        from raydp_tpu import obs
        from raydp_tpu.obs import costmodel as _costmodel
        from raydp_tpu.obs import profiler as _profiler

        # compute observatory (obs/profiler.py): the always-on step-phase
        # recorder (estimator.step.* histograms; RAYDP_TPU_STEP_PROFILER=0
        # swaps in a shared no-op), an armed on-demand capture window
        # (session.profile_fit), and the cost model's peak for the live
        # MFU gauge — all resolved once per fit
        recorder = self._step_recorder = _profiler.step_recorder()
        fit_capture = self._fit_capture = _profiler.armed_capture()
        self._flops_per_step = None
        self._fit_step_wall = 0.0
        try:
            self._peak_info = _costmodel.device_peak_flops()
        except Exception:  # raydp-lint: disable=swallowed-exceptions (an exotic backend without device_kind must not fail the fit)
            self._peak_info = {"kind": None, "peak": None,
                               "peak_source": "unknown"}

        enable_persistent_compilation_cache()
        rng = jax.random.PRNGKey(self.seed)
        with obs.span("estimator.compile", what="init") as init_span:
            # one jitted init: flax init run eagerly compiles dozens of tiny
            # ops, which costs ~0.5s EACH on cold TPU backends (~30s total)
            sample = _fmap(jnp.asarray, sample_np)
            params, opt_state = jax.jit(
                lambda r, s: (lambda p: (p, tx.init(p)))(module.init(r, s))
            )(rng, sample)
            jax.block_until_ready(params)
        init_compile = init_span.duration
        from raydp_tpu.exchange.jax_io import _mesh_device_count, _mesh_single_device

        if self.param_sharding_rules is not None:
            params = jax.device_put(params, self.param_sharding_rules(mesh, params))
            opt_state = tx.init(params)  # re-derive on the sharded params
        elif _mesh_device_count(mesh) > 1:
            params = jax.device_put(
                params,
                jax.tree.map(lambda _: NamedSharding(mesh, PartitionSpec()), params),
            )
            opt_state = tx.init(params)
        else:
            # single-device mesh: committed arrays (even SingleDeviceSharding)
            # force a slow executor path on some PJRT plugins, so commit only
            # when the mesh pins a NON-default device; jitted-init opt_state
            # is kept as-is
            device = _mesh_single_device(mesh)
            if device != jax.devices()[0]:
                params = jax.device_put(params, device)
                opt_state = jax.device_put(opt_state, device)

        donate = (0, 1, 2) if self.donate_state else ()

        # loss accumulates ON DEVICE: a host float(loss) per step would force
        # a sync and serialize the H2D/compute pipeline (measured 6× slowdown)
        def step_impl(params, opt_state, loss_sum, x, y):
            def compute(p):
                return loss_fn(module.apply(p, x), y)

            loss, grads = jax.value_and_grad(compute)(params)
            updates, opt_state2 = tx.update(grads, opt_state, params)
            return (
                optax.apply_updates(params, updates),
                opt_state2,
                loss_sum + loss,
            )

        train_step = partial_jit(donate_argnums=donate)(step_impl)

        eval_fns = self._make_eval_step(module, loss_fn)

        start_epoch = 0
        start_step = 0
        if self.resume_from_epoch is not None:
            # step-level resume (beyond the reference's model-only
            # checkpointing, SURVEY.md §5): reload params at the checkpointed
            # (epoch[, step]) and continue — the recovery path when a slice
            # fails. An (epoch, step) tuple resumes MID-epoch, replaying only
            # the tail steps (batch order is deterministic per seed+epoch).
            if not self.checkpoint_dir:
                raise ValueError("resume_from_epoch requires checkpoint_dir")
            resume = self.resume_from_epoch
            resume_epoch, resume_step = (
                resume if isinstance(resume, tuple) else (resume, None)
            )
            # host-OWNED template copies: on CPU, device_get can return
            # numpy views aliasing the live jax buffers, and orbax may hand
            # template leaves back by identity — the restore result must
            # never share memory with the runtime (the sanitizer registers
            # restored leaves as externally owned, and a span over live
            # jax memory would misfire when the allocator recycles it)
            template = {
                "params": jax.tree.map(np.array, jax.device_get(params)),
                "opt_state": jax.tree.map(np.array, jax.device_get(opt_state)),
            }
            restored = self._restore_checkpoint(
                resume_epoch, template, step=resume_step
            )
            # Stage restored leaves as JAX-OWNED buffers before any
            # dispatch: on CPU, device_put/jnp.asarray zero-copy suitably-
            # aligned numpy arrays, so the staged state would alias host
            # memory owned by orbax's restore machinery — and with
            # donate_state the first train step hands exactly those aliased
            # buffers to XLA for reuse. Observed on 2-core CPU boxes as
            # garbage/denormal params after a mid-epoch resume (the seed-era
            # "streaming NaN" flake); a host-side numpy copy does NOT fix it
            # (the copy is zero-copy-staged and donated all the same). The
            # on-device ``jnp.array(…, copy=True)`` allocates a fresh
            # runtime-owned buffer in the TARGET sharding — donation-safe,
            # dtype-preserving, and large sharded models never materialize
            # an unsharded leaf on one device (device_put shards during
            # transfer).
            def _owned(x, like_sharding):
                return jnp.array(jax.device_put(x, like_sharding), copy=True)

            params = jax.tree.map(
                lambda x, p: _owned(x, p.sharding), restored["params"], params
            )
            # exact resume incl. optimizer moments; leave uncommitted — jit
            # places leaves to match params (the live opt_state's scalar
            # leaves are uncommitted too)
            opt_state = jax.tree.map(
                lambda x: jnp.array(x, copy=True), restored["opt_state"]
            )
            if resume_step is None:
                start_epoch = resume_epoch + 1
            else:
                start_epoch = resume_epoch
                start_step = resume_step

        import contextlib

        profile_ctx = (
            jax.profiler.trace(self.profile_dir)
            if self.profile_dir
            else contextlib.nullcontext()
        )

        self._history = []
        self.compile_seconds_ = init_compile
        first_step_done = False
        # the ExitStack is entered FIRST so its callbacks run LAST: the
        # streaming pipeline's close (registered below once the runner
        # exists) must stop/drain/join the whole-fit producer on ANY exit —
        # a consumer exception abandoning a producer parked on the full
        # queue would leak the thread and pin its in-flight device segments
        # (the leaks sanitizer audits exactly this at shutdown)
        with contextlib.ExitStack() as _fit_stack, profile_ctx, mesh:
            run_scan_epoch, run_fullfit = self._build_scan_runner(
                train_source, batch_size, mesh, step_impl, donate
            )
            # scan_epochs=False is an explicit opt-out of lax.scan-driven
            # training for staged data — it must restore the true per-step
            # loop, not silently reroute into segment scans (streaming fits
            # opt out with stream_scan_steps=0 instead)
            run_stream_segments = (
                self._build_stream_runner(mesh, step_impl, donate, batch_size)
                if run_scan_epoch is None
                and self.stream_scan_steps > 0
                and self.label_column is not None
                and (self.streaming or self.scan_epochs is not False)
                else None
            )
            save_steps = self.save_every_steps if self.checkpoint_dir else None

            def save_mid_epoch(params_, opt_state_, epoch_, step_):
                self._save_checkpoint(params_, epoch_, opt_state_, step=step_)

            if run_stream_segments is not None:
                # whole-fit streaming pipeline: ONE producer covers every
                # epoch (epoch N+1's first segment decodes while epoch N's
                # tail trains); each epoch's host iterator is built lazily
                # by this plan when the producer reaches it
                seg_steps = self._stream_segment_steps

                def _stream_epoch_plan(epoch_):
                    epoch_seed_ = None if not self.shuffle else self.seed + epoch_
                    epoch_start_ = start_step if epoch_ == start_epoch else 0
                    coalesced_ = epoch_start_ % seg_steps == 0
                    base_iter_ = self._epoch_batches(
                        train_source, batch_size, epoch_seed_,
                        segment_rows=(
                            seg_steps * batch_size if coalesced_ else None
                        ),
                    )
                    host_iter_ = base_iter_
                    if epoch_start_:
                        import itertools

                        skip = (
                            epoch_start_ // seg_steps
                            if coalesced_
                            else epoch_start_
                        )
                        host_iter_ = itertools.islice(host_iter_, skip, None)
                    # base_iter_ rides along unwrapped: the executor-decode
                    # evidence flag lives on the block-stream iterator, which
                    # an islice wrapper (mid-epoch resume) would hide
                    return host_iter_, coalesced_, base_iter_

                run_stream_segments.start(
                    _stream_epoch_plan, range(start_epoch, self.num_epochs)
                )
                _fit_stack.callback(run_stream_segments.close)

            # whole-fit fast path: when nothing needs params BETWEEN epochs
            # (no checkpointing, no per-epoch eval, no resume), the entire
            # fit is one dispatch — an outer epoch-scan over stacked
            # permutations. One dispatch + one history fetch per FIT.
            fullfit_done = False
            if (
                run_fullfit is not None
                and not self.checkpoint_dir
                and eval_source is None
                and start_epoch == 0
                and start_step == 0
                and self.num_epochs > 0
                # an armed capture window needs per-epoch dispatches: the
                # whole-fit single dispatch has no step boundary for the
                # budget to stop at, and its trace would show one opaque
                # launch instead of steady-state steps
                and fit_capture is None
            ):
                seeds = [
                    None if not self.shuffle else self.seed + e
                    for e in range(self.num_epochs)
                ]
                t_fit = time.perf_counter()
                compile_before = self.compile_seconds_
                full = run_fullfit(params, opt_state, seeds)
                if full is not None:
                    params, opt_state, losses, steps_per_epoch = full
                    # the loss/time placeholders stay None: the dispatch is
                    # ASYNC — real training time is only known at the final
                    # losses fetch (the fence), which fills both in; and
                    # slicing losses[e] here would dispatch E unused gathers
                    self._history = [
                        {
                            "epoch": e,
                            "train_loss": (None, steps_per_epoch),
                            "epoch_seconds": None,
                        }
                        for e in range(self.num_epochs)
                    ]
                    fullfit_done = True

            for epoch in (
                () if fullfit_done else range(start_epoch, self.num_epochs)
            ):
                epoch_seed = None if not self.shuffle else self.seed + epoch
                epoch_start_step = start_step if epoch == start_epoch else 0
                phase_before = recorder.totals()
                steps_before = getattr(recorder, "steps", 0)
                # the epoch span IS the epoch timer: history's epoch_seconds
                # is read from the same record the trace timeline shows
                with obs.span(
                    "estimator.epoch", epoch=epoch,
                    resumed_at=epoch_start_step,
                ) as epoch_span:
                    if run_scan_epoch is not None:
                        params, opt_state, loss_sum, steps = run_scan_epoch(
                            params, opt_state, epoch_seed,
                            start_step=epoch_start_step,
                            save_cb=(
                                (lambda p, o, s, _e=epoch: save_mid_epoch(p, o, _e, s))
                                if save_steps
                                else None
                            ),
                        )
                    elif run_stream_segments is not None:
                        # consume this epoch's segments off the whole-fit
                        # pipeline (the producer, started before the loop,
                        # builds each epoch's host iterator itself —
                        # coalesced whole-segment slices except on a
                        # mid-segment resume)
                        params, opt_state, loss_sum, steps = run_stream_segments(
                            params, opt_state, epoch, epoch_start_step,
                            save_cb=(
                                (lambda p, o, s, _e=epoch: save_mid_epoch(p, o, _e, s))
                                if save_steps
                                else None
                            ),
                        )
                    else:
                        host_iter = self._epoch_batches(
                            train_source, batch_size, epoch_seed
                        )
                        if epoch_start_step:
                            # deterministic order per (seed, epoch): dropping
                            # the first K batches replays exactly the un-run
                            # tail
                            import itertools

                            host_iter = itertools.islice(
                                host_iter, epoch_start_step, None
                            )
                        train_iter = PrefetchingDeviceIterator(
                            host_iter, mesh, shard_direct=self.shard_direct
                        )
                        loss_sum = jnp.zeros((), jnp.float32)
                        steps = epoch_start_step
                        pending_save = None
                        # explicit next() so the step profiler can split
                        # each iteration into its phases: ingest (host
                        # slice + queue wait), h2d (device_put dispatch,
                        # read from the iterator's own split), compute
                        # (the train_step call), sync (the bounded fence)
                        profiled = recorder.enabled
                        t_loop0 = time.perf_counter()
                        while True:
                            h2d0 = train_iter.h2d_s
                            t_iter = time.perf_counter()
                            try:
                                x, y = next(train_iter)
                            except StopIteration:  # raydp-lint: disable=swallowed-exceptions (explicit next(): epoch end is the loop's normal exit)
                                break
                            if profiled:
                                h2d_d = train_iter.h2d_s - h2d0
                                recorder.note("h2d", h2d_d)
                                recorder.note(
                                    "ingest",
                                    (time.perf_counter() - t_iter) - h2d_d,
                                )
                            if pending_save is not None:
                                # DEFERRED one step: a save that would
                                # coincide with the epoch's final step is
                                # dropped (the epoch-complete epoch_N
                                # supersedes it) — so a step checkpoint
                                # always has tail steps to replay
                                save_mid_epoch(params, opt_state, epoch, pending_save)
                                pending_save = None
                            t_c = time.perf_counter()
                            if not first_step_done:
                                # the first call compiles (cold TPU compiles
                                # take tens of seconds); record it so callers
                                # can report steady-state throughput
                                # separately
                                if fit_capture is not None:
                                    fit_capture.begin_steps()
                                with obs.span(
                                    "estimator.compile", what="first_step"
                                ) as cspan:
                                    params, opt_state, loss_sum = train_step(
                                        params, opt_state, loss_sum, x, y
                                    )
                                    jax.block_until_ready(loss_sum)
                                self.compile_seconds_ += cspan.duration
                                first_step_done = True
                                # XLA's own flops count for the live MFU
                                # gauge: one extra lower()+compile(), served
                                # from the (persistent) compilation cache
                                # the first dispatch just filled
                                self._flops_per_step = (
                                    _costmodel.step_flops_from_jitted(
                                        train_step, params, opt_state,
                                        loss_sum, x, y,
                                    )
                                )
                                # the compile step is NOT a steady-state
                                # step: keep it (and the flops lookup) out
                                # of both the compute histogram and the
                                # step-wall clock the phases are gated
                                # against — compile_seconds_ carries it
                                t_loop0 += time.perf_counter() - t_c
                            else:
                                params, opt_state, loss_sum = train_step(
                                    params, opt_state, loss_sum, x, y
                                )
                                if profiled:
                                    recorder.note(
                                        "compute", time.perf_counter() - t_c
                                    )
                            if fit_capture is not None:
                                fit_capture.note_step()
                            steps += 1
                            if save_steps and steps % save_steps == 0:
                                pending_save = steps
                            if (
                                self.sync_every_steps
                                and steps % self.sync_every_steps == 0
                            ):
                                # bounded pipeline bubble; see __init__
                                t_s = time.perf_counter()
                                jax.block_until_ready(loss_sum)
                                if profiled:
                                    recorder.note(
                                        "sync", time.perf_counter() - t_s
                                    )
                        self._fit_step_wall += time.perf_counter() - t_loop0
                        steps -= epoch_start_step
                    epoch_span.set(steps=steps)
                    phase_delta = {
                        k: v - phase_before.get(k, 0.0)
                        for k, v in recorder.totals().items()
                    }
                    if phase_delta:
                        # the analyzer's phase-split args: explain_last_fit
                        # attributes this epoch's interval into ingest/h2d/
                        # compute/sync exactly like query stage spans split
                        # by read_s/compute_s/emit_s
                        epoch_span.set(
                            ingest_s=round(phase_delta.get("ingest", 0.0), 6),
                            h2d_s=round(phase_delta.get("h2d", 0.0), 6),
                            compute_s=round(phase_delta.get("compute", 0.0), 6),
                            sync_s=round(phase_delta.get("sync", 0.0), 6),
                        )
                obs.metrics.counter("estimator.steps").inc(steps)
                # the RECORDER's step delta, not the loop's: the compile
                # step is excluded from both numerator and denominator —
                # the live gauge and fit_stats_ must describe one ratio
                self._update_live_mfu(
                    phase_delta, getattr(recorder, "steps", 0) - steps_before
                )
                if steps == 0 and epoch_start_step > 0:
                    # resumed exactly at this epoch's end (a stale final-step
                    # checkpoint from an older layout): nothing trained —
                    # recording a zero-loss epoch would poison downstream
                    # metrics; just finalize the epoch and move on
                    if self.checkpoint_dir:
                        self._save_checkpoint(params, epoch, opt_state)
                        self._gc_step_checkpoints(epoch)
                    continue
                # defer the host read: float(loss_sum) here would sync the
                # pipeline every epoch; store the device scalar instead
                record: Dict[str, Any] = {
                    "epoch": epoch,
                    "train_loss": (loss_sum, steps),
                    "epoch_seconds": epoch_span.duration,
                }
                if eval_source is not None:
                    with obs.span("estimator.eval", epoch=epoch):
                        record.update(
                            self._evaluate_host(
                                eval_source, params, eval_fns, mesh, batch_size
                            )
                        )
                self._history.append(record)
                # EVERY process calls save: orbax's Checkpointer runs
                # cross-process barriers and writes from the primary host
                # only — a lone process-0 save deadlocks on those barriers
                if self.checkpoint_dir:
                    self._save_checkpoint(params, epoch, opt_state)
                    self._gc_step_checkpoints(epoch)

        if self._history:
            # ONE host fetch for every epoch's loss: a per-record float()
            # would pay a full transport round trip PER EPOCH (~70ms each on
            # tunneled PJRT — measured 0.56s of pure RTT for an 8-epoch fit
            # whose compute takes 0.14s). The fullfit path already returns
            # the losses as one [E] array — fetch it directly (no stack
            # dispatch, one RTT instead of two).
            if fullfit_done:
                stacked = np.asarray(losses)  # the fence: training is done
                per_epoch_s = (
                    time.perf_counter()
                    - t_fit
                    - (self.compile_seconds_ - compile_before)
                ) / max(self.num_epochs, 1)
                for rec in self._history:
                    rec["epoch_seconds"] = per_epoch_s
                # the whole fit was ONE dispatch: its fenced wall time is
                # the only honest compute figure (per-step phases don't
                # exist inside a single XLA program)
                recorder.note(
                    "compute", per_epoch_s * self.num_epochs,
                    steps=self.num_epochs * steps_per_epoch,
                )
            else:
                stacked = np.asarray(
                    jnp.stack([rec["train_loss"][0] for rec in self._history])
                )
            for rec, val in zip(self._history, stacked):
                _, steps = rec["train_loss"]
                rec["train_loss"] = float(val) / max(steps, 1)
        self._module = module
        # keep params ON DEVICE: a device_get here drags the full parameter
        # set (MBs of embedding tables for DLRM) through the host transfer
        # path every fit; apply/evaluate are faster with device params, and
        # checkpointing does its own device_get
        self._params = params
        obs.metrics.counter("estimator.fits").inc()
        obs.metrics.gauge("estimator.compile_s").set(self.compile_seconds_)
        # fit_stats_: the compute observatory's fit-level summary — phase
        # totals, FLOPs accounting, and the MFU the live gauge reported
        phase_totals = recorder.totals()
        device_s = phase_totals.get("compute", 0.0) + phase_totals.get(
            "sync", 0.0
        )
        flops_step = getattr(self, "_flops_per_step", None)
        steps_total = getattr(recorder, "steps", 0)
        mfps = (
            flops_step * steps_total / device_s
            if flops_step and steps_total and device_s > 0
            else None
        )
        mfu_val = _costmodel.mfu(mfps, self._peak_info.get("peak"))
        self.fit_stats_ = {
            "steps": steps_total,
            "step_phase_seconds": {
                k: round(v, 6) for k, v in phase_totals.items()
            },
            "step_wall_s": (
                round(self._fit_step_wall, 6) if self._fit_step_wall else None
            ),
            "flops_per_step": flops_step,
            "model_flops_per_sec": mfps,
            "mfu": mfu_val,
            "peak_flops": self._peak_info.get("peak"),
            "device_kind": self._peak_info.get("kind"),
            "peak_source": self._peak_info.get("peak_source"),
            "profiler": "on" if recorder.enabled else "off",
        }
        if mfps:
            obs.metrics.gauge("estimator.model_flops_per_sec").set(mfps)
        if mfu_val is not None:
            obs.metrics.gauge("estimator.mfu").set(mfu_val)
        obs.flush_throttled(1.0)
        return self._history

    # per-fit streaming pipeline stats (VERDICT r4 weak #4: the streaming
    # gap claim needs evidence): bytes staged for upload, time the producer
    # spent blocked on a full queue (consumer-bound), time the consumer
    # spent blocked on an empty queue (transfer/producer-bound).
    stream_stats_: Dict[str, Any]

    # per-fit compute-observatory summary (obs/profiler.py + obs/costmodel):
    # step-phase totals, FLOPs accounting, live MFU — docs/estimators.md
    fit_stats_: Dict[str, Any]

    def explain_last_fit(self, top_k: int = 5) -> dict:
        """Critical-path wall-time attribution of the last ``fit()`` (the
        PR 14 analyzer over the fit's span tree: epoch leaves phase-split
        into ingest/h2d/compute/sync by the step profiler's args). The
        report's ``text`` field is human-readable."""
        records = getattr(self, "last_fit_records_", None)
        if not records:
            raise RuntimeError("no fit has run on this estimator yet")
        from raydp_tpu.obs.profiler import explain_fit

        return explain_fit(records, top_k=top_k)

    def _update_live_mfu(self, phase_delta: Dict[str, float],
                         steps: int) -> None:
        """Refresh the ``estimator.mfu`` / ``estimator.model_flops_per_sec``
        gauges from one epoch's measured device time (compute + sync phase
        seconds) — called at every epoch boundary so a scrape MID-fit shows
        the live number. Async backends undercount the denominator between
        fences; ``sync_every_steps`` bounds the error (docs/observability.md
        "Compute observatory")."""
        flops_step = getattr(self, "_flops_per_step", None)
        if not flops_step or not steps:
            return
        device_s = phase_delta.get("compute", 0.0) + phase_delta.get(
            "sync", 0.0
        )
        if device_s <= 0.0:
            return
        from raydp_tpu import obs
        from raydp_tpu.obs import costmodel

        mfps = flops_step * steps / device_s
        obs.metrics.gauge("estimator.model_flops_per_sec").set(mfps)
        mfu_val = costmodel.mfu(mfps, self._peak_info.get("peak"))
        if mfu_val is not None:
            obs.metrics.gauge("estimator.mfu").set(mfu_val)
        obs.flush_throttled(1.0)

    def _note_step_flops_abstract(self, step_fn: Any, params: Any,
                                  opt_state: Any, batch_x: Any,
                                  batch_y: Any) -> None:
        """Record the fit's FLOPs-per-step for the segment-scanned paths by
        lowering the SINGLE-step function at the batch's shapes (XLA's
        cost analysis counts a scan body once regardless of trip count, so
        the compiled segment executable can't be divided by steps).
        ``batch_x``/``batch_y`` are one batch's shape donors — arrays or
        ShapeDtypeStructs. First call wins; failures leave flops unknown."""
        if getattr(self, "_flops_per_step", None):
            return
        try:
            import jax
            import jax.numpy as jnp

            from raydp_tpu.obs import costmodel

            def sds(a):
                return jax.ShapeDtypeStruct(a.shape, a.dtype)

            self._flops_per_step = costmodel.step_flops_abstract(
                step_fn,
                jax.tree.map(sds, params),
                jax.tree.map(sds, opt_state),
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.tree.map(sds, batch_x),
                jax.tree.map(sds, batch_y),
            )
        except Exception:  # raydp-lint: disable=swallowed-exceptions (flops stay unknown; the fit is unaffected)
            self._flops_per_step = None

    def _build_stream_runner(self, mesh, step_impl, donate, batch_size=None):
        """Segment-scanned streaming (ROADMAP r3 #3): assemble
        ``stream_scan_steps`` host batches into a [S, B, ...] super-batch,
        upload once, drive it with ONE jitted ``lax.scan`` — O(segment) host
        memory with ~S× fewer dispatches than the per-step loop. Used for
        streaming fits and for staged data too large for the whole-epoch
        scan. With save_every_steps, the segment length snaps to the save
        cadence so step checkpoints land exactly on their steps; saves are
        deferred until the next segment begins, so a checkpoint always has
        tail steps to replay.

        Segments are pipelined ``stream_prefetch_segments`` deep through
        N-way rotating upload streams: ONE producer thread lives for the
        WHOLE fit (not per epoch), reads blocks, shapes segments, and
        starts their H2D uploads while earlier segments' scans are still
        executing — and at an epoch boundary it rolls straight into the
        next epoch's first segment, so the consumer never waits out a
        decode ramp between epochs (the per-epoch producer restart used to
        cost ~a first-segment decode of consumer idle EVERY epoch). On the
        (default) coalesced path the host iterator yields whole segments
        as one contiguous slice and the producer just reshapes it
        ([S·B, ...] → [S, B, ...], zero-copy) — the per-batch Python loop
        and the np.stack copy per segment exist only on the legacy
        batch-granular path (mid-segment resume).

        With ``stream_wire_quant`` float feature leaves travel the wire
        int8 + per-row scales and are widened back INSIDE the jitted scan
        (see jax_io's wire-staging helpers); integer id leaves always ride
        exact int32."""
        import queue
        import threading

        import jax
        import jax.numpy as jnp

        seg = int(self.stream_scan_steps)
        save_every = (
            int(self.save_every_steps)
            if self.checkpoint_dir and self.save_every_steps
            else None
        )
        if save_every is not None:
            # the segment length must DIVIDE the save cadence so checkpoints
            # land exactly on multiples of save_every_steps (save=100,
            # seg=32 → seg becomes 25: boundaries 25/50/75/100). Largest
            # divisor ≤ stream_scan_steps; seg=1 always qualifies.
            seg = min(seg, save_every)
            while save_every % seg:
                seg -= 1
        # callers build the epoch's host iterator at segment granularity
        # from this (the coalesced fast path)
        self._stream_segment_steps = seg
        compiled: Dict[int, Any] = {}
        # compute observatory: the per-fit step-phase recorder + armed
        # capture window (set by _fit_once before this builder runs);
        # segment paths note phases at segment granularity with steps=S
        recorder = self._step_recorder
        fit_capture = self._fit_capture

        from raydp_tpu.exchange.jax_io import (
            SegmentUploader,
            iter_prefetch,
            partitioner_for,
            quantize_rows,
            widen_wire,
        )

        # -- mixed-dtype wire spec (static for the whole fit) --------------
        # which feature leaves quantize: float leaves only; integer id
        # leaves already ride the wire exact (int32 feature_groups)
        groups = self._feature_groups()
        leaf_dtypes = (
            [np.dtype(self.feature_dtype)]
            if groups is None
            else [np.dtype(dt) for _, dt in groups]
        )
        wire_dtype = None
        if self.stream_wire_quant:
            wire_dtype = (
                "int8"
                if self.stream_wire_quant is True
                else str(self.stream_wire_quant)
            )
            if wire_dtype != "int8":
                raise ValueError(
                    f"stream_wire_quant={self.stream_wire_quant!r}: only "
                    "'int8' (or True) is supported"
                )
        wire_flags = [
            wire_dtype is not None and np.issubdtype(dt, np.floating)
            for dt in leaf_dtypes
        ]
        wire_on = any(wire_flags)
        single_leaf = groups is None

        def _wire_encode(hx):
            """Host half of the wire format: each float leaf becomes
            (int8 q, float32 per-row scale); the wire container is a FLAT
            tuple ``(leaves..., scales...)`` of plain arrays, so the
            uploader's staging/ping-pong machinery needs no special cases."""
            leaves = list(hx) if isinstance(hx, tuple) else [hx]
            wire, scales = [], []
            for leaf, flag in zip(leaves, wire_flags):
                if flag:
                    q, s = quantize_rows(np.asarray(leaf))
                    wire.append(q)
                    scales.append(s)
                else:
                    wire.append(np.asarray(leaf))
            return tuple(wire + scales)

        def _wire_widen(x):
            """Device half, traced INSIDE the jitted scan body: widen each
            quantized leaf back to its model dtype (bit-identical to the
            host dequant) and rebuild the model's feature container."""
            nf = len(wire_flags)
            scales = list(x[nf:])
            out, si = [], 0
            for leaf, flag, dt in zip(x[:nf], wire_flags, leaf_dtypes):
                if flag:
                    out.append(widen_wire(leaf, scales[si], dt))
                    si += 1
                else:
                    out.append(leaf)
            return out[0] if single_leaf else tuple(out)

        if wire_on:
            # widen PER STEP inside the scan: only one batch's float copy
            # ever materializes, and XLA fuses the dequant into the step
            def _wire_step(p, o, ls, x, y):
                return step_impl(p, o, ls, _wire_widen(x), y)

            scan_step = _wire_step
        else:
            scan_step = step_impl

        def epoch_body(params, opt_state, xb, yb):
            return _scan_over_batches(scan_step, params, opt_state, xb, yb)

        # the streaming runner's feeds AND its step jit ride the same
        # partitioner: shard_stacked places the segments, partition_step
        # (== partial_jit's checked_jit chain) jits the scan body
        partitioner = partitioner_for(mesh, "data", self.shard_direct)
        jitted = partitioner.partition_step(
            epoch_body, donate_argnums=(0, 1) if donate else ()
        )

        # N-way ping-pong upload staging: ``stream_prefetch_segments``
        # rotating host buffers feed the async transfers (each recycled only
        # after the transfer that used it completed); automatically degrades
        # to per-segment allocation on CPU jax, where device_put zero-copy
        # ALIASES host numpy buffers and reuse would corrupt an in-flight
        # segment
        uploader = SegmentUploader(
            mesh,
            depth=max(2, self.stream_prefetch_segments),
            partitioner=partitioner,
        )
        stats = self.stream_stats_ = {
            "bytes_uploaded": 0,
            "producer_idle_s": 0.0,
            "consumer_idle_s": 0.0,
            "segments": 0,
            "cached_epochs": 0,
            "staging_buffer_reuse": uploader.reuse_host_buffers,
            "staging_copies": 0,
            "upload_streams": uploader.upload_streams,
            "shard_direct": self.shard_direct,
            "wire_dtype": wire_dtype if wire_on else None,
            "wire_bytes_saved": 0,
            "executor_decode": False,
        }

        def _produce_fit(epoch_plan, epochs, out_q: "queue.Queue", stop):
            """THE producer thread — one per fit, streaming every epoch
            back to back: shape each segment, START its device upload, and
            at an epoch boundary roll straight into the next epoch's blocks
            (the next epoch's first segment decodes while the current
            epoch's tail is still training — the per-epoch producer restart
            used to hand the consumer a decode-ramp stall every epoch).
            Items are a (dx, dy) segment, ``None`` for epoch end, or an
            exception to re-raise consumer-side (epochs are consumed
            strictly in production order, so no per-item epoch tag is
            needed). The bounded queue (depth = stream_prefetch_segments)
            applies backpressure so only that many segments' worth of
            host/device memory is in flight; ``stop`` lets a failing
            consumer unblock a producer parked on the full queue.
            ``epoch_plan(epoch)`` returns that epoch's ``(host_iter,
            coalesced, block_iter)`` (block_iter = the unwrapped
            block-stream iterator carrying the executor-decode evidence
            flag) — coalesced
            items are whole-segment slices (reshaped zero-copy), per-batch
            items are stacked (mid-segment resume only). The host iterator
            is itself prefetched one segment deep (``iter_prefetch``), so
            segment k+1 DECODES while segment k's async device_put is in
            flight — block IO, wire encode, staging copy, and transfer all
            overlap."""

            def _emit(item) -> bool:
                from raydp_tpu import obs

                t0 = time.perf_counter()
                while not stop.is_set():
                    try:
                        out_q.put(item, timeout=0.2)
                        # time parked on a FULL queue = consumer-bound
                        idle = time.perf_counter() - t0
                        stats["producer_idle_s"] += idle
                        obs.metrics.counter(
                            "estimator.stream.producer_idle_s"
                        ).inc(idle)
                        return True
                    except queue.Full:  # raydp-lint: disable=swallowed-exceptions (bounded-queue backpressure loop)
                        continue
                return False

            def _upload(hx, hy):
                from raydp_tpu import obs

                logical = _f_nbytes(hx) + hy.nbytes
                if wire_on:
                    hx = _wire_encode(hx)
                nbytes = _f_nbytes(hx) + hy.nbytes
                stats["bytes_uploaded"] += nbytes
                stats["wire_bytes_saved"] += max(0, logical - nbytes)
                stats["segments"] += 1
                obs.metrics.counter("estimator.stream.bytes_uploaded").inc(
                    nbytes
                )
                obs.metrics.counter("estimator.stream.segments").inc()
                t_up = time.perf_counter()
                dx, dy = uploader.upload(hx, hy)
                # producer-side H2D dispatch wall, normalized per-step by
                # the segment's REAL batch count (hy is stacked [S, B] on
                # both producer paths — the tail segment is shorter than
                # seg); a lost cross-thread race costs one sample, like
                # every other lock-free instrument
                recorder.note(
                    "h2d", time.perf_counter() - t_up,
                    steps=max(1, hy.shape[0]),
                )
                stats["staging_copies"] = uploader.staging_copies
                return dx, dy

            try:
                for epoch_ in epochs:
                    if stop.is_set():
                        return
                    if (
                        hybrid_gate is not None
                        and not hybrid_gate.is_set()
                        and epoch_ != epochs[0]
                    ):
                        # hybrid, decision pending: epoch 1 usually seals the
                        # device cache and every later epoch replays it —
                        # running ahead would upload segments only to throw
                        # them away. Hold at the boundary until the consumer
                        # rules (sealed → exit; overflow/resume → stream on).
                        while not hybrid_gate.wait(0.2):
                            if stop.is_set():
                                return
                    if cache is not None and cache_ready["ok"]:
                        # hybrid: everything from here on replays the device
                        # cache — no more host IO to do
                        return
                    host_iter, coalesced, block_iter = epoch_plan(epoch_)
                    if coalesced:
                        from raydp_tpu.exchange.jax_io import coalesce_segment

                        for x, y in iter_prefetch(host_iter, depth=1):
                            hx, hy, k = coalesce_segment(
                                x, np.asarray(y), batch_size
                            )
                            if k == 0:
                                continue  # sub-batch tail: drop_last semantics
                            if not _emit(_upload(hx, hy)):
                                return
                    else:
                        xs: List[Any] = []
                        ys: List[np.ndarray] = []
                        for x, y in iter_prefetch(host_iter, depth=1):
                            xs.append(_fmap(np.asarray, x))
                            ys.append(np.asarray(y))
                            if len(xs) == seg:
                                if not _emit(
                                    _upload(_f_stack(xs), np.stack(ys))
                                ):
                                    return
                                xs, ys = [], []
                        if xs:
                            if not _emit(
                                _upload(_f_stack(xs), np.stack(ys))
                            ):
                                return
                    stats["executor_decode"] = stats["executor_decode"] or bool(
                        getattr(block_iter, "executor_decode_active", False)
                    )
                    if not _emit(None):
                        return
            except BaseException as exc:  # noqa: BLE001 - surface in consumer
                _emit(exc)

        # hybrid mode: the first FULLY-streamed epoch's uploaded segments are
        # pinned here and later epochs scan them straight from device memory
        # (order reshuffled per epoch). None = disabled or overflowed the
        # device budget mid-stream.
        hybrid = self.streaming == "hybrid"
        cache: Optional[List[Any]] = [] if hybrid else None
        cache_ready = {"ok": False}
        # set once the consumer has ruled on the device cache (sealed OR
        # abandoned): until then the producer holds at epoch boundaries —
        # see _produce_fit
        hybrid_gate = threading.Event() if hybrid else None

        def _device_cache_budget() -> int:
            budget = self.stream_cache_memory_limit or self.scan_memory_limit
            try:
                stats_ = jax.devices()[0].memory_stats() or {}
                limit = int(stats_.get("bytes_limit", 0))
                if limit > 0:
                    # leave at least half of HBM for params/activations —
                    # pinning must degrade to streaming, not to device OOM
                    budget = min(budget, limit // 2)
            except Exception:  # raydp-lint: disable=swallowed-exceptions (backend without memory stats: keep the config budget)
                pass  # backend without memory stats: keep the config budget
            return budget

        cache_budget = _device_cache_budget() if hybrid else 0

        # the whole-fit pipeline: one queue + one producer thread, started
        # once by _fit_once before the epoch loop and closed in its finally
        pipe: Dict[str, Any] = {"q": None, "stop": None, "thread": None}

        def start(epoch_plan, epochs):
            """Spawn the whole-fit producer (idempotent; one per fit)."""
            if pipe["thread"] is not None:
                return
            pipe["q"] = queue.Queue(maxsize=self.stream_prefetch_segments)
            pipe["stop"] = threading.Event()
            pipe["thread"] = threading.Thread(
                target=_produce_fit,
                args=(epoch_plan, list(epochs), pipe["q"], pipe["stop"]),
                daemon=True,
            )
            pipe["thread"].start()

        def close():
            """Stop + drain + join the producer. A failing (or cache-served)
            consumer must not abandon a producer parked on the full queue —
            it would pin ``stream_prefetch_segments`` device segments
            forever."""
            thread = pipe["thread"]
            if thread is None:
                return
            pipe["stop"].set()
            while True:
                try:
                    pipe["q"].get_nowait()
                except queue.Empty:  # raydp-lint: disable=swallowed-exceptions (queue drain at shutdown)
                    break
            thread.join(timeout=10)
            pipe["thread"] = None

        def run(params, opt_state, epoch, start_step, save_cb=None):
            nonlocal cache
            if cache is not None and not cache_ready["ok"] and start_step != 0:
                # a resumed (partial) epoch must not become the cache: later
                # epochs would silently replay only its tail
                cache = None
            if cache is not None and cache_ready["ok"] and start_step == 0:
                # hybrid steady state: replay the device cache. The producer
                # may have run ahead into this epoch before the cache sealed
                # — close it now so its prefetched segments don't sit pinned
                # behind a full queue for the rest of the fit
                close()
                return _run_cached(params, opt_state, epoch)
            if pipe["thread"] is None:
                raise RuntimeError(
                    "stream pipeline not started (run.start was not called)"
                )
            done = start_step
            loss_total = jnp.zeros((), jnp.float32)
            try:
                params, opt_state, loss_total, done = _consume(
                    params, opt_state, loss_total, done, epoch, save_cb
                )
                if cache is not None and start_step == 0:
                    cache_ready["ok"] = True  # one FULL epoch pinned
            finally:
                if hybrid_gate is not None:
                    # the cache ruling for this epoch is in (sealed,
                    # abandoned, or the fit is failing): unblock a producer
                    # holding at the boundary either way
                    hybrid_gate.set()
            return params, opt_state, loss_total, done - start_step

        run.start = start
        run.close = close

        def _run_cached(params, opt_state, epoch):
            """Hybrid later-epoch path: scan the pinned device segments —
            zero host IO, zero H2D. Segment order reshuffles per GLOBAL
            epoch (same seed+epoch convention as the streamed path). No
            mid-epoch step checkpoints: a step-resume streams its epoch
            fresh, whose batch order differs from the cached replay — only
            epoch-boundary checkpoints are replay-consistent here."""
            stats["cached_epochs"] += 1
            loss_total = None
            done = 0
            dispatches = 0
            order = np.arange(len(cache))
            if self.shuffle:
                np.random.default_rng((self.seed or 0) + epoch).shuffle(order)
            for oi in order:
                xb, yb = cache[int(oi)]
                length = _f0(xb).shape[0]
                if length not in compiled:
                    with _compile_span(length) as cspan:
                        compiled[length] = jitted.lower(
                            params, opt_state, xb, yb
                        ).compile()
                    self.compile_seconds_ += cspan.duration
                    self._note_step_flops_abstract(
                        scan_step, params, opt_state,
                        _fmap(
                            lambda a: jax.ShapeDtypeStruct(
                                a.shape[1:], a.dtype
                            ),
                            xb,
                        ),
                        jax.ShapeDtypeStruct(yb.shape[1:], yb.dtype),
                    )
                t_c = time.perf_counter()
                params, opt_state, loss_sum = compiled[length](
                    params, opt_state, xb, yb
                )
                recorder.note(
                    "compute", time.perf_counter() - t_c, steps=length
                )
                loss_total = (
                    loss_sum if loss_total is None else loss_total + loss_sum
                )
                done += length
                dispatches += 1
                if (
                    self.sync_every_steps
                    and dispatches % self.sync_every_steps == 0
                ):
                    # same queue-depth cap as _consume: multi-epoch cached
                    # fits must not enqueue unbounded async dispatches
                    t_s = time.perf_counter()
                    jax.block_until_ready(loss_total)
                    recorder.note("sync", time.perf_counter() - t_s)
            if loss_total is None:
                loss_total = jnp.zeros((), jnp.float32)
            return params, opt_state, loss_total, done

        def _consume(params, opt_state, loss_total, done, epoch, save_cb):
            nonlocal cache
            pending_save = None
            dispatches = 0
            cache_bytes = 0
            seg_q = pipe["q"]
            from raydp_tpu import obs

            while True:
                t0 = time.perf_counter()
                item = seg_q.get()
                # time parked on an EMPTY queue = transfer/producer-bound
                idle = time.perf_counter() - t0
                stats["consumer_idle_s"] += idle
                obs.metrics.counter("estimator.stream.consumer_idle_s").inc(
                    idle
                )
                if item is None:
                    break  # this epoch's end sentinel (strict production order)
                if isinstance(item, BaseException):
                    raise item
                xb, yb = item
                # per-step by the segment's REAL batch count (tail
                # segments are shorter than seg)
                recorder.note(
                    "ingest", idle, steps=max(1, _f0(xb).shape[0])
                )
                if cache is not None and not cache_ready["ok"]:
                    cache_bytes += _f_nbytes(xb) + yb.nbytes
                    if cache_bytes > cache_budget:
                        cache = None  # over the device budget: stay streaming
                    else:
                        cache.append((xb, yb))
                if pending_save is not None:
                    # more data follows the boundary: commit the deferred
                    # step checkpoint (a boundary at stream end is dropped —
                    # the epoch-complete save supersedes it)
                    if save_cb is not None:
                        save_cb(params, opt_state, pending_save)
                    pending_save = None
                length = _f0(xb).shape[0]
                if length not in compiled:
                    with _compile_span(length) as cspan:
                        compiled[length] = jitted.lower(
                            params, opt_state, xb, yb
                        ).compile()
                    self.compile_seconds_ += cspan.duration
                    self._note_step_flops_abstract(
                        scan_step, params, opt_state,
                        _fmap(
                            lambda a: jax.ShapeDtypeStruct(
                                a.shape[1:], a.dtype
                            ),
                            xb,
                        ),
                        jax.ShapeDtypeStruct(yb.shape[1:], yb.dtype),
                    )
                if fit_capture is not None:
                    fit_capture.begin_steps()
                t_c = time.perf_counter()
                params, opt_state, loss_sum = compiled[length](
                    params, opt_state, xb, yb
                )
                recorder.note(
                    "compute", time.perf_counter() - t_c, steps=length
                )
                if fit_capture is not None:
                    fit_capture.note_step(length)
                loss_total = loss_total + loss_sum
                done += length
                if save_every is not None and done % save_every == 0:
                    pending_save = done
                dispatches += 1
                if (
                    self.sync_every_steps
                    and dispatches % self.sync_every_steps == 0
                ):
                    # cap the async dispatch queue (the per-step loop's
                    # sync_every_steps, counted in DISPATCHES here —
                    # undrained queues degrade tunneled PJRT transports;
                    # see __init__)
                    t_s = time.perf_counter()
                    jax.block_until_ready(loss_total)
                    recorder.note("sync", time.perf_counter() - t_s)
            return params, opt_state, loss_total, done

        return run

    def _build_scan_runner(self, train_source, batch_size, mesh, step_impl, donate):
        """Whole-epoch training as ONE jitted ``lax.scan`` over the staged
        batches — removes the per-step Python dispatch that costs 13-16% vs a
        raw jit loop (VERDICT r2 item 2). Two variants:

        - single-device: the dataset lives ON DEVICE for the whole fit; each
          epoch ships only a permutation vector and gathers shuffled batches
          device-side (H2D of the data happens once per fit — decisive on
          tunneled PJRT transports where transfers are slow);
        - multi-device / multi-process: host-shuffles, reshapes to
          [steps, batch, F] and uploads once per epoch (same H2D volume as the
          per-step path, but a single dispatch), sharded P(None, "data", ...).

        Compilation is AOT (``lower().compile()``) so ``compile_seconds_``
        records the real compile cost rather than folding a whole epoch's
        compute into it. Returns ``(run_epoch, run_fullfit)`` — the second
        drives the WHOLE fit (all epochs) as one dispatch via an outer
        epoch-scan over stacked permutations, available on the
        device-resident path only (None otherwise); callers use it when no
        per-epoch side effect (checkpoint, eval) needs params between
        epochs. Returns (None, None) when the scan path doesn't apply
        (streaming, oversized staged arrays, or scan_epochs=False)."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec

        from raydp_tpu.exchange.jax_io import _mesh_device_count

        if self.streaming or not isinstance(train_source, _HostArrays):
            return None, None
        if self.scan_epochs is False:
            return None, None
        feats, labs = train_source.features, train_source.labels
        if labs is None or len(_f0(feats)) < batch_size:
            return None, None
        if self.scan_epochs is None:
            if _f_nbytes(feats) + labs.nbytes > self.scan_memory_limit:
                return None, None

        n = len(_f0(feats))
        steps_per_epoch = n // batch_size
        n_used = steps_per_epoch * batch_size
        device_resident = (
            jax.process_count() == 1 and _mesh_device_count(mesh) == 1
        )

        def epoch_body(params, opt_state, xb, yb):
            return _scan_over_batches(step_impl, params, opt_state, xb, yb)

        # segment cap: save_every_steps chunks the epoch into several scans
        # with a checkpoint after each (mid-epoch recovery); otherwise ONE
        # scan covers the whole epoch. Distinct segment lengths (the tail)
        # compile once each and are cached. Gated on checkpoint_dir exactly
        # like the save callback: save_every_steps without a checkpoint dir
        # must not pay segmentation overhead for zero checkpointing benefit.
        save_every = self.save_every_steps if self.checkpoint_dir else None
        seg_cap = min(save_every or steps_per_epoch, steps_per_epoch)
        compiled: Dict[int, Any] = {}
        # compute observatory (set by _fit_once before this builder runs):
        # scan dispatches note phases at segment granularity
        recorder = self._step_recorder
        fit_capture = self._fit_capture

        def _note_flops(params, opt_state):
            """Single-step flops donors at this fit's batch shapes (the
            scan executables can't be read directly — cost analysis counts
            a scan body once)."""
            self._note_step_flops_abstract(
                step_impl, params, opt_state,
                _fmap(
                    lambda a: jax.ShapeDtypeStruct(
                        (batch_size,) + a.shape[1:], np.dtype(a.dtype)
                    ),
                    feats,
                ),
                jax.ShapeDtypeStruct(
                    (batch_size,) + labs.shape[1:], np.dtype(labs.dtype)
                ),
            )

        def _order(seed):
            order = np.arange(n)
            if self.shuffle:
                np.random.default_rng(seed).shuffle(order)
            return order[:n_used].astype(np.int32)

        if device_resident:
            from raydp_tpu.exchange.jax_io import _mesh_single_device

            device = _mesh_single_device(mesh)
            cached = getattr(self, "_device_stage", None)
            if (
                cached is not None
                and cached[0] is train_source
                and cached[1] == device
            ):
                # repeated fits on the same staged data skip the H2D upload
                # (~160ms for 4MB over a tunneled transport, vs ~120ms of
                # actual compute at small configs). ONE slot on the
                # estimator — only the most recent dataset stays pinned in
                # HBM; released by clear_staging_cache() or the next dataset.
                xs_dev, ys_dev = cached[2], cached[3]
            else:
                if device != jax.devices()[0]:
                    xs_dev = jax.device_put(feats, device)  # pytree-ok
                    ys_dev = jax.device_put(labs, device)
                else:
                    # default device: stay uncommitted (committed arrays
                    # force a slow executor path on some PJRT plugins — see
                    # device_put_batch)
                    xs_dev = _fmap(jnp.asarray, feats)
                    ys_dev = jnp.asarray(labs)
                self._device_stage = (train_source, device, xs_dev, ys_dev)

            def make_gather(length):
                def seg_gather(params, opt_state, xs, ys, perm):
                    xb = _fmap(
                        lambda a: a[perm].reshape(
                            (length, batch_size) + a.shape[1:]
                        ),
                        xs,
                    )
                    yb = ys[perm].reshape((length, batch_size) + ys.shape[1:])
                    return epoch_body(params, opt_state, xb, yb)

                return partial_jit(
                    donate_argnums=(0, 1) if donate else ()
                )(seg_gather)

            def run_segment(params, opt_state, order, start, length):
                perm = jnp.asarray(
                    order[start * batch_size : (start + length) * batch_size]
                )
                if length not in compiled:
                    with _compile_span(length) as cspan:
                        compiled[length] = (
                            make_gather(length)
                            .lower(params, opt_state, xs_dev, ys_dev, perm)
                            .compile()
                        )
                    self.compile_seconds_ += cspan.duration
                    _note_flops(params, opt_state)
                if fit_capture is not None:
                    fit_capture.begin_steps()
                t_c = time.perf_counter()
                out = compiled[length](params, opt_state, xs_dev, ys_dev, perm)
                recorder.note(
                    "compute", time.perf_counter() - t_c, steps=length
                )
                if fit_capture is not None:
                    fit_capture.note_step(length)
                return out

        else:
            jitted = partial_jit(
                donate_argnums=(0, 1) if donate else ()
            )(epoch_body)

            def run_segment(params, opt_state, order, start, length):
                sel = order[start * batch_size : (start + length) * batch_size]
                t_h = time.perf_counter()
                xb = _put_stacked_batch(
                    mesh,
                    _fmap(
                        lambda a: a[sel].reshape(
                            (length, batch_size) + a.shape[1:]
                        ),
                        feats,
                    ),
                    shard_direct=self.shard_direct,
                )
                yb = _put_stacked_batch(
                    mesh,
                    labs[sel].reshape((length, batch_size) + labs.shape[1:]),
                    shard_direct=self.shard_direct,
                )
                recorder.note(
                    "h2d", time.perf_counter() - t_h, steps=length
                )
                if length not in compiled:
                    with _compile_span(length) as cspan:
                        compiled[length] = jitted.lower(
                            params, opt_state, xb, yb
                        ).compile()
                    self.compile_seconds_ += cspan.duration
                    _note_flops(params, opt_state)
                if fit_capture is not None:
                    fit_capture.begin_steps()
                t_c = time.perf_counter()
                out = compiled[length](params, opt_state, xb, yb)
                recorder.note(
                    "compute", time.perf_counter() - t_c, steps=length
                )
                if fit_capture is not None:
                    fit_capture.note_step(length)
                return out

        def run_epoch(params, opt_state, seed, start_step=0, save_cb=None):
            order = _order(seed)
            # the common one-segment epoch must not pay an extra scalar-add
            # dispatch per epoch (measured 1.5ms/dispatch on tunneled PJRT —
            # 30 epochs cost 4% of the whole DLRM fit)
            loss_total = None
            done = start_step
            while done < steps_per_epoch:
                length = min(seg_cap, steps_per_epoch - done)
                params, opt_state, loss_sum = run_segment(
                    params, opt_state, order, done, length
                )
                loss_total = (
                    loss_sum if loss_total is None else loss_total + loss_sum
                )
                done += length
                # the epoch-complete checkpoint is the outer loop's epoch_N
                if save_cb is not None and done < steps_per_epoch:
                    save_cb(params, opt_state, done)
            if loss_total is None:
                loss_total = jnp.zeros((), jnp.float32)
            return params, opt_state, loss_total, steps_per_epoch - start_step

        run_fullfit = None
        # Mixed-dtype (embedding-gather) workloads run FASTER as per-epoch
        # dispatches than as one whole-fit dispatch: on v5e at the DLRM
        # tracked config the nested epoch-scan measured 1.7-2.1M sps and a
        # flattened single scan 2.0-2.2M, vs 2.8M for per-epoch dispatch
        # with whole-epoch pre-gather — the outer scan defeats XLA's gather
        # fusion. Dense models (MLP) keep the fullfit win (r4: 1.26x pure).
        if device_resident and not isinstance(feats, tuple):

            def fullfit_body(params, opt_state, xs, ys, perms):
                # outer scan over epochs of the inner per-step scan: ONE
                # dispatch trains the whole fit; per-epoch loss sums come
                # back as one [E] array. The pure-JAX ceiling dispatches
                # once per epoch — this path beats it by construction.
                def one_epoch(carry, perm):
                    p, o = carry
                    xb = _fmap(
                        lambda a: a[perm].reshape(
                            (steps_per_epoch, batch_size) + a.shape[1:]
                        ),
                        xs,
                    )
                    yb = ys[perm].reshape(
                        (steps_per_epoch, batch_size) + ys.shape[1:]
                    )
                    p, o, loss_sum = epoch_body(p, o, xb, yb)
                    return (p, o), loss_sum

                (params, opt_state), losses = jax.lax.scan(
                    one_epoch, (params, opt_state), perms
                )
                return params, opt_state, losses

            def run_fullfit(params, opt_state, seeds):
                if len(seeds) * n_used * 4 > self.scan_memory_limit:
                    return None  # permutation stack would not fit; use epochs
                perms = jnp.asarray(np.stack([_order(s) for s in seeds]))
                key = ("fullfit", len(seeds))
                if key not in compiled:
                    with _compile_span("fullfit") as cspan:
                        compiled[key] = (
                            partial_jit(
                                donate_argnums=(0, 1) if donate else (),
                            )(fullfit_body)
                            .lower(params, opt_state, xs_dev, ys_dev, perms)
                            .compile()
                        )
                    self.compile_seconds_ += cspan.duration
                    _note_flops(params, opt_state)
                params, opt_state, losses = compiled[key](
                    params, opt_state, xs_dev, ys_dev, perms
                )
                return params, opt_state, losses, steps_per_epoch

        return run_epoch, run_fullfit

    def _epoch_batches(self, source, batch_size, seed, shuffle=None,
                       segment_rows=None):
        """One epoch of host batches from either a staged ``_HostArrays`` or
        a ``Dataset`` (streamed block-by-block, O(block) memory). Multi-
        process streaming shards by block-span plan — equal rows per process
        (the divide_blocks invariant) with nothing materialized.

        ``segment_rows`` (the stream runner's coalesced path): yield
        SEGMENT-sized slices (``stream_scan_steps × batch_size`` rows each)
        instead of per-batch slices — every item is a whole number of full
        batches except a possibly sub-batch final tail, which the consumer
        trims (drop_last at batch granularity, exactly the per-batch
        behavior)."""
        import jax

        if shuffle is None:
            shuffle = self.shuffle
        if isinstance(source, _HostArrays):
            if segment_rows:
                return source.iter_segments(
                    batch_size, segment_rows, shuffle, seed
                )
            return source.iter(batch_size, shuffle, seed)
        from raydp_tpu.exchange.dataset import streaming_shard_plan

        plan = None
        p = jax.process_count()
        if p > 1:
            plan = streaming_shard_plan(source.counts, p, jax.process_index())
        return source.iter_batches(
            segment_rows or batch_size, self.feature_columns,
            self.label_column,
            shuffle=shuffle, seed=seed,
            # segment granularity keeps the tail (the consumer trims it to
            # full batches); batch granularity drops partials as before
            drop_last=not segment_rows,
            feature_dtype=self.feature_dtype, label_dtype=self.label_dtype,
            streaming=True, block_plan=plan,
            feature_groups=self._feature_groups(),
            executor_decode=self.stream_executor_decode,
        )

    def _make_eval_step(self, module, loss_fn):
        """(per-batch step, whole-set scan) pair. The scan drives one epoch
        of evaluation as ONE dispatch — metrics state is already a carry —
        instead of a per-batch Python loop (the exact dispatch pattern the
        train path eliminated; VERDICT r3 weak #6). The per-batch step
        remains for streaming sources, multi-device meshes, and the tail
        batch the static-shape scan can't cover."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        metrics = self._metrics

        # ROW-weighted loss accumulation (matches the Torch estimator's
        # reporting): a short tail batch must not count as much as a full
        # one, or one odd row could contribute half of eval_loss
        @jax.jit
        def eval_step(params, mstate, loss_sum, count, x, y):
            pred = module.apply(params, x)
            mstate = metrics.update(mstate, pred, y)
            rows = float(_f0(x).shape[0])
            return mstate, loss_sum + loss_fn(pred, y) * rows, count + rows

        @jax.jit
        def eval_scan(params, mstate, xb, yb):
            rows = float(_f0(xb).shape[1])

            def body(carry, xy):
                ms, ls, c = carry
                pred = module.apply(params, xy[0])
                ms = metrics.update(ms, pred, xy[1])
                return (ms, ls + loss_fn(pred, xy[1]) * rows, c + rows), None

            init = (mstate, jnp.zeros(()), jnp.zeros(()))
            (ms, ls, c), _ = lax.scan(body, init, (xb, yb))
            return ms, ls, c

        return eval_step, eval_scan

    def _evaluate_host(
        self, source, params, eval_fns, mesh, batch_size
    ) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        from raydp_tpu.exchange.jax_io import (
            PrefetchingDeviceIterator,
            _mesh_device_count,
        )

        eval_step, eval_scan = eval_fns
        mstate = self._metrics.init_state()
        loss_sum = jnp.zeros(())
        count = jnp.zeros(())

        scannable = (
            isinstance(source, _HostArrays)
            and source.labels is not None
            and self.scan_epochs is not False
            and jax.process_count() == 1
            and _mesh_device_count(mesh) == 1
            and (
                self.scan_epochs is True
                or _f_nbytes(source.features) + source.labels.nbytes
                <= self.scan_memory_limit
            )
        )
        if scannable:
            from raydp_tpu.exchange.jax_io import _mesh_single_device

            feats, labs = source.features, source.labels
            n = len(_f0(feats))
            steps = n // batch_size
            if steps:
                device = _mesh_single_device(mesh)
                cached = getattr(self, "_eval_device_stage", None)
                if (
                    cached is not None
                    and cached[0] is source
                    and cached[1] == batch_size  # reshape depends on it
                    and cached[2] == device  # arrays committed to the OLD
                    # device must not be reused after a mesh change (mirrors
                    # the train-side _device_stage check)
                ):
                    xb, yb = cached[3], cached[4]
                else:
                    xb = _fmap(
                        lambda a: a[: steps * batch_size].reshape(
                            (steps, batch_size) + a.shape[1:]
                        ),
                        feats,
                    )
                    yb = labs[: steps * batch_size].reshape(
                        (steps, batch_size) + labs.shape[1:]
                    )
                    if device != jax.devices()[0]:
                        xb = jax.device_put(xb, device)  # pytree-ok
                        yb = jax.device_put(yb, device)
                    else:
                        xb = _fmap(jnp.asarray, xb)
                        yb = jnp.asarray(yb)
                    # one slot, like the train-set device cache: per-epoch
                    # eval must not re-upload the eval set every epoch
                    self._eval_device_stage = (source, batch_size, device, xb, yb)
                mstate, loss_sum, count = eval_scan(params, mstate, xb, yb)
            if n % batch_size:
                tail_x = _fmap(
                    lambda a: jnp.asarray(a[steps * batch_size :]), feats
                )
                tail_y = jnp.asarray(labs[steps * batch_size :])
                mstate, loss_sum, count = eval_step(
                    params, mstate, loss_sum, count, tail_x, tail_y
                )
        else:
            for x, y in PrefetchingDeviceIterator(
                self._epoch_batches(source, batch_size, None, shuffle=False),
                mesh, shard_direct=self.shard_direct,
            ):
                mstate, loss_sum, count = eval_step(
                    params, mstate, loss_sum, count, x, y
                )
        # one transfer for both scalars: separate float() calls would pay a
        # full transport round trip each (~70ms on tunneled PJRT)
        loss_v, count_v = np.asarray(jnp.stack([loss_sum, count]))
        out = {"eval_loss": float(loss_v) / max(float(count_v), 1.0)}
        out.update({f"eval_{k}": v for k, v in self._metrics.compute(mstate).items()})
        return out

    def evaluate(self, ds) -> Dict[str, float]:
        """Standalone evaluation with the trained params."""
        if self._params is None:
            raise RuntimeError("call fit() first")
        mesh = self._resolve_mesh()
        # cache the jitted pair: a fresh _make_eval_step per call would make
        # EVERY evaluate() retrace (and on big models recompile) from scratch
        cached = getattr(self, "_eval_fns_cache", None)
        if cached is not None and cached[0] is self._module:
            eval_fns = cached[1]
        else:
            eval_fns = self._make_eval_step(self._module, self._resolve_loss())
            self._eval_fns_cache = (self._module, eval_fns)
        source = ds if self.streaming else self._stage_host(ds)
        with mesh:
            return self._evaluate_host(
                source,
                self._params,
                eval_fns,
                mesh,
                self._effective_batch(mesh),
            )

    # ------------------------------------------------------------------
    # fit_on_etl (reference fit_on_spark, :332-363)
    # ------------------------------------------------------------------

    # fit_on_etl (both exchange paths, incl. fs_directory parquet staging)
    # is inherited from EtlEstimatorInterface — shared by every estimator

    # ------------------------------------------------------------------
    # checkpointing (orbax; reference uses AIR Checkpoint dicts :243-250)
    # ------------------------------------------------------------------

    def _gc_step_checkpoints(self, epoch: int) -> None:
        """The epoch-complete checkpoint supersedes that epoch's mid-epoch
        step checkpoints — drop them so save_every_steps doesn't accumulate
        one full model copy per segment per epoch. With ``keep_checkpoints``
        set, epoch checkpoints older than the newest N go too. Primary host
        only (the save above already barriered, so epoch_N is committed
        everywhere)."""
        import re
        import shutil

        import jax

        if jax.process_index() != 0:
            return
        root = os.path.abspath(self.checkpoint_dir)
        try:
            names = os.listdir(root)
        except OSError:
            return
        keep_from = (
            epoch - self.keep_checkpoints + 1 if self.keep_checkpoints else None
        )
        for name in names:
            if re.fullmatch(rf"epoch_{epoch}_step_\d+", name):
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)
            elif keep_from is not None:
                m = re.fullmatch(r"epoch_(\d+)", name)
                if m and int(m.group(1)) < keep_from:
                    shutil.rmtree(os.path.join(root, name), ignore_errors=True)

    def _ckpt_path(self, epoch: int, step: Optional[int] = None) -> str:
        name = f"epoch_{epoch}" if step is None else f"epoch_{epoch}_step_{step}"
        return os.path.join(os.path.abspath(self.checkpoint_dir), name)

    def _save_checkpoint(
        self, params, epoch: int, opt_state, step: Optional[int] = None
    ) -> None:
        """Full training state (params + optimizer state) via orbax — exact
        step-level resume, strictly stronger than the reference's model-only
        AIR checkpoints (torch/estimator.py:243-250). ``step`` is the number
        of completed steps WITHIN ``epoch`` (save_every_steps cadence);
        ``step=None`` marks the epoch complete.

        The host state is DEEP-COPIED before it reaches orbax: on backends
        where ``device_get`` is zero-copy (CPU), the returned numpy arrays
        alias the live device buffers, and orbax's StandardCheckpointer can
        complete file writes asynchronously — with ``donate_state`` a later
        train step reuses those exact buffers, so an in-flight write could
        serialize whatever the optimizer scribbled over them. (Same aliased-
        buffer-vs-donation hazard class as the resume-staging fix in
        ``_fit_once``, which was the verified root cause of the 2-core-box
        "streaming NaN" flake; the copy here closes the save-side window.)"""
        import jax
        import orbax.checkpoint as ocp

        state = jax.tree.map(
            lambda x: np.array(x, copy=True),
            {
                "params": jax.device_get(params),
                "opt_state": jax.device_get(opt_state),
            },
        )
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(self._ckpt_path(epoch, step), state, force=True)

    def _restore_checkpoint(
        self, epoch: int, target: Optional[dict] = None, step: Optional[int] = None
    ) -> dict:
        """Checkpoint layout: {"params": <variables>, "opt_state": <optax>}.
        ``target`` (a concrete state template) restores optax namedtuple
        structure exactly; without it containers come back as plain pytrees
        (fine for params, which are dicts all the way down)."""
        import orbax.checkpoint as ocp

        path = self._ckpt_path(epoch, step)
        with ocp.StandardCheckpointer() as ckptr:
            if target is not None:
                restored = ckptr.restore(path, target)
            else:
                restored = ckptr.restore(path)
        # sanitizer bookkeeping (RAYDP_TPU_SANITIZE=donation, no-op
        # otherwise): restored leaves are host memory owned by orbax's
        # restore machinery — on CPU jax a zero-copy staging of them must
        # never be donated (the PR 2 streaming-NaN class); registering them
        # here lets checked_jit catch any future staging path that skips
        # the owned-copy dance in _fit
        from raydp_tpu.sanitize import donation_check_enabled

        if donation_check_enabled():
            import jax

            from raydp_tpu.sanitize import note_external_host_buffer

            for leaf in jax.tree_util.tree_leaves(restored):
                if isinstance(leaf, np.ndarray):
                    note_external_host_buffer(leaf, tag="orbax restore")
        return restored

    def load_checkpoint(self, epoch: int):
        restored = self._restore_checkpoint(epoch)
        self._params = restored["params"]
        if self._module is None:
            self._module = self._resolve_model()
        return self._params

    # ------------------------------------------------------------------
    # inference loading + predict (the serving plane's path: a replica
    # restores params from the newest committed checkpoint and serves
    # module.apply — no optimizer is ever constructed)
    # ------------------------------------------------------------------

    def load_latest_checkpoint(self):
        """Restore params from the NEWEST committed checkpoint under
        ``checkpoint_dir`` (epoch-complete preferred over that epoch's
        step checkpoints, exactly ``latest_checkpoint``'s ordering) for
        INFERENCE: unlike the fit-oriented resume path, no optax optimizer
        is resolved, no opt_state template is built, and nothing is staged
        to device — the restored host opt_state leaves are dropped on the
        spot (orbax's StandardCheckpointer restores the saved tree whole;
        a partial target raises a key-mismatch). Returns ``(epoch, step)``
        of the checkpoint served (``step`` None for epoch-complete)."""
        found = latest_checkpoint(self.checkpoint_dir)
        if found is None:
            raise FileNotFoundError(
                f"no committed checkpoint under {self.checkpoint_dir!r}"
            )
        epoch, step = found
        restored = self._restore_checkpoint(epoch, step=step)
        self._params = restored["params"]  # opt_state dropped host-side
        if self._module is None:
            self._module = self._resolve_model()
        return epoch, step

    def predict(self, batch):
        """Inference over a host feature batch (numpy array, or a tuple of
        arrays on the mixed-dtype path) with the current params — available
        after ``fit()`` OR ``load_latest_checkpoint()``/``load_checkpoint``.
        Returns host numpy. The jitted apply is cached per module identity
        (jax's own cache then keys on batch shape), mirroring the evaluate
        path's _eval_fns_cache so repeated predicts never retrace."""
        import jax

        if self._params is None:
            raise RuntimeError(
                "no params: call fit() or load_latest_checkpoint() first"
            )
        if self._module is None:
            self._module = self._resolve_model()
        cached = getattr(self, "_predict_fn_cache", None)
        if cached is not None and cached[0] is self._module:
            fn = cached[1]
        else:
            fn = jax.jit(self._module.apply)
            self._predict_fn_cache = (self._module, fn)
        return np.asarray(fn(self._params, batch))

    # ------------------------------------------------------------------

    def get_model(self) -> JaxModel:
        if self._params is None:
            raise RuntimeError("call fit() first")
        return JaxModel(self._module, self._params)

    @property
    def history(self) -> List[Dict[str, float]]:
        return self._history


def latest_checkpoint(checkpoint_dir: Optional[str]):
    """Newest committed checkpoint as ``(epoch, step_or_None)`` — or None.
    ``epoch_N`` (epoch complete) sorts after every ``epoch_N_step_K``
    (orbax renames the tmp dir only after a successful commit, so a bare
    checkpoint directory is complete)."""
    import re

    if not checkpoint_dir:
        return None
    root = os.path.abspath(checkpoint_dir)
    if not os.path.isdir(root):
        return None
    found = []
    for name in os.listdir(root):
        if not os.path.isdir(os.path.join(root, name)):
            continue
        m = re.fullmatch(r"epoch_(\d+)(?:_step_(\d+))?", name)
        if m:
            step = int(m.group(2)) if m.group(2) is not None else None
            found.append((int(m.group(1)), step))
    if not found:
        return None
    return max(found, key=lambda es: (es[0], float("inf") if es[1] is None else es[1]))


def latest_checkpoint_epoch(checkpoint_dir: Optional[str]) -> Optional[int]:
    """Highest epoch with a COMPLETE (end-of-epoch) checkpoint on disk."""
    import re

    if not checkpoint_dir:
        return None
    root = os.path.abspath(checkpoint_dir)
    if not os.path.isdir(root):
        return None
    epochs = [
        int(m.group(1))
        for name in os.listdir(root)
        for m in [re.fullmatch(r"epoch_(\d+)", name)]
        if m and os.path.isdir(os.path.join(root, name))
    ]
    return max(epochs) if epochs else None


