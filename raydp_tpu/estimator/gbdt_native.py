"""Native distributed histogram GBDT — the backend XGBoostEstimator uses
when ``xgboost`` is not installed.

Parity note: the reference's GBDT path (xgboost/estimator.py:61-81) delegates
to xgboost_ray's Rabit-allreduce actors. GBDT is host-side math with no TPU
involvement (SURVEY.md §2.4), so what matters for parity is the *distributed
training shape*: sharded data on rank actors, per-round gradient/histogram
computation local to each rank, a collective reduction of histograms, and a
single model coming back. This module implements exactly that shape on the
framework's own SPMD job runtime:

- each rank holds its shard binned to uint8 (quantile bins, like xgboost's
  'hist' tree method) and caches preds/grad/hess between calls;
- tree growth is LEVEL-WISE: per level the driver ships the partial tree,
  ranks return per-node (grad, hess) histograms, and the driver reduces them
  and picks best splits (the reduction rides the driver instead of Rabit —
  same semantics, simpler transport);
- leaf values are the standard second-order estimates -G/(H+lambda).

Supported objectives: reg:squarederror, binary:logistic (the two the
reference's examples exercise).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

MAX_BINS = 64

# rank-process-local state, keyed by job name (functions shipped to a rank
# run in the same worker process for the job's lifetime, so module globals
# persist across job.run calls)
_STATE: Dict[str, Dict[str, Any]] = {}


@dataclasses.dataclass
class Tree:
    feature: np.ndarray  # int32 [nodes]; -1 = leaf
    threshold_bin: np.ndarray  # int32 [nodes]; go left when bin <= threshold
    left: np.ndarray  # int32 [nodes]
    right: np.ndarray  # int32 [nodes]
    value: np.ndarray  # float32 [nodes]; leaf output


def _new_tree() -> Tree:
    return Tree(
        feature=np.array([-1], np.int32),
        threshold_bin=np.array([0], np.int32),
        left=np.array([-1], np.int32),
        right=np.array([-1], np.int32),
        value=np.array([0.0], np.float32),
    )


def _descend(tree: Tree, binned: np.ndarray) -> np.ndarray:
    """Vectorized node assignment of every row under a (partial) tree."""
    n = binned.shape[0]
    node = np.zeros(n, np.int32)
    for _ in range(64):  # depth bound; loop exits when all rows hit leaves
        feat = tree.feature[node]
        active = feat >= 0
        if not active.any():
            break
        rows = np.nonzero(active)[0]
        f = feat[rows]
        go_left = binned[rows, f] <= tree.threshold_bin[node[rows]]
        node[rows] = np.where(
            go_left, tree.left[node[rows]], tree.right[node[rows]]
        )
    return node


def _bin_features(features: np.ndarray, edges: List[np.ndarray]) -> np.ndarray:
    binned = np.empty(features.shape, np.uint8)
    for f in range(features.shape[1]):
        binned[:, f] = np.searchsorted(edges[f], features[:, f], side="left").astype(
            np.uint8
        )
    return binned


def _grad_hess(pred: np.ndarray, y: np.ndarray, objective: str):
    if objective == "binary:logistic":
        p = 1.0 / (1.0 + np.exp(-pred))
        return p - y, np.maximum(p * (1.0 - p), 1e-16)
    # reg:squarederror
    return pred - y, np.ones_like(pred)


def _loss(pred: np.ndarray, y: np.ndarray, objective: str) -> float:
    if objective == "binary:logistic":
        p = 1.0 / (1.0 + np.exp(-pred))
        eps = 1e-12
        return float(-np.mean(y * np.log(p + eps) + (1 - y) * np.log(1 - p + eps)))
    return float(np.mean((pred - y) ** 2))


# ---------------------------------------------------------------------------
# rank-side functions (picklable classes shipped via job.run)
# ---------------------------------------------------------------------------


class InitFn:
    """Load this rank's shard, reply with a quantile sample for binning."""

    def __init__(self, job_key: str, shards, feature_columns, label_column,
                 sample_rows: int = 4096):
        self.job_key = job_key
        self.shards = shards
        self.feature_columns = feature_columns
        self.label_column = label_column
        self.sample_rows = sample_rows

    def __call__(self, ctx):
        features, labels = self.shards[ctx.rank].to_numpy(
            self.feature_columns, self.label_column
        )
        features = np.asarray(features, np.float64)
        labels = np.asarray(labels, np.float64).reshape(-1)
        _STATE[self.job_key] = {"features": features, "labels": labels}
        n = len(features)
        take = min(self.sample_rows, n)
        idx = np.random.default_rng(ctx.rank).choice(n, take, replace=False)
        return {"n": n, "label_sum": float(labels.sum()), "sample": features[idx]}


class BinFn:
    """Bin the local shard with the driver's global quantile edges."""

    def __init__(self, job_key: str, edges: List[np.ndarray], base: float):
        self.job_key = job_key
        self.edges = edges
        self.base = base

    def __call__(self, ctx):
        st = _STATE[self.job_key]
        st["binned"] = _bin_features(st["features"], self.edges)
        st["pred"] = np.full(len(st["features"]), self.base, np.float64)
        return True


class GradFn:
    """Refresh grad/hess from the current predictions (start of a round)."""

    def __init__(self, job_key: str, objective: str):
        self.job_key = job_key
        self.objective = objective

    def __call__(self, ctx):
        st = _STATE[self.job_key]
        st["grad"], st["hess"] = _grad_hess(
            st["pred"], st["labels"], self.objective
        )
        return True


class HistFn:
    """Per-node (grad, hess) histograms of the local shard under the partial
    tree — the piece a Rabit allreduce would sum; here the driver reduces."""

    def __init__(self, job_key: str, tree: Tree, active_nodes: List[int],
                 n_bins: int):
        self.job_key = job_key
        self.tree = tree
        self.active_nodes = active_nodes
        self.n_bins = n_bins

    def __call__(self, ctx):
        st = _STATE[self.job_key]
        binned, g, h = st["binned"], st["grad"], st["hess"]
        assign = _descend(self.tree, binned)
        n_feat = binned.shape[1]
        out = {}
        for node in self.active_nodes:
            mask = assign == node
            if not mask.any():
                out[node] = np.zeros((n_feat, self.n_bins, 2), np.float64)
                continue
            b = binned[mask]
            gg, hh = g[mask], h[mask]
            hist = np.zeros((n_feat, self.n_bins, 2), np.float64)
            for f in range(n_feat):
                hist[f, :, 0] = np.bincount(
                    b[:, f], weights=gg, minlength=self.n_bins
                )[: self.n_bins]
                hist[f, :, 1] = np.bincount(
                    b[:, f], weights=hh, minlength=self.n_bins
                )[: self.n_bins]
            out[node] = hist
        return out


class ApplyFn:
    """Fold the finalized tree into the local predictions; report local loss."""

    def __init__(self, job_key: str, tree: Tree, learning_rate: float,
                 objective: str):
        self.job_key = job_key
        self.tree = tree
        self.learning_rate = learning_rate
        self.objective = objective

    def __call__(self, ctx):
        st = _STATE[self.job_key]
        st["pred"] += self.learning_rate * self.tree.value[
            _descend(self.tree, st["binned"])
        ]
        return {
            "n": len(st["pred"]),
            "loss_sum": _loss(st["pred"], st["labels"], self.objective)
            * len(st["pred"]),
        }


class CleanupFn:
    def __init__(self, job_key: str):
        self.job_key = job_key

    def __call__(self, ctx):
        _STATE.pop(self.job_key, None)
        return True


# ---------------------------------------------------------------------------
# driver-side training loop
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class NativeBooster:
    """The trained model: predictable on raw (unbinned) feature matrices."""

    trees: List[Tree]
    edges: List[np.ndarray]
    base_score: float
    objective: str
    learning_rate: float

    def predict(self, features: np.ndarray, output_margin: bool = False):
        features = np.asarray(features, np.float64)
        binned = _bin_features(features, self.edges)
        margin = np.full(binned.shape[0], self.base_score, np.float64)
        for tree in self.trees:
            margin += self.learning_rate * tree.value[_descend(tree, binned)]
        if self.objective == "binary:logistic" and not output_margin:
            return 1.0 / (1.0 + np.exp(-margin))
        return margin

    def save_raw(self) -> bytes:
        import pickle

        return pickle.dumps(self)

    @staticmethod
    def load_raw(blob: bytes) -> "NativeBooster":
        import pickle

        model = pickle.loads(blob)
        if not isinstance(model, NativeBooster):
            raise TypeError("not a NativeBooster blob")
        return model


def _best_split(hist: np.ndarray, lam: float, min_child_weight: float):
    """(gain, feature, bin) of the best split for one node's histogram, or
    None. Vectorized over features x bins via cumulative sums."""
    G = hist[:, :, 0].sum(axis=1)  # [F] (same total every feature)
    H = hist[:, :, 1].sum(axis=1)
    gl = np.cumsum(hist[:, :, 0], axis=1)  # [F, B] left-of-or-at bin
    hl = np.cumsum(hist[:, :, 1], axis=1)
    gr = G[:, None] - gl
    hr = H[:, None] - hl
    valid = (hl >= min_child_weight) & (hr >= min_child_weight)
    parent = (G[0] ** 2) / (H[0] + lam)
    gain = gl**2 / (hl + lam) + gr**2 / (hr + lam) - parent
    gain = np.where(valid, gain, -np.inf)
    f, b = np.unravel_index(np.argmax(gain), gain.shape)
    if not np.isfinite(gain[f, b]) or gain[f, b] <= 1e-12:
        return None
    return float(gain[f, b]), int(f), int(b)


def train_distributed(
    job,
    shards,
    params: Dict[str, Any],
    num_boost_round: int,
    feature_columns: Sequence[str],
    label_column: str,
) -> Tuple[NativeBooster, List[Dict[str, float]]]:
    """Drive the distributed boosting loop over an ALREADY-STARTED SpmdJob.
    Returns (booster, per-round history)."""
    objective = str(params.get("objective", "reg:squarederror"))
    lr = float(params.get("eta", params.get("learning_rate", 0.3)))
    max_depth = int(params.get("max_depth", 6))
    lam = float(params.get("lambda", params.get("reg_lambda", 1.0)))
    min_child_weight = float(params.get("min_child_weight", 1.0))
    n_bins = min(MAX_BINS, int(params.get("max_bin", MAX_BINS)))

    job_key = f"gbdt-{job.job_name}"
    infos = job.run(InitFn(job_key, shards, list(feature_columns), label_column))
    total = sum(i["n"] for i in infos)
    label_mean = sum(i["label_sum"] for i in infos) / max(total, 1)
    if objective == "binary:logistic":
        p = min(max(label_mean, 1e-6), 1 - 1e-6)
        base = float(np.log(p / (1 - p)))
    else:
        base = float(label_mean)

    sample = np.concatenate([i["sample"] for i in infos], axis=0)
    edges = []
    for f in range(sample.shape[1]):
        qs = np.quantile(sample[:, f], np.linspace(0, 1, n_bins)[1:-1])
        edges.append(np.unique(qs))
    job.run(BinFn(job_key, edges, base))

    trees: List[Tree] = []
    history: List[Dict[str, float]] = []
    try:
        for round_idx in range(num_boost_round):
            job.run(GradFn(job_key, objective))
            tree = _new_tree()
            node_stats: Dict[int, Tuple[float, float]] = {}
            active = [0]
            for _depth in range(max_depth):
                if not active:
                    break
                hists = job.run(HistFn(job_key, tree, active, n_bins))
                reduced = {
                    node: sum(h[node] for h in hists) for node in active
                }
                next_active = []
                for node in active:
                    hist = reduced[node]
                    node_stats[node] = (
                        float(hist[0, :, 0].sum()),
                        float(hist[0, :, 1].sum()),
                    )
                    split = _best_split(hist, lam, min_child_weight)
                    if split is None:
                        continue
                    _gain, f, b = split
                    left_id = len(tree.feature)
                    right_id = left_id + 1
                    tree.feature[node] = f
                    tree.threshold_bin[node] = b
                    tree.left[node] = left_id
                    tree.right[node] = right_id
                    tree.feature = np.append(tree.feature, [-1, -1]).astype(np.int32)
                    tree.threshold_bin = np.append(
                        tree.threshold_bin, [0, 0]
                    ).astype(np.int32)
                    tree.left = np.append(tree.left, [-1, -1]).astype(np.int32)
                    tree.right = np.append(tree.right, [-1, -1]).astype(np.int32)
                    tree.value = np.append(tree.value, [0.0, 0.0]).astype(np.float32)
                    gl = float(hist[f, : b + 1, 0].sum())
                    hl = float(hist[f, : b + 1, 1].sum())
                    g, h = node_stats[node]
                    node_stats[left_id] = (gl, hl)
                    node_stats[right_id] = (g - gl, h - hl)
                    next_active += [left_id, right_id]
                active = next_active
            # leaf values: -G/(H+lambda) for every remaining leaf
            for node, (g, h) in node_stats.items():
                if tree.feature[node] < 0:
                    tree.value[node] = -g / (h + lam)
            applied = job.run(ApplyFn(job_key, tree, lr, objective))
            loss = sum(a["loss_sum"] for a in applied) / max(
                sum(a["n"] for a in applied), 1
            )
            trees.append(tree)
            history.append({"round": round_idx, "train_loss": loss})
    finally:
        try:
            job.run(CleanupFn(job_key))
        except Exception:  # raydp-lint: disable=swallowed-exceptions (distributed cleanup is best-effort; workers GC on exit)
            pass
    booster = NativeBooster(trees, edges, base, objective, lr)
    return booster, history
