"""XGBoostEstimator — distributed GBDT parity.

The reference's XGBoostEstimator (xgboost/estimator.py:31-116) delegates to
``xgboost_ray``'s Rabit-allreduce actors. GBDT is host-side math (no TPU
involvement — SURVEY.md §2.4 marks it out of TPU scope), so this estimator
runs xgboost's own collective-based distributed training across this
framework's SPMD rank actors when ``xgboost`` is installed, and degrades to a
clear ImportError when it isn't (it is not part of this image's baked deps).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from raydp_tpu.estimator.base import EstimatorInterface, EtlEstimatorInterface


def _have_xgboost() -> bool:
    try:
        import xgboost  # noqa: F401

        return True
    except ImportError:
        return False


class _XGBWorkerFn:
    """Per-rank training: xgboost collective (Rabit successor) over TCP,
    rendezvousing at the driver-hosted tracker."""

    def __init__(self, config: Dict[str, Any], shards, eval_shards,
                 worker_args: Dict[str, Any]):
        self.config = config
        self.shards = shards
        self.eval_shards = eval_shards
        self.worker_args = worker_args  # tracker coordinates from the driver

    def __call__(self, ctx):
        import xgboost as xgb

        cfg = self.config
        features, labels = self.shards[ctx.rank].to_numpy(
            cfg["feature_columns"], cfg["label_column"]
        )
        dtrain = xgb.DMatrix(features, label=labels)
        evals = []
        if self.eval_shards is not None:
            ef, el = self.eval_shards[ctx.rank].to_numpy(
                cfg["feature_columns"], cfg["label_column"]
            )
            evals = [(xgb.DMatrix(ef, label=el), "eval")]

        if ctx.world_size > 1:
            args = dict(self.worker_args)
            args["dmlc_task_id"] = str(ctx.rank)
            with xgb.collective.CommunicatorContext(**args):
                booster = xgb.train(
                    cfg["params"], dtrain, num_boost_round=cfg["num_boost_round"],
                    evals=evals,
                )
        else:
            booster = xgb.train(
                cfg["params"], dtrain, num_boost_round=cfg["num_boost_round"],
                evals=evals,
            )
        return booster.save_raw().decode("latin1") if ctx.rank == 0 else None


def _driver_ip() -> str:
    """The driver's address as seen from the cluster — workers on other
    hosts must be able to reach the tracker (loopback only works when every
    rank shares the driver's machine)."""
    import socket

    try:
        from raydp_tpu.cluster.api import head_tcp_addr

        host, port = head_tcp_addr()[len("tcp://"):].rsplit(":", 1)
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect((host, int(port)))  # no traffic: routing lookup only
            return s.getsockname()[0]
        finally:
            s.close()
    except Exception:
        return "127.0.0.1"


def _start_tracker(n_workers: int):
    """Driver-side rendezvous tracker (the role xgboost_ray's tracker plays in
    the reference). Returns (tracker_or_None, worker_args)."""
    if n_workers <= 1:
        return None, {}
    from xgboost.tracker import RabitTracker

    tracker = RabitTracker(host_ip=_driver_ip(), n_workers=n_workers)
    tracker.start()
    args = tracker.worker_args()
    return tracker, dict(args)


class XGBoostEstimator(EstimatorInterface, EtlEstimatorInterface):
    """Distributed GBDT (reference xgboost/estimator.py:31-116). Two
    backends behind one API:

    - ``xgboost``: xgboost's own collective training across this framework's
      SPMD rank actors, rendezvousing at a driver-hosted RabitTracker;
    - ``native``: the in-repo distributed histogram GBDT
      (estimator/gbdt_native.py) — same sharded-data/reduced-histograms
      shape, no external dependency.

    ``backend="auto"`` (default) picks xgboost when installed, else native.
    """

    def __init__(
        self,
        params: Optional[Dict[str, Any]] = None,
        num_boost_round: int = 10,
        feature_columns: Optional[Sequence[str]] = None,
        label_column: Optional[str] = None,
        num_workers: int = 1,
        backend: str = "auto",
    ):
        if backend not in ("auto", "xgboost", "native"):
            raise ValueError(f"unknown backend {backend!r}")
        if backend == "auto":
            backend = "xgboost" if _have_xgboost() else "native"
        if backend == "xgboost" and not _have_xgboost():
            raise ImportError(
                "XGBoostEstimator(backend='xgboost') requires the 'xgboost' "
                "package, which is not installed. Use backend='native' (or "
                "'auto') for the built-in distributed histogram GBDT."
            )
        self.backend = backend
        self.params = dict(params or {"objective": "reg:squarederror"})
        self.num_boost_round = num_boost_round
        self.feature_columns = list(feature_columns or [])
        self.label_column = label_column
        self.num_workers = num_workers
        self._raw_model: Optional[str] = None
        self._native_model = None
        self._history: List[Dict[str, float]] = []

    def fit(self, train_ds, evaluate_ds=None, max_retries: int = 0):
        from raydp_tpu.spmd import create_spmd_job

        attempts = 0
        while True:
            try:
                shards = train_ds.split(self.num_workers, equal=True)
                eval_shards = (
                    evaluate_ds.split(self.num_workers, equal=True)
                    if evaluate_ds is not None
                    else None
                )
                if self.backend == "native":
                    from raydp_tpu.estimator import gbdt_native

                    job = create_spmd_job(world_size=self.num_workers).start()
                    try:
                        booster, history = gbdt_native.train_distributed(
                            job, shards, self.params, self.num_boost_round,
                            self.feature_columns, self.label_column,
                        )
                    finally:
                        job.stop()
                    self._native_model = booster
                    self._history = history
                    return booster
                cfg = {
                    "params": self.params,
                    "num_boost_round": self.num_boost_round,
                    "feature_columns": self.feature_columns,
                    "label_column": self.label_column,
                }
                tracker, worker_args = _start_tracker(self.num_workers)
                job = create_spmd_job(world_size=self.num_workers).start()
                try:
                    results = job.run(
                        _XGBWorkerFn(cfg, shards, eval_shards, worker_args),
                        timeout=600.0,
                    )
                finally:
                    job.stop()
                    if tracker is not None:
                        try:
                            tracker.wait_for()
                        except Exception:  # raydp-lint: disable=swallowed-exceptions (tracker join after workers finished is best-effort)
                            pass
                self._raw_model = results[0]
                return self._raw_model
            except Exception:
                attempts += 1
                if attempts > max_retries:
                    raise

    # fit_on_etl (incl. the fs_directory parquet staging path) is inherited
    # from EtlEstimatorInterface — shared by every estimator

    def get_model(self):
        if self.backend == "native":
            if self._native_model is None:
                raise RuntimeError("call fit() first")
            return self._native_model
        import xgboost as xgb

        if self._raw_model is None:
            raise RuntimeError("call fit() first")
        booster = xgb.Booster()
        booster.load_model(bytearray(self._raw_model.encode("latin1")))
        return booster

    @property
    def history(self) -> List[Dict[str, float]]:
        """Per-round train loss (native backend)."""
        return self._history
