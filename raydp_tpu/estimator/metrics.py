"""Metric registry for estimators.

Replaces the reference's torchmetrics wrapper (TorchMetric,
torch/torch_metrics.py:21-55) and keras metric-by-name serialization
(tf/estimator.py:124-136) with pure-JAX streaming metrics: each metric keeps a
(sum-like, count-like) state so per-batch updates compose across steps and —
because they are plain jnp ops — run *inside* the jitted step function, with
the cross-device reduction compiled in.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

# metric: (update(pred, target) -> (value_sum, weight)); result = value_sum/weight
_REGISTRY: Dict[str, Callable] = {}


def register_metric(name: str):
    def wrap(fn):
        _REGISTRY[name] = fn
        return fn

    return wrap


@register_metric("mse")
def _mse(pred, target):
    import jax.numpy as jnp

    pred = pred.reshape(target.shape)
    return jnp.sum((pred - target) ** 2), target.size


@register_metric("mae")
def _mae(pred, target):
    import jax.numpy as jnp

    pred = pred.reshape(target.shape)
    return jnp.sum(jnp.abs(pred - target)), target.size


@register_metric("rmse")
def _rmse(pred, target):  # finalized with sqrt in Metrics.compute
    import jax.numpy as jnp

    pred = pred.reshape(target.shape)
    return jnp.sum((pred - target) ** 2), target.size


@register_metric("accuracy")
def _accuracy(pred, target):
    import jax.numpy as jnp

    if pred.ndim > target.ndim:
        predicted = jnp.argmax(pred, axis=-1)
    else:
        predicted = (pred.reshape(target.shape) > 0.5).astype(target.dtype)
    return jnp.sum(predicted == target), target.size


class Metrics:
    """A named bundle of streaming metrics with jit-friendly state."""

    def __init__(self, names):
        self.names = list(names or [])
        for name in self.names:
            if name not in _REGISTRY:
                raise ValueError(
                    f"unknown metric {name!r}; available: {sorted(_REGISTRY)}"
                )

    def init_state(self) -> Dict[str, Tuple]:
        import jax.numpy as jnp

        return {
            n: (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
            for n in self.names
        }

    def update(self, state, pred, target):
        import jax.numpy as jnp

        out = {}
        for n in self.names:
            add_v, add_w = _REGISTRY[n](pred, target)
            v, w = state[n]
            out[n] = (v + add_v.astype(jnp.float32), w + jnp.float32(add_w))
        return out

    def compute(self, state) -> Dict[str, float]:
        results = {}
        for n in self.names:
            v, w = state[n]
            value = float(v) / max(float(w), 1.0)
            if n == "rmse":
                value = value**0.5
            results[n] = value
        return results
