"""TFEstimator — Keras parity estimator.

The reference's TFEstimator (tf/estimator.py:38-274) serializes the keras
model/optimizer/loss (:98-136), ships them to Ray Train's TensorflowTrainer
workers, and trains under ``MultiWorkerMirroredStrategy`` (:160). Here the
worker gang is this framework's SPMD job launcher: each rank actor writes its
own ``TF_CONFIG`` (cluster = all ranks' 127.0.0.1 ports, task = its rank)
before importing tensorflow — exactly the contract MWMS expects — and reads
its equal-share dataset shard straight from the shared-memory object store.

Serialization matches the reference: keras model → JSON config + initial
weights; optimizer/loss/metrics → keras serialize dicts (instances) or plain
names (strings), rebuilt inside the strategy scope on every worker.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from raydp_tpu.estimator.base import EstimatorInterface, EtlEstimatorInterface


class _TFWorkerFn:
    """Picklable per-rank training closure."""

    def __init__(self, config: Dict[str, Any], shards, eval_shards, addrs: List[str]):
        self.config = config
        self.shards = shards
        self.eval_shards = eval_shards
        self.addrs = addrs

    def __call__(self, ctx):
        import json
        import os

        # cluster spec = every rank's OWN host:port (job.worker_addresses),
        # so MWMS collectives rendezvous across hosts — the reference gets
        # this from Ray Train's TF_CONFIG assembly (tf/estimator.py:160)
        os.environ["TF_CONFIG"] = json.dumps(
            {
                "cluster": {"worker": list(self.addrs)},
                "task": {"type": "worker", "index": ctx.rank},
            }
        )
        os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
        import tensorflow as tf

        cfg = self.config
        if ctx.world_size > 1:
            strategy = tf.distribute.MultiWorkerMirroredStrategy()
        else:
            strategy = tf.distribute.get_strategy()  # no-op strategy

        with strategy.scope():
            model = tf.keras.models.model_from_json(cfg["model_json"])
            if cfg["weights"] is not None:
                model.set_weights([np.asarray(w) for w in cfg["weights"]])
            optimizer = tf.keras.optimizers.deserialize(dict(cfg["optimizer"]))
            loss_obj = (
                tf.keras.losses.deserialize(dict(cfg["loss"]))
                if isinstance(cfg["loss"], dict)
                else tf.keras.losses.get(cfg["loss"])
            )
            # build optimizer slots up front (Keras 3 requires explicit build)
            optimizer.build(model.trainable_variables)

        shard = self.shards[ctx.rank]
        features, labels = shard.to_numpy(
            cfg["feature_columns"], cfg["label_column"]
        )
        batch = cfg["batch_size"]
        dataset = tf.data.Dataset.from_tensor_slices((features, labels))
        if cfg["shuffle"]:
            dataset = dataset.shuffle(len(features), seed=cfg["seed"])
        dataset = dataset.batch(batch, drop_remainder=True).repeat()
        # ranks already hold disjoint equal shards: MWMS must not re-shard
        options = tf.data.Options()
        options.experimental_distribute.auto_shard_policy = (
            tf.data.experimental.AutoShardPolicy.OFF
        )
        dataset = dataset.with_options(options)
        steps_per_epoch = max(1, len(features) // batch)
        dist_iter = iter(strategy.experimental_distribute_dataset(dataset))

        # Custom strategy.run loop: Keras 3's model.fit no longer supports
        # MultiWorkerMirroredStrategy (the reference's TF2 path did); the
        # gradient all-reduce rides strategy's collectives in apply_gradients.
        @tf.function
        def train_step(x, y):
            def replica_step(x, y):
                with tf.GradientTape() as tape:
                    pred = tf.reshape(model(x, training=True), tf.shape(y))
                    per_example = loss_obj(y, pred)
                    loss = tf.reduce_mean(per_example)
                grads = tape.gradient(loss, model.trainable_variables)
                optimizer.apply_gradients(zip(grads, model.trainable_variables))
                return loss

            per_replica = strategy.run(replica_step, args=(x, y))
            return strategy.reduce(
                tf.distribute.ReduceOp.MEAN, per_replica, axis=None
            )

        eval_arrays = None
        if self.eval_shards is not None:
            eval_arrays = self.eval_shards[ctx.rank].to_numpy(
                cfg["feature_columns"], cfg["label_column"]
            )

        history: Dict[str, List[float]] = {"loss": []}
        for _ in range(cfg["num_epochs"]):
            total = 0.0
            for _ in range(steps_per_epoch):
                x, y = next(dist_iter)
                total += float(train_step(x, y))
            history["loss"].append(total / steps_per_epoch)
            if eval_arrays is not None:
                ef, el = eval_arrays
                pred = model(tf.convert_to_tensor(ef), training=False)
                eval_loss = float(
                    tf.reduce_mean(loss_obj(el, tf.reshape(pred, el.shape)))
                )
                history.setdefault("val_loss", []).append(eval_loss)

        weights = (
            [np.asarray(w) for w in model.get_weights()] if ctx.rank == 0 else None
        )
        return {"history": history, "weights": weights}


class TFEstimator(EstimatorInterface, EtlEstimatorInterface):
    def __init__(
        self,
        model: Any = None,  # keras model instance or zero-arg creator fn
        optimizer: Any = "adam",
        loss: Any = "mse",
        metrics: Optional[Sequence[str]] = None,
        feature_columns: Optional[Sequence[str]] = None,
        label_column: Optional[str] = None,
        batch_size: int = 64,
        num_epochs: int = 10,
        num_workers: int = 1,
        shuffle: bool = True,
        seed: int = 0,
    ):
        self._model_arg = model
        self._optimizer_arg = optimizer
        self._loss_arg = loss
        self.metrics = list(metrics or [])
        self.feature_columns = list(feature_columns or [])
        self.label_column = label_column
        self.batch_size = batch_size
        self.num_epochs = num_epochs
        self.num_workers = num_workers
        self.shuffle = shuffle
        self.seed = seed
        self._weights: Optional[List[np.ndarray]] = None
        self._model_json: Optional[str] = None
        self._history: Dict[str, List[float]] = {}

    def _serialize(self) -> Dict[str, Any]:
        """Keras model/optimizer/loss → shippable dicts (reference :98-136)."""
        import tensorflow as tf

        model = self._model_arg
        if callable(model) and not isinstance(model, tf.keras.Model):
            model = model()
        self._model_json = model.to_json()
        weights = [np.asarray(w) for w in model.get_weights()]

        optimizer = self._optimizer_arg
        if isinstance(optimizer, str):
            optimizer = tf.keras.optimizers.get(optimizer)
        optimizer_cfg = tf.keras.optimizers.serialize(optimizer)

        loss = self._loss_arg
        if not isinstance(loss, str):
            loss = tf.keras.losses.serialize(
                loss if not isinstance(loss, type) else loss()
            )
        return {
            "model_json": self._model_json,
            "weights": weights,
            "optimizer": optimizer_cfg,
            "loss": loss,
            "metrics": self.metrics,
            "feature_columns": self.feature_columns,
            "label_column": self.label_column,
            "batch_size": self.batch_size,
            "num_epochs": self.num_epochs,
            "shuffle": self.shuffle,
            "seed": self.seed,
        }

    def fit(self, train_ds, evaluate_ds=None, max_retries: int = 0):
        from raydp_tpu.spmd import create_spmd_job

        attempts = 0
        while True:
            try:
                cfg = self._serialize()
                shards = train_ds.split(self.num_workers, equal=True)
                eval_shards = (
                    evaluate_ds.split(self.num_workers, equal=True)
                    if evaluate_ds is not None
                    else None
                )
                job = create_spmd_job(
                    world_size=self.num_workers, placement_strategy="SPREAD"
                ).start()
                try:
                    # resolve AFTER start: each rank's address must point at
                    # the host it actually landed on, not the driver's
                    worker_fn = _TFWorkerFn(
                        cfg, shards, eval_shards, job.worker_addresses()
                    )
                    results = job.run(worker_fn, timeout=900.0)
                finally:
                    job.stop()
                self._history = results[0]["history"]
                self._weights = results[0]["weights"]
                return self._history
            except Exception:
                attempts += 1
                if attempts > max_retries:
                    raise

    # fit_on_etl (incl. the fs_directory parquet staging path) is inherited
    # from EtlEstimatorInterface — shared by every estimator

    def get_model(self):
        """Rebuild the trained keras model (reference :270-274)."""
        import tensorflow as tf

        if self._weights is None:
            raise RuntimeError("call fit() first")
        model = tf.keras.models.model_from_json(self._model_json)
        model.set_weights(self._weights)
        return model

    @property
    def history(self) -> Dict[str, List[float]]:
        return self._history
