"""Distributed estimators (reference L6: Torch/TF/XGBoost estimators →
JaxEstimator flagship + parity estimators)."""

from raydp_tpu.estimator.base import EstimatorInterface, EtlEstimatorInterface
from raydp_tpu.estimator.jax_estimator import JaxEstimator, JaxModel
from raydp_tpu.estimator.metrics import Metrics, register_metric
from raydp_tpu.estimator.tf_estimator import TFEstimator
from raydp_tpu.estimator.torch_estimator import TorchEstimator
from raydp_tpu.estimator.xgboost_estimator import XGBoostEstimator

__all__ = [
    "EstimatorInterface",
    "EtlEstimatorInterface",
    "JaxEstimator",
    "JaxModel",
    "Metrics",
    "TFEstimator",
    "TorchEstimator",
    "XGBoostEstimator",
    "register_metric",
]
