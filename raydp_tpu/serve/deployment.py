"""Deployment: the user-facing handle over a replica pool.

``serve.deploy(estimator, ...)`` (or an explicit model + checkpoint_dir)
spawns N ``ModelReplica`` actors, wires the dynamic batcher in front of them,
and starts the controller (healing + optional autoscaling). The deployment
object is the request client: ``predict(payload)`` is thread-safe and
blocking — concurrent client threads are the intended usage.

Replica-count management is RECONCILIATION-shaped: every path (explicit
``scale_to``, autoscaler decisions, failure healing) just moves the pool
toward ``_target``; races between the controller thread and a user thread
self-correct on the next pass instead of needing a lock held across spawn
RPCs (which the blocking-under-lock rule — correctly — forbids). Scale-in
always drains: the batcher stops routing to the victim, its in-flight
batches finish, then it is killed.

Rolling reload: ``reload()`` walks the replicas ONE AT A TIME; each replica
restores the newest checkpoint and AOT-warms it while its old generation
keeps serving (ModelReplica swaps atomically), so the deployment serves
every request throughout — from the old weights until that replica's swap,
from the new after.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from raydp_tpu import obs, sanitize
from raydp_tpu.cluster import api as cluster
from raydp_tpu.cluster.common import ActorState, ClusterError
from raydp_tpu.serve.autoscaler import ServeController
from raydp_tpu.serve.batcher import DynamicBatcher
from raydp_tpu.serve.config import ServeConf
from raydp_tpu.serve.replica import ModelReplica, ReplicaSpec


class Deployment:
    def __init__(
        self,
        spec: ReplicaSpec,
        conf: ServeConf,
        replicas: int = 1,
        feature_columns=None,
    ):
        if not cluster.is_initialized():
            cluster.init()
        self._spec = spec
        self._conf = conf
        self._name = spec.name
        self._closed = False
        self._next_idx = 0
        self._next_stream = 0  # round-robin cursor for decode streams
        self._lock = sanitize.named_lock(
            "serve.deployment", threading.RLock()
        )
        # guarded-by: self._lock
        self._handles: List = []
        self._target = max(1, int(replicas))
        if conf.autoscale:
            self._target = min(
                max(self._target, conf.min_replicas), conf.max_replicas
            )
        self._m_out = obs.metrics.counter("serve.scale_out")
        self._m_in = obs.metrics.counter("serve.scale_in")
        self._m_reloads = obs.metrics.counter("serve.reloads")
        self._m_failovers = obs.metrics.counter("serve.replica_replacements")
        self._g_replicas = obs.metrics.gauge("serve.replicas")
        # client-side record of the last COMPLETED decode stream (stamps,
        # serving replica, stream id) — explain_last_stream starts here
        # (guarded-by: self._lock)
        self._last_stream: Optional[dict] = None
        admission = None
        if conf.tenant:
            # ride the named tenant's fair-share queue (docs/multitenancy.md);
            # a serve-only tenant (no ETL session) registers with defaults
            from raydp_tpu.tenancy import registry as _treg

            scheduler = _treg.scheduler()
            if conf.tenant not in scheduler.snapshot():
                scheduler.register(conf.tenant)
            admission = scheduler.handle(conf.tenant)
        self.batcher = DynamicBatcher(
            conf,
            feature_columns=feature_columns,
            on_replica_failure=self._on_replica_failure,
            admission=admission,
        )
        try:
            with obs.span(
                "serve.deploy", deployment=self._name,
                replicas=self._target,
            ):
                self._reconcile()
            self.controller = ServeController(self, conf)
        except BaseException:
            # a deployment that failed to come up must not leave batcher
            # threads or half-spawned replicas behind the leak audit
            self._teardown()
            raise

    # -- replica pool ---------------------------------------------------

    def _spawn_one(self):
        with self._lock:
            idx = self._next_idx
            self._next_idx += 1
        handle = cluster.spawn(
            ModelReplica,
            self._spec,
            name=f"{self._name}-serve-replica-{idx}",
            # death is handled by the deployment's own healing (a fresh
            # spawn reloads the checkpoint), not the head's restart path —
            # one recovery story instead of two racing ones
            max_restarts=0,
            max_concurrency=self._conf.replica_max_concurrency,
            light=self._conf.replica_light,
        )
        return handle

    def _reconcile(self) -> None:
        """Move the pool to ``_target``. Spawns and drains run OFF the
        lock; membership mutations under it."""
        while True:
            with self._lock:
                if self._closed:
                    return
                current = len(self._handles)
                target = self._target
            if current < target:
                try:
                    handle = self._spawn_one()
                except (ClusterError, OSError):
                    # cluster unreachable (teardown racing a heal tick) or
                    # spawn rejected: serve on with the survivors rather
                    # than wedging the controller in a spawn-retry loop
                    obs.log.warning(
                        "serve replica spawn failed; continuing with "
                        "current pool", deployment=self._name, exc_info=True,
                    )
                    break
                with self._lock:
                    if self._closed or len(self._handles) >= self._target:
                        surplus = True
                    else:
                        self._handles.append(handle)
                        surplus = False
                if surplus:  # lost a race; don't leak the spawn
                    self._kill_quietly(handle)
                else:
                    self.batcher.add_replica(handle)
            elif current > target:
                with self._lock:
                    if len(self._handles) <= self._target:
                        continue
                    victim = self._handles.pop()  # youngest first
                # graceful drain: stop routing, let in-flight finish, kill
                self.batcher.remove_replica(victim.actor_id, drain=True)
                self._kill_quietly(victim)
            else:
                break
        self._g_replicas.set(self.replica_count())

    @staticmethod
    def _kill_quietly(handle) -> None:
        try:
            handle.kill(no_restart=True)
        except Exception:  # raydp-lint: disable=swallowed-exceptions (victim may already be dead; the head GCs either way)
            pass

    def _on_replica_failure(self, handle) -> None:
        # called from a batcher dispatcher thread; the controller's next
        # tick does the actual replacement — the batcher has already
        # stopped routing to the failed id
        obs.log.warning(
            "serve replica failed; healing on next controller tick",
            actor_id=handle.actor_id, deployment=self._name,
        )

    def heal(self) -> int:
        """Resolve batcher-flagged replicas against the head's verdict
        (DEAD or unknown: drop and replace; ALIVE: the failure was a
        transient transport blip, resume routing), probe the rest for
        silent deaths (a replica SIGKILLed while idle never trips a
        dispatcher), then reconcile back to target. Returns the number of
        replicas replaced."""
        with self._lock:
            if self._closed:
                return 0
            snapshot = list(self._handles)
        flagged = set(self.batcher.failed_ids())
        dead = []
        for handle in snapshot:
            gone = False
            try:
                gone = handle.state() == ActorState.DEAD
            except ClusterError:
                gone = True  # unknown to the head = not servable
            if gone:
                dead.append(handle)
            elif handle.actor_id in flagged:
                self.batcher.add_replica(handle)  # transient: clear the flag
        if not dead:
            return 0
        with self._lock:
            for handle in dead:
                if handle in self._handles:
                    self._handles.remove(handle)
        for handle in dead:
            self.batcher.remove_replica(handle.actor_id, drain=False)
        self._m_failovers.inc(len(dead))
        obs.instant(
            "serve.replica_replaced", count=len(dead), deployment=self._name
        )
        self._reconcile()
        return len(dead)

    def replica_count(self) -> int:
        with self._lock:
            return len(self._handles)

    def scale_to(self, n: int) -> None:
        """Explicit scale (also the autoscaler's actuator). Scale-in drains
        gracefully; scale-out spawns warm zygote forks."""
        n = max(1, int(n))
        with self._lock:
            old = self._target
            self._target = n
        if n > old:
            self._m_out.inc(n - old)
        elif n < old:
            self._m_in.inc(old - n)
        self._reconcile()

    # -- request surface ------------------------------------------------

    def predict(self, payload, timeout: Optional[float] = None):
        """Blocking inference; thread-safe — this IS the client."""
        return self.batcher.predict(payload, timeout)

    def submit(self, payload):
        """Async variant: returns a request whose ``.result(timeout)``
        yields the prediction rows."""
        return self.batcher.submit(payload)

    # -- decode streaming (docs/serving.md, "Decode serving") -----------

    def _pick_decode_handle(self):
        with self._lock:
            if not self._handles:
                raise ClusterError("no live replicas")
            handle = self._handles[self._next_stream % len(self._handles)]
            self._next_stream += 1
        return handle

    def stream(self, prompt_tokens, max_new_tokens: int,
               timeout: float = 120.0):
        """Stream generated tokens for one prompt (generator of ints).

        Picks a replica round-robin, submits to its continuous-batching
        decode engine, and polls tokens out as they land. On replica
        death or reload mid-stream the deployment heals and RESUBMITS to
        a survivor with prompt + already-emitted tokens as the prefix —
        the KV cache is re-prefilled there, and because a decode step is
        bit-identical to a prefill over the same tokens (the kernel-family
        parity contract, f32 cache), the continuation carries on with
        exactly the tokens the dead replica would have produced. No token
        is ever emitted twice and none is lost: zero-drop re-admission,
        stream edition.

        Sampled streams (``obs.request_sample_rate``, tracing on) mint ONE
        trace id at admission that survives failover: a ``serve.stream``
        root span here, the engine's prefill child + per-round
        ``serve.decode.step`` fan-in spans on whichever replica serves each
        segment, and a ``serve.stream.failover`` span per re-prefill — one
        trace across driver/head/replica (docs/observability.md)."""
        import random
        import time

        from raydp_tpu.obs import tracing as _tracing
        from raydp_tpu.serve.batcher import _RETRYABLE

        prompt = [int(t) for t in prompt_tokens]
        max_new = int(max_new_tokens)
        emitted: List[int] = []
        t_request = time.monotonic()
        deadline = t_request + timeout
        failovers = 0
        rpc_timeout = self._conf.request_timeout_s
        ctx = None
        if (
            _tracing.enabled()
            and self._conf.request_sample_rate > 0
            and random.random() < self._conf.request_sample_rate
        ):
            ctx = _tracing.mint_context()
        handle = None
        sid = None
        t_first = None
        error = None
        try:
            while True:
                try:
                    handle = self._pick_decode_handle()
                    # the submit RPC runs under the stream's context, so
                    # the head's actor-lookup span and the replica's RPC
                    # hop land in the same trace
                    with _tracing.use_context(ctx):
                        sid = handle.decode_submit.options(
                            timeout=rpc_timeout
                        ).remote(
                            prompt + emitted, max_new - len(emitted),
                            trace_ctx=ctx,
                        ).result()
                    cursor = 0
                    while True:
                        res = handle.decode_poll.options(
                            timeout=rpc_timeout
                        ).remote(sid, cursor).result()
                        new = res["tokens"]
                        cursor += len(new)
                        for tok in new:
                            if t_first is None:
                                t_first = time.monotonic()
                            emitted.append(int(tok))
                            yield int(tok)
                        if res["error"]:
                            # engine-side failure (e.g. retired by a reload
                            # mid-stream): same recovery as a dead replica
                            raise ClusterError(res["error"])
                        if res["done"]:
                            return
                        if time.monotonic() > deadline:
                            raise TimeoutError(
                                f"decode stream timed out after {timeout}s "
                                f"({len(emitted)}/{max_new} tokens)"
                            )
                        time.sleep(0.003)
                except _RETRYABLE + (KeyError,):
                    failovers += 1
                    t_fail = time.monotonic()
                    if failovers > self._conf.max_retries:
                        raise
                    if t_fail > deadline:
                        raise TimeoutError(
                            f"decode stream timed out after {timeout}s "
                            f"({len(emitted)}/{max_new} tokens)"
                        )
                    obs.log.warning(
                        "decode stream failover: re-prefilling on a survivor",
                        deployment=self._name, emitted=len(emitted),
                        exc_info=True,
                    )
                    obs.metrics.counter("serve.decode.failovers").inc()
                    self.heal()
                    if ctx is not None and _tracing.enabled():
                        heal_s = time.monotonic() - t_fail
                        _tracing.record_span(
                            "serve.stream.failover",
                            time.time_ns() // 1000 - int(heal_s * 1e6),
                            int(heal_s * 1e6),
                            trace=ctx[0], parent=ctx[1],
                            emitted=len(emitted), failovers=failovers,
                            deployment=self._name,
                        )
        except BaseException as exc:
            error = repr(exc)[:200]
            raise
        finally:
            t_done = time.monotonic()
            record = {
                "deployment": self._name,
                "handle": handle,
                "stream_id": sid,
                "tokens": len(emitted),
                "failovers": failovers,
                "error": error,
                "wall_s": max(0.0, t_done - t_request),
                "ttft_s": (
                    max(0.0, t_first - t_request)
                    if t_first is not None else None
                ),
                "trace": ctx[0] if ctx else None,
            }
            with self._lock:
                self._last_stream = record
            if ctx is not None and _tracing.enabled():
                _tracing.record_span(
                    "serve.stream",
                    time.time_ns() // 1000 - int(record["wall_s"] * 1e6),
                    int(record["wall_s"] * 1e6),
                    trace=ctx[0], span_id=ctx[1], parent=None,
                    deployment=self._name, tokens=len(emitted),
                    failovers=failovers, error=error,
                    ttft_ms=(
                        round(record["ttft_s"] * 1000.0, 3)
                        if record["ttft_s"] is not None else None
                    ),
                )

    def generate(self, prompt_tokens, max_new_tokens: int,
                 timeout: float = 120.0) -> List[int]:
        """Blocking convenience over ``stream``: the full token list."""
        return list(self.stream(prompt_tokens, max_new_tokens, timeout))

    def explain_last_stream(self, top_k: int = 5) -> dict:
        """Decompose the last completed stream's wall time: TTFT into
        queue wait / KV alloc / prefill compute / dispatch, and the steady
        state into step compute / admission churn / batch-fill stall —
        from the serving engine's own stream record plus this client's
        stamps. Works with tracing OFF, exactly like ``explain_last_query``
        / ``explain_last_fit``; returns the ``obs.analysis.explain_stream``
        report with a rendered ``text`` field."""
        with self._lock:
            record = dict(self._last_stream) if self._last_stream else None
        if record is None:
            raise RuntimeError(
                "no stream has completed on this deployment yet"
            )
        engine_record = None
        handle = record.get("handle")
        if handle is not None and record.get("stream_id"):
            try:
                engine_record = handle.decode_explain.options(
                    timeout=self._conf.request_timeout_s
                ).remote(record["stream_id"]).result()
            except Exception:  # raydp-lint: disable=swallowed-exceptions (the serving replica may have died since; the client stamps still attribute what they can)
                engine_record = None
        from raydp_tpu.obs.analysis import explain_stream

        return explain_stream(record, engine_record, top_k=top_k)

    def decode_stats(self) -> List[dict]:
        """Per-replica decode engine stats (inflight/queued/KV/goodput/veto
        causes) — empty dicts for replicas that never streamed."""
        with self._lock:
            snapshot = list(self._handles)
        return [h.decode_stats.remote().result() for h in snapshot]

    # -- lifecycle ------------------------------------------------------

    def reload(self) -> List[dict]:
        """Rolling checkpoint reload: one replica at a time picks up the
        newest committed checkpoint; old weights serve until each replica's
        new generation is warm. Returns the per-replica info dicts."""
        with self._lock:
            snapshot = list(self._handles)
        infos = []
        with obs.span("serve.reload", deployment=self._name,
                      replicas=len(snapshot)):
            for handle in snapshot:
                infos.append(handle.reload.remote().result())
        self._m_reloads.inc()
        return infos

    def infos(self) -> List[dict]:
        with self._lock:
            snapshot = list(self._handles)
        return [h.info.remote().result() for h in snapshot]

    def profile(self, payload=None) -> dict:
        """Capture one replica's warm inference under the compute
        observatory (``ModelReplica.profile``): the first live replica
        runs a deep (jax-profiler when available, span-only otherwise)
        capture of one inference and returns the capture summary —
        on-demand, never on the request path."""
        with self._lock:
            snapshot = list(self._handles)
        if not snapshot:
            raise RuntimeError("no live replicas to profile")
        return snapshot[0].profile.remote(payload).result()

    def stats(self) -> dict:
        out = self.batcher.stats()
        out["target_replicas"] = self._target
        out["doorbell_pooled"] = int(
            obs.metrics.counter("serve.doorbell_pooled").value
        )
        return out

    def _teardown(self) -> None:
        controller = getattr(self, "controller", None)
        if controller is not None:
            controller.close()
        batcher = getattr(self, "batcher", None)
        if batcher is not None:
            batcher.close()
        with self._lock:
            self._closed = True
            victims = list(self._handles)
            self._handles.clear()
        for handle in victims:
            self._kill_quietly(handle)
        self._g_replicas.set(0)

    def close(self) -> None:
        """Stop serving: controller and batcher threads join (pending
        requests fail with a closed error), replicas are killed. Idempotent;
        call before ``cluster.shutdown()`` so the leak audit stays clean."""
        with self._lock:
            if self._closed:
                return
        self._teardown()

    def __enter__(self) -> "Deployment":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def deploy(
    estimator=None,
    *,
    model=None,
    checkpoint_dir: Optional[str] = None,
    name: str = "default",
    replicas: int = 1,
    conf: Optional[dict] = None,
    example=None,
    feature_columns=None,
) -> Deployment:
    """Stand up an online serving deployment for a trained model.

    Pass a fitted/configured ``JaxEstimator`` (its model, feature columns and
    ``checkpoint_dir`` are adopted — weights always travel via the
    checkpoint, never by value) or an explicit ``model`` + ``checkpoint_dir``.
    ``example`` (one feature row) lets replicas AOT-compile every batch
    bucket at boot so no request ever pays a compile. ``conf`` takes
    ``serve.*`` keys (docs/serving.md); an active ETL session's ``serve.*``
    configs are merged underneath it."""
    if estimator is not None:
        model = model if model is not None else estimator._model_arg
        checkpoint_dir = checkpoint_dir or estimator.checkpoint_dir
        if feature_columns is None:
            feature_columns = list(estimator.feature_columns) or None
    if model is None or not checkpoint_dir:
        raise ValueError(
            "deploy needs an estimator, or model= plus checkpoint_dir="
        )
    resolved = ServeConf.resolve(conf)
    decode_kwargs = {}
    if resolved.decode:
        decode_kwargs = {
            "capacity_tokens": resolved.decode_capacity_tokens,
            "page_tokens": resolved.decode_page_tokens,
            "max_seqs": resolved.decode_max_seqs,
            "max_new_tokens": resolved.decode_max_new_tokens,
            "int8_kv": resolved.decode_int8_kv,
            "eos_token": resolved.decode_eos_token,
            "max_mem_pressure": resolved.max_mem_pressure,
            "ttft_slo_ms": resolved.decode_ttft_slo_ms,
            "tpot_slo_ms": resolved.decode_tpot_slo_ms,
            "tenant": resolved.tenant,
        }
    spec = ReplicaSpec(
        model=model,
        checkpoint_dir=checkpoint_dir,
        buckets=resolved.buckets,
        example=example,
        name=name,
        decode=decode_kwargs,
    )
    return Deployment(
        spec, resolved, replicas=replicas, feature_columns=feature_columns
    )
