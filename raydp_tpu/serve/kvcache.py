"""Paged KV cache for incremental decode, backed by the shm block store.

One shared-memory arena (``store.create_block(storage="shm")``) holds a pool
of fixed-size pages; each sequence owns a block table (list of page ids) and
a valid length. Decode steps ``append`` the newest K/V rows and ``gather``
dense per-layer [B, H, Tcap, D] tensors for ``ops.flash_decode`` — positions
at or past a sequence's length are garbage by design and masked inside the
kernel by ``kv_len``.

Living in shm (``rtpu-`` prefix) makes the cache a first-class citizen of
the memory-watermark plane: ``mem.shm_bytes`` / ``mem.pressure`` see every
page the moment the arena is created, the admission controller in
``serve.decode`` can veto new sequences on pressure, and the leak audit
fails shutdown if an arena outlives its engine.

Optional int8 mode stores quantized K/V values plus per-row (per position,
per head) f32 scales from ``ops.quantization.quantize_int8``; the decode
kernel dequantizes on the fly. f32 mode is bit-exact — the mode the
decode-vs-prefill determinism contract is stated for.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Sequence

import numpy as np

from raydp_tpu.obs import metrics

DEFAULT_PAGE_TOKENS = 128


class KVCacheFull(RuntimeError):
    """No free pages — the admission controller should defer, not crash."""


class PagedKVCache:
    """Page-pool KV cache with per-sequence block tables.

    layers/heads/head_dim: model geometry (one pool spans all layers).
    capacity_tokens: per-sequence maximum length (multiple of page_tokens);
        the fixed shape the decode kernel compiles against.
    max_seqs: sizes the default pool (``max_seqs`` full-length sequences).
    int8: store int8 values + per-row f32 scales instead of f32 values.
    """

    def __init__(
        self,
        *,
        layers: int,
        heads: int,
        head_dim: int,
        capacity_tokens: int,
        page_tokens: int = DEFAULT_PAGE_TOKENS,
        max_seqs: int = 8,
        pool_pages: int | None = None,
        int8: bool = False,
        storage: str = "shm",
        tenant: str = "",
    ):
        if capacity_tokens % page_tokens:
            raise ValueError(
                f"capacity_tokens {capacity_tokens} must be a multiple of "
                f"page_tokens {page_tokens}"
            )
        self.layers = layers
        self.heads = heads
        self.head_dim = head_dim
        self.capacity_tokens = capacity_tokens
        self.page_tokens = page_tokens
        self.pages_per_seq = capacity_tokens // page_tokens
        self.pool_pages = pool_pages or max_seqs * self.pages_per_seq
        self.int8 = int8
        self.tenant = str(tenant or "")

        page_rows = page_tokens * heads
        val_itemsize = 1 if int8 else 4
        self._val_bytes = (
            layers * 2 * self.pool_pages * page_rows * head_dim * val_itemsize
        )
        self._scale_bytes = (
            layers * 2 * self.pool_pages * page_rows * 4 if int8 else 0
        )
        total = self._val_bytes + self._scale_bytes

        from raydp_tpu.store.object_store import (
            ObjectRef, _register, create_block, current_owner,
        )

        self._block = create_block(total, storage=storage)
        # Register the arena with the head under THIS process's owner id
        # (the replica actor in serving). A replica SIGKILLed mid-decode
        # then strands no KV memory: actor death fires the head's
        # owner-GC (`_on_owner_dead`), which unlinks the segment like any
        # owned block — an unsealed block is otherwise known only to its
        # creator, and a SIGKILL would orphan it forever. Explicit owner:
        # the block-service handoff must never adopt the arena (it would
        # outlive the replica, which is exactly backwards). Best-effort —
        # a standalone engine (unit tests, driver-side experiments) has
        # no head; there the creator's abort() + leak audit cover it.
        self._ref = None
        try:
            ref = ObjectRef(self._block.object_id, total)
            _register(ref, current_owner())
            self._ref = ref
        except Exception:  # raydp-lint: disable=swallowed-exceptions (no cluster: standalone engines clean up via abort(); nothing to GC head-side)
            self._ref = None
        view = self._block.writable_view()
        val_dtype = np.int8 if int8 else np.float32
        # [layer, k/v, page, token, head, dim] — token-major rows inside a
        # page so a page is a contiguous run of quantization rows
        self._vals = np.frombuffer(
            view, dtype=val_dtype, count=self._val_bytes // val_itemsize
        ).reshape(layers, 2, self.pool_pages, page_tokens, heads, head_dim)
        if int8:
            self._scales = np.frombuffer(
                view, dtype=np.float32, count=self._scale_bytes // 4,
                offset=self._val_bytes,
            ).reshape(layers, 2, self.pool_pages, page_tokens, heads)
        else:
            self._scales = None

        self._lock = threading.Lock()
        self._free: List[int] = list(range(self.pool_pages))
        self._tables: Dict[str, List[int]] = {}
        self._lengths: Dict[str, int] = {}
        self._closed = False
        self.nbytes = total
        metrics.gauge("serve.kv.bytes").set_watermark(float(total))
        metrics.gauge("serve.kv.pages_total").set(float(self.pool_pages))
        self._update_gauges()

    # -- bookkeeping --------------------------------------------------------

    def _update_gauges(self) -> None:
        metrics.gauge("serve.kv.pages_free").set(float(len(self._free)))
        metrics.gauge("serve.kv.seqs").set(float(len(self._tables)))
        # occupancy/fragmentation plane (docs/observability.md, decode/KV
        # table): occupancy is the page pool's fill; fragmentation is the
        # share of allocated token slots no live position occupies (pages
        # are fixed-size, so a 1-token tail page is mostly waste) — the
        # "why is the pool full at low token counts" signal
        used = self.pool_pages - len(self._free)
        metrics.gauge("serve.kv.page_occupancy").set(
            used / float(self.pool_pages) if self.pool_pages else 0.0
        )
        allocated_tokens = used * self.page_tokens
        live_tokens = sum(self._lengths.values())
        metrics.gauge("serve.kv.fragmentation").set(
            1.0 - live_tokens / float(allocated_tokens)
            if allocated_tokens else 0.0
        )
        used_bytes = (
            self.nbytes * (used / float(self.pool_pages))
            if self.pool_pages else 0.0
        )
        metrics.gauge("serve.kv.used_bytes").set(used_bytes)
        if self.tenant:
            # tenant.<ns>.* names become tenant-labeled TSDB series
            # (obs/timeseries.py split_labels) — per-tenant KV accounting
            metrics.gauge(f"tenant.{self.tenant}.serve.kv.bytes").set(
                used_bytes
            )

    def alloc(self, seq_id: str) -> None:
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id!r} already allocated")
            self._tables[seq_id] = []
            self._lengths[seq_id] = 0
            self._update_gauges()

    def free(self, seq_id: str) -> None:
        with self._lock:
            pages = self._tables.pop(seq_id, [])
            self._lengths.pop(seq_id, None)
            self._free.extend(pages)
            self._update_gauges()

    def length(self, seq_id: str) -> int:
        return self._lengths[seq_id]

    def lengths(self, seq_ids: Sequence[str]) -> np.ndarray:
        return np.asarray([self._lengths[s] for s in seq_ids], np.int32)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_tokens)

    def can_admit(self, n_tokens: int) -> bool:
        return len(self._free) >= self.pages_needed(n_tokens)

    # -- data path ----------------------------------------------------------

    def append(self, seq_id: str, k_new: np.ndarray, v_new: np.ndarray) -> None:
        """Write the newest K/V rows. k_new/v_new: [layers, heads, t, dim]
        float32 (model output layout). Grows the block table as pages fill;
        raises KVCacheFull when the pool is dry (caller defers admission —
        in-flight sequences always have their pages already)."""
        t = k_new.shape[2]
        with self._lock:
            table = self._tables[seq_id]
            start = self._lengths[seq_id]
            if start + t > self.capacity_tokens:
                raise ValueError(
                    f"sequence {seq_id!r} would exceed capacity "
                    f"{self.capacity_tokens} ({start}+{t})"
                )
            need = self.pages_needed(start + t) - len(table)
            if need > len(self._free):
                raise KVCacheFull(
                    f"need {need} pages, {len(self._free)} free"
                )
            for _ in range(need):
                table.append(self._free.pop())
            self._lengths[seq_id] = start + t
            self._update_gauges()

        # [heads, t, dim] -> token-major [t, heads, dim] rows
        k_rows = np.ascontiguousarray(
            np.transpose(k_new, (0, 2, 1, 3)), dtype=np.float32
        )
        v_rows = np.ascontiguousarray(
            np.transpose(v_new, (0, 2, 1, 3)), dtype=np.float32
        )
        if self.int8:
            k_rows, k_sc = _quantize_rows(k_rows)
            v_rows, v_sc = _quantize_rows(v_rows)

        pos = start
        off = 0
        while off < t:
            page_idx = table[pos // self.page_tokens]
            in_page = pos % self.page_tokens
            n = min(self.page_tokens - in_page, t - off)
            sl = slice(in_page, in_page + n)
            src = slice(off, off + n)
            self._vals[:, 0, page_idx, sl] = k_rows[:, src]
            self._vals[:, 1, page_idx, sl] = v_rows[:, src]
            if self.int8:
                self._scales[:, 0, page_idx, sl] = k_sc[:, src]
                self._scales[:, 1, page_idx, sl] = v_sc[:, src]
            pos += n
            off += n

    def gather(self, seq_ids: Sequence[str]):
        """Dense per-layer cache tensors for a decode batch.

        f32 mode: (k, v) each [layers, B, heads, Tcap, dim] float32.
        int8 mode: (k, k_scale, v, v_scale) — values int8, scales
        [layers, B, heads, Tcap] float32.

        Unwritten positions are whatever the pool holds — the decode kernel
        masks them via kv_len, so no zero-fill pass is spent on them."""
        with self._lock:
            tables = []
            for s in seq_ids:
                table = self._tables[s]
                pad = self.pages_per_seq - len(table)
                # pad with page 0: masked by kv_len, never read meaningfully
                tables.append(table + [0] * pad)
            page_ids = np.asarray(tables, np.int64)  # [B, pages_per_seq]

        # [layers, 2, B, pages, page_tokens, heads, dim]
        vals = self._vals[:, :, page_ids]
        ly, _, bsz = vals.shape[:3]
        dense = vals.reshape(
            ly, 2, bsz, self.capacity_tokens, self.heads, self.head_dim
        ).transpose(0, 1, 2, 4, 3, 5)  # [layers, 2, B, heads, Tcap, dim]
        k, v = dense[:, 0], dense[:, 1]
        if not self.int8:
            return np.ascontiguousarray(k), np.ascontiguousarray(v)
        sc = self._scales[:, :, page_ids].reshape(
            ly, 2, bsz, self.capacity_tokens, self.heads
        ).transpose(0, 1, 2, 4, 3)  # [layers, 2, B, heads, Tcap]
        return (
            np.ascontiguousarray(k),
            np.ascontiguousarray(sc[:, 0]),
            np.ascontiguousarray(v),
            np.ascontiguousarray(sc[:, 1]),
        )

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._vals = None
        self._scales = None
        if self._ref is not None:
            # graceful retirement: drop the head's ownership record first
            # so the owner-GC has nothing left to do when the actor exits
            from raydp_tpu.store.object_store import delete

            try:
                delete([self._ref])
            except Exception:  # raydp-lint: disable=swallowed-exceptions (head already gone at teardown: its shutdown unlinked the segment)
                pass
            self._ref = None
        try:
            self._block.abort()
        except BufferError:  # raydp-lint: disable=swallowed-exceptions (a live numpy view pins the mmap; unlink still frees the name)
            pass
        metrics.gauge("serve.kv.bytes").set(0.0)
        # pages_total too (ISSUE 17 satellite): a closed arena must not
        # keep advertising capacity to scrapes
        metrics.gauge("serve.kv.pages_total").set(0.0)
        metrics.gauge("serve.kv.pages_free").set(0.0)
        metrics.gauge("serve.kv.seqs").set(0.0)
        metrics.gauge("serve.kv.page_occupancy").set(0.0)
        metrics.gauge("serve.kv.fragmentation").set(0.0)
        metrics.gauge("serve.kv.used_bytes").set(0.0)
        if self.tenant:
            metrics.gauge(f"tenant.{self.tenant}.serve.kv.bytes").set(0.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _quantize_rows(x: np.ndarray):
    """Per-row int8 quantization of [layers, t, heads, dim] rows (row = one
    position of one head), matching ``ops.quantization.quantize_int8``'s
    deterministic path so the kernel-side dequant is the exact inverse
    scale."""
    from raydp_tpu.ops.quantization import quantize_int8

    ly, t, h, d = x.shape
    vals, scales = quantize_int8(x.reshape(ly * t * h, d))
    return (
        np.asarray(vals).reshape(ly, t, h, d),
        np.asarray(scales).reshape(ly, t, h),
    )
