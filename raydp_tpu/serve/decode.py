"""Continuous-batching decode engine: autoregressive serving for
``TransformerLM`` checkpoints.

Orca-style iteration-level scheduling: the engine keeps a fixed number of
decode SLOTS and runs one model step per loop iteration; sequences join a
slot the moment one frees (after a prefill pass that warms their pages in
the ``PagedKVCache``) and leave the moment they finish — no bucket-padded
one-shot batches, no head-of-line blocking behind the longest sequence in
an admission batch. The decode step always runs at the fixed compiled shape
``[max_seqs, 1]`` (empty slots carry a pad sequence and are masked by
``kv_len``), so XLA numerics are bit-stable regardless of which sequences
share a step — the property the SIGKILL-mid-decode chaos gate's
token-identity check rests on.

Determinism contract (docs/serving.md): with a float32 cache, a decode step
is bit-identical to a prefill pass over the same tokens (the kernel-family
parity in ``ops/flash_attention.py``), so a stream resumed on another
replica by RE-PREFILLING prompt + already-emitted tokens continues with
exactly the tokens the dead replica would have produced. Sampling is greedy
(argmax) — deterministic by construction.

Admission is vetoed by the memory-watermark plane: the KV arena lives in
shm where ``mem.pressure`` sees it, and new sequences wait while pressure
exceeds the configured ceiling or the page pool cannot hold their worst
case. In-flight sequences always have their pages reserved up front, so a
step can never die on a full pool.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from raydp_tpu import sanitize
from raydp_tpu.obs import metrics
from raydp_tpu.obs import tracing as _tracing
from raydp_tpu.serve.kvcache import PagedKVCache

_PAD_SEQ = "_pad"

# retired-stream timing records kept for explain_last_stream (engine-side
# half of the decode observatory; docs/observability.md)
_RECORD_KEEP = 64


@dataclass
class _Stream:
    stream_id: str
    prompt: List[int]
    max_new_tokens: int
    t_submit: float
    tokens: List[int] = field(default_factory=list)
    done: bool = False
    error: Optional[str] = None
    t_first: Optional[float] = None
    # sampled stream trace context (trace_id, root_span_id) minted at
    # admission by the caller — engine-side spans parent under the root
    ctx: Optional[Tuple[str, str]] = None
    # lifecycle stamps + phase accumulators (always on, tracing or not):
    # the record explain_last_stream decomposes TTFT and time-per-token from
    t_admit: Optional[float] = None  # popped from pending → prefill starts
    t_last: Optional[float] = None  # previous token's emit (TPOT gaps)
    t_done: Optional[float] = None  # last token emitted
    prefill_s: float = 0.0  # prefill_fn compute
    kv_alloc_s: float = 0.0  # cache alloc + page-warm appends
    step_compute_s: float = 0.0  # decode-step walls while in a slot
    churn_s: float = 0.0  # other streams' admissions while in a slot
    steps: int = 0
    good_tokens: int = 0
    late_tokens: int = 0


class DecodeEngine:
    """One process-local continuous-batching loop over a TransformerLM.

    Standalone-constructible (tests run it without any actor around it);
    ``ModelReplica`` hosts one per process behind ``decode_submit`` /
    ``decode_poll`` RPCs. ``model`` must use a non-collective attention
    impl ("flash" recommended — it is the kernel family ``flash_decode``
    is parity-gated against).
    """

    def __init__(
        self,
        model,
        params,
        *,
        capacity_tokens: int = 512,
        page_tokens: int = 128,
        max_seqs: int = 4,
        max_new_tokens: int = 64,
        int8_kv: bool = False,
        eos_token: Optional[int] = None,
        max_mem_pressure: float = 0.95,
        ttft_slo_ms: Optional[float] = None,
        tpot_slo_ms: Optional[float] = None,
        tenant: str = "",
    ):
        self._model = model
        self._params = params
        self.capacity_tokens = int(capacity_tokens)
        self.max_seqs = int(max_seqs)
        self.max_new_tokens_cap = int(max_new_tokens)
        self.int8_kv = bool(int8_kv)
        self.eos_token = eos_token
        self.max_mem_pressure = float(max_mem_pressure)
        # per-token deadline tracking (serve.decode.goodput): first token
        # against ttft_slo_ms, token k against t_first + (k-1)*tpot_slo_ms
        # (cumulative — a slow step makes every later token late until the
        # engine catches back up, which is what an SLO consumer perceives)
        self.ttft_slo_ms = float(ttft_slo_ms) if ttft_slo_ms else None
        self.tpot_slo_ms = float(tpot_slo_ms) if tpot_slo_ms else None
        self.tenant = str(tenant or "")

        head_dim = model.d_model // model.num_heads
        self._cache = PagedKVCache(
            layers=model.num_layers,
            heads=model.num_heads,
            head_dim=head_dim,
            capacity_tokens=self.capacity_tokens,
            page_tokens=int(page_tokens),
            max_seqs=self.max_seqs + 1,  # + the pad sequence's page
            int8=self.int8_kv,
            tenant=self.tenant,
        )
        self._cache.alloc(_PAD_SEQ)
        zero = np.zeros((model.num_layers, model.num_heads, 1, head_dim),
                        np.float32)
        self._cache.append(_PAD_SEQ, zero, zero)

        import jax

        self._prefill_fn = jax.jit(
            lambda p, toks: model.apply(p, toks, return_kv=True)
        )
        self._decode_fn = jax.jit(
            lambda p, toks, kv_len, caches: model.apply(
                p, toks, kv_caches=caches, kv_len=kv_len
            )
        )

        self._lock = sanitize.named_lock("serve.decode", threading.Lock())
        # guarded-by: self._lock
        self._pending: deque = deque()
        self._streams: Dict[str, _Stream] = {}
        self._slots: List[Optional[str]] = [None] * self.max_seqs
        self._ids = itertools.count()
        self._closed = False
        self._wake = threading.Event()
        # retired-stream records for explain_last_stream, newest last
        # (guarded-by: self._lock)
        self._records: "OrderedDict[str, dict]" = OrderedDict()
        self._last_record: Optional[dict] = None
        # engine-local tallies for stats() — the metric counters below are
        # process-global and would conflate engines across tests
        self._good_total = 0
        self._late_total = 0
        self._veto_counts = {"kv_pages": 0, "slots": 0, "mem_pressure": 0}
        self._last_state_note = 0.0
        # end of the previous decode round (perf_counter): riders are
        # charged the FULL round-to-round wall — kernel, emit bookkeeping,
        # throttled flush RPCs, loop overhead — not just the kernel window,
        # so explain_stream's steady state decomposes to the engine's real
        # serving cost. Reset at each admission (that window is churn).
        self._round_anchor: Optional[float] = None

        self._m_tokens = metrics.counter("serve.decode.tokens")
        self._m_steps = metrics.counter("serve.decode.steps")
        self._m_prefills = metrics.counter("serve.decode.prefills")
        self._m_vetoed = metrics.counter("serve.decode.admission_vetoed")
        # veto causes, split so "why is my stream queued" has a metric
        self._m_veto_kv = metrics.counter("serve.decode.veto.kv_pages")
        self._m_veto_slots = metrics.counter("serve.decode.veto.slots")
        self._m_veto_mem = metrics.counter("serve.decode.veto.mem_pressure")
        self._m_good = metrics.counter("serve.decode.good_tokens")
        self._m_late = metrics.counter("serve.decode.late_tokens")
        self._g_goodput = metrics.gauge("serve.decode.goodput")
        self._g_inflight = metrics.gauge("serve.decode.inflight")
        self._g_queued = metrics.gauge("serve.decode.queued")
        self._h_fill = metrics.histogram("serve.decode.batch_fill")
        self._h_step = metrics.histogram("serve.decode.step_s")
        self._h_ttft = metrics.histogram("serve.ttft_ms")
        # cached at init like every other decode instrument (a registry
        # lookup per observation in the hot loop was the ISSUE 17 satellite)
        self._h_prefill = metrics.histogram("serve.decode.prefill_s")
        self._h_token = metrics.histogram("serve.decode.token_ms")
        self._h_tpot = metrics.histogram("serve.tpot_ms")
        # tenant.<ns>.* histograms become tenant-labeled percentile series
        # in the TSDB (split_labels + histogram fan-out, obs/timeseries.py)
        self._h_ttft_tenant = (
            metrics.histogram(f"tenant.{self.tenant}.serve.ttft_ms")
            if self.tenant else None
        )
        self._h_tpot_tenant = (
            metrics.histogram(f"tenant.{self.tenant}.serve.tpot_ms")
            if self.tenant else None
        )

        self._thread = threading.Thread(
            target=self._loop, name="serve-decode", daemon=True
        )
        self._thread.start()

    # -- client surface ------------------------------------------------

    def submit(
        self,
        prompt_tokens: Sequence[int],
        max_new_tokens: int,
        stream_id: Optional[str] = None,
        trace_ctx: Optional[Tuple[str, str]] = None,
    ) -> str:
        """Queue a sequence; returns a stream id to ``poll``. The prompt
        must fit the cache with its worst-case continuation. ``trace_ctx``
        is a sampled stream's (trace_id, root_span_id), minted at admission
        by the caller — the engine's prefill and step fan-in spans parent
        under it, one trace across driver/head/replica."""
        prompt = [int(t) for t in prompt_tokens]
        max_new = min(int(max_new_tokens), self.max_new_tokens_cap)
        if not prompt:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new > self.capacity_tokens:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new ({max_new}) exceeds "
                f"cache capacity {self.capacity_tokens}"
            )
        with self._lock:
            if self._closed:
                raise RuntimeError("decode engine closed")
            sid = stream_id or f"s{next(self._ids)}"
            if sid in self._streams:
                raise ValueError(f"stream {sid!r} already exists")
            stream = _Stream(sid, prompt, max_new, time.monotonic())
            if trace_ctx is not None:
                stream.ctx = (str(trace_ctx[0]), str(trace_ctx[1]))
            self._streams[sid] = stream
            self._pending.append(stream)
            self._g_queued.set(float(len(self._pending)))
        self._wake.set()
        return sid

    def poll(self, stream_id: str, cursor: int = 0) -> dict:
        """Tokens emitted at or after ``cursor`` plus terminal state —
        the polling half of the streaming API (request/response-shaped so
        it rides the ordinary actor RPC path)."""
        with self._lock:
            stream = self._streams.get(stream_id)
            if stream is None:
                raise KeyError(f"unknown stream {stream_id!r}")
            out = {
                "tokens": list(stream.tokens[int(cursor):]),
                "done": stream.done,
                "error": stream.error,
            }
            if stream.done:
                # terminal poll retires the bookkeeping once drained
                if int(cursor) + len(out["tokens"]) >= len(stream.tokens):
                    self._streams.pop(stream_id, None)
        return out

    def generate(
        self, prompt_tokens: Sequence[int], max_new_tokens: int,
        timeout: float = 60.0,
    ) -> List[int]:
        """Blocking convenience wrapper: submit + drain one stream."""
        sid = self.submit(prompt_tokens, max_new_tokens)
        deadline = time.monotonic() + timeout
        tokens: List[int] = []
        while True:
            res = self.poll(sid, len(tokens))
            tokens.extend(res["tokens"])
            if res["error"]:
                raise RuntimeError(res["error"])
            if res["done"]:
                return tokens
            if time.monotonic() > deadline:
                raise TimeoutError(f"stream {sid} timed out")
            time.sleep(0.002)

    def stats(self) -> dict:
        with self._lock:
            judged = self._good_total + self._late_total
            return {
                "inflight": sum(1 for s in self._slots if s is not None),
                "queued": len(self._pending),
                "streams": len(self._streams),
                "kv_pages_free": self._cache.free_pages,
                "kv_pages_total": self._cache.pool_pages,
                "kv_bytes": self._cache.nbytes,
                "good_tokens": self._good_total,
                "late_tokens": self._late_total,
                "goodput": (
                    self._good_total / judged if judged else None
                ),
                "vetoes": dict(self._veto_counts),
            }

    def explain(self, stream_id: Optional[str] = None) -> Optional[dict]:
        """The engine-kept timing record for one retired stream (default:
        the most recently retired) — the tracing-OFF data source behind
        ``deployment.explain_last_stream()`` (obs/analysis.py decode arm).
        Returns None when no stream has retired (or the id aged out of the
        bounded record window)."""
        with self._lock:
            if stream_id is None:
                rec = self._last_record
            else:
                rec = self._records.get(stream_id)
            return dict(rec) if rec is not None else None

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for stream in self._streams.values():
                if not stream.done:
                    stream.done = True
                    stream.error = "decode engine closed"
                    self._retire_locked(stream)
            self._pending.clear()
        self._wake.set()
        self._thread.join(timeout=10.0)
        self._cache.close()
        self._g_inflight.set(0.0)
        self._g_queued.set(0.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- engine loop ---------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._closed:
                    return
            try:
                worked = self._admit()
                worked = self._step() or worked
                self._note_state_throttled()
            except Exception as exc:  # noqa: BLE001 - engine must not die silently
                from raydp_tpu import obs

                obs.log.warning("decode engine step failed", exc_info=True)
                self._fail_all(exc)
                return
            if not worked:
                self._wake.wait(0.005)
                self._wake.clear()

    def _fail_all(self, exc: BaseException) -> None:
        with self._lock:
            for stream in self._streams.values():
                if not stream.done:
                    stream.done = True
                    stream.error = f"{type(exc).__name__}: {exc}"
                    self._retire_locked(stream)
            self._pending.clear()
            self._slots = [None] * self.max_seqs
            self._g_inflight.set(0.0)

    def _mem_pressure(self) -> float:
        try:
            from raydp_tpu.obs.profiler import current_mem_pressure

            return float(current_mem_pressure())
        except Exception:  # raydp-lint: disable=swallowed-exceptions (no samples yet = no veto signal)
            return 0.0

    def _admit(self) -> bool:
        """Move pending sequences into free slots: prefill their prompt at
        the fixed [1, capacity] shape, warm their KV pages, and emit the
        first token. Vetoed (not failed) while the page pool or the
        memory-watermark plane says no."""
        admitted = False
        while True:
            with self._lock:
                if not self._pending:
                    break
                try:
                    slot = self._slots.index(None)
                except ValueError:  # raydp-lint: disable=swallowed-exceptions (no free slot is the normal full-batch state, not an error; admission resumes when a stream retires)
                    self._m_veto_slots.inc()
                    self._veto_counts["slots"] += 1
                    break
                stream = self._pending[0]
                worst_case = len(stream.prompt) + stream.max_new_tokens
                if not self._cache.can_admit(worst_case):
                    self._m_vetoed.inc()
                    self._m_veto_kv.inc()
                    self._veto_counts["kv_pages"] += 1
                    break
                self._pending.popleft()
                self._g_queued.set(float(len(self._pending)))
            if self._mem_pressure() > self.max_mem_pressure:
                # put it back and stop admitting until pressure drains
                with self._lock:
                    self._pending.appendleft(stream)
                    self._g_queued.set(float(len(self._pending)))
                    self._veto_counts["mem_pressure"] += 1
                self._m_vetoed.inc()
                self._m_veto_mem.inc()
                break

            t0 = time.perf_counter()
            stream.t_admit = time.monotonic()
            prompt = stream.prompt
            length = len(prompt)
            toks = np.zeros((1, self.capacity_tokens), np.int32)
            toks[0, :length] = prompt
            import jax.numpy as jnp

            logits, new_kv = self._prefill_fn(self._params, jnp.asarray(toks))
            logits = np.asarray(logits)
            stream.prefill_s = time.perf_counter() - t0
            t_alloc = time.perf_counter()
            self._cache.alloc(stream.stream_id)
            k_rows = np.stack(
                [np.asarray(k)[0, :, :length] for k, _ in new_kv]
            ).astype(np.float32)
            v_rows = np.stack(
                [np.asarray(v)[0, :, :length] for _, v in new_kv]
            ).astype(np.float32)
            self._cache.append(stream.stream_id, k_rows, v_rows)
            stream.kv_alloc_s = time.perf_counter() - t_alloc
            first = int(np.argmax(logits[0, length - 1]))
            self._m_prefills.inc()
            self._emit(stream, first, slot=slot)
            admit_s = time.perf_counter() - t0
            self._h_prefill.observe(admit_s)
            with self._lock:
                # streams already decoding stalled for this admission's
                # whole window — the "admission churn" phase of their
                # time-per-token decomposition
                for sid in self._slots:
                    if sid is None or sid == stream.stream_id:
                        continue
                    other = self._streams.get(sid)
                    if other is not None:
                        other.churn_s += admit_s
                # the admission window is charged as churn above — move the
                # round anchor past it so _step doesn't charge it again
                self._round_anchor = time.perf_counter()
            if stream.ctx is not None and _tracing.enabled():
                now_wall_us = time.time_ns() // 1000
                _tracing.record_span(
                    "serve.decode.prefill",
                    now_wall_us - int(admit_s * 1e6), int(admit_s * 1e6),
                    trace=stream.ctx[0], parent=stream.ctx[1],
                    stream=stream.stream_id, prompt_tokens=length,
                    queue_s=round(stream.t_admit - stream.t_submit, 6),
                    prefill_s=round(stream.prefill_s, 6),
                    kv_alloc_s=round(stream.kv_alloc_s, 6),
                )
            admitted = True
        return admitted

    def _emit(self, stream: _Stream, token: int, slot: Optional[int] = None) -> None:
        now = time.monotonic()
        with self._lock:
            stream.tokens.append(int(token))
            n_tok = len(stream.tokens)
            if stream.t_first is None:
                stream.t_first = now
                ttft_ms = (now - stream.t_submit) * 1000.0
                self._h_ttft.observe(ttft_ms)
                if self._h_ttft_tenant is not None:
                    self._h_ttft_tenant.observe(ttft_ms)
                on_time = (
                    self.ttft_slo_ms is None or ttft_ms <= self.ttft_slo_ms
                )
            else:
                tpot_ms = (now - (stream.t_last or stream.t_first)) * 1000.0
                self._h_tpot.observe(tpot_ms)
                if self._h_tpot_tenant is not None:
                    self._h_tpot_tenant.observe(tpot_ms)
                # cumulative deadline: token k due at t_first + (k-1)*TPOT
                on_time = self.tpot_slo_ms is None or (
                    (now - stream.t_first) * 1000.0
                    <= (n_tok - 1) * self.tpot_slo_ms
                )
            stream.t_last = now
            if self.ttft_slo_ms is not None or self.tpot_slo_ms is not None:
                if on_time:
                    stream.good_tokens += 1
                    self._good_total += 1
                    self._m_good.inc()
                else:
                    stream.late_tokens += 1
                    self._late_total += 1
                    self._m_late.inc()
                judged = self._good_total + self._late_total
                self._g_goodput.set(self._good_total / float(judged))
            self._m_tokens.inc()
            finished = (
                len(stream.tokens) >= stream.max_new_tokens
                or (self.eos_token is not None and token == self.eos_token)
            )
            if finished:
                stream.done = True
                stream.t_done = now
                self._retire_locked(stream)
                if slot is None and stream.stream_id in self._slots:
                    slot = self._slots.index(stream.stream_id)
                if slot is not None and self._slots[slot] == stream.stream_id:
                    self._slots[slot] = None
                self._cache.free(stream.stream_id)
            elif slot is not None:
                self._slots[slot] = stream.stream_id
            self._g_inflight.set(
                float(sum(1 for s in self._slots if s is not None))
            )

    def _retire_locked(self, stream: _Stream) -> None:
        """Fold a finished/failed stream's stamps into a bounded record the
        explain surface can fetch after the stream's bookkeeping is gone.
        Caller holds ``self._lock``. Every duration is a same-process
        monotonic difference — valid to combine with the driver's own
        stamps only as durations, never as absolute times."""
        t_first = stream.t_first
        t_done = stream.t_done if stream.t_done is not None else stream.t_last
        rec = {
            "stream_id": stream.stream_id,
            "prompt_tokens": len(stream.prompt),
            "tokens": len(stream.tokens),
            "steps": stream.steps,
            "error": stream.error,
            "trace": stream.ctx[0] if stream.ctx else None,
            "queue_s": max(
                0.0, (stream.t_admit or stream.t_submit) - stream.t_submit
            ),
            "prefill_s": stream.prefill_s,
            "kv_alloc_s": stream.kv_alloc_s,
            "step_compute_s": stream.step_compute_s,
            "churn_s": stream.churn_s,
            "ttft_s": (
                max(0.0, t_first - stream.t_submit)
                if t_first is not None else None
            ),
            "steady_s": (
                max(0.0, t_done - t_first)
                if t_first is not None and t_done is not None else None
            ),
            "wall_s": (
                max(0.0, t_done - stream.t_submit)
                if t_done is not None else None
            ),
            "good_tokens": stream.good_tokens,
            "late_tokens": stream.late_tokens,
        }
        self._records[stream.stream_id] = rec
        self._last_record = rec
        while len(self._records) > _RECORD_KEEP:
            self._records.popitem(last=False)

    def _note_state_throttled(self, min_interval: float = 1.0) -> None:
        """Drop a structured decode-state record into the process flight
        ring (~1/s). The ring ships with EVERY telemetry flush, tracing on
        or off, so a replica SIGKILLed mid-decode leaves its in-flight
        streams, page-table summary, and token counts on the head — the
        decode section of its crash dossier (obs/recorder.py)."""
        now = time.monotonic()
        if now - self._last_state_note < min_interval:
            return
        self._last_state_note = now
        with self._lock:
            inflight = {}
            for sid in self._slots:
                if sid is None:
                    continue
                stream = self._streams.get(sid)
                if stream is None:
                    continue
                try:
                    kv_len = self._cache.length(sid)
                except KeyError:
                    kv_len = 0
                inflight[sid] = {
                    "emitted": len(stream.tokens), "kv_len": kv_len,
                    "prompt": len(stream.prompt),
                }
            state = {
                "inflight": inflight,
                "queued": len(self._pending),
                "pages": {
                    "free": self._cache.free_pages,
                    "total": self._cache.pool_pages,
                    "page_tokens": self._cache.page_tokens,
                },
            }
        from raydp_tpu.obs.recorder import note_log
        from raydp_tpu.obs.tracing import process_role

        note_log("INFO", process_role(), "serve.decode.state", state)

    def _step(self) -> bool:
        """One continuous-batching decode iteration over every occupied
        slot, at the fixed [max_seqs, 1] shape (pad slots masked out)."""
        with self._lock:
            slots = list(self._slots)
            active = [
                (i, self._streams[sid])
                for i, sid in enumerate(slots) if sid is not None
            ]
        if not active:
            return False

        t0 = time.perf_counter()
        seq_ids = [sid if sid is not None else _PAD_SEQ for sid in slots]
        toks = np.zeros((self.max_seqs, 1), np.int32)
        kv_len = np.ones(self.max_seqs, np.int32)
        for i, stream in active:
            toks[i, 0] = stream.tokens[-1]
            kv_len[i] = self._cache.length(stream.stream_id) + 1

        import jax.numpy as jnp

        gathered = self._cache.gather(seq_ids)
        if self.int8_kv:
            k8, ks, v8, vs = gathered
            caches = [
                (jnp.asarray(k8[ly]), jnp.asarray(ks[ly]),
                 jnp.asarray(v8[ly]), jnp.asarray(vs[ly]))
                for ly in range(k8.shape[0])
            ]
        else:
            k, v = gathered
            caches = [
                (jnp.asarray(k[ly]), jnp.asarray(v[ly]))
                for ly in range(k.shape[0])
            ]

        logits, new_kv = self._decode_fn(
            self._params, jnp.asarray(toks), jnp.asarray(kv_len), caches
        )
        logits = np.asarray(logits)

        for i, stream in active:
            k_rows = np.stack(
                [np.asarray(k)[i] for k, _ in new_kv]
            ).astype(np.float32)
            v_rows = np.stack(
                [np.asarray(v)[i] for _, v in new_kv]
            ).astype(np.float32)
            self._cache.append(stream.stream_id, k_rows, v_rows)
            self._emit(stream, int(np.argmax(logits[i, -1])))

        t_end = time.perf_counter()
        step_s = t_end - t0
        # riders are charged round-to-round wall: with active streams the
        # loop runs back-to-back, so anchor→end covers the kernel PLUS the
        # previous round's span/flush bookkeeping and any GIL time the
        # replica's poll handlers stole between rounds — time a rider
        # really spent being served (the kernel-only histograms keep step_s)
        anchor = self._round_anchor
        round_s = t_end - anchor if anchor is not None and anchor <= t0 \
            else step_s
        round_s = max(round_s, step_s)
        self._round_anchor = t_end
        self._m_steps.inc()
        self._h_step.observe(step_s)
        self._h_fill.observe(len(active) / float(self.max_seqs))
        self._h_token.observe(step_s * 1000.0 / len(active))
        with self._lock:
            # every rider perceives the whole round as its token's compute —
            # the "step compute" phase of the time-per-token decomposition
            for _, stream in active:
                stream.step_compute_s += round_s
                stream.steps += 1
        sampled = [s for _, s in active if s.ctx is not None]
        if sampled and _tracing.enabled():
            # ONE fan-in span per round linking the sampled streams riding
            # this batch — the serve.batch shape, decode edition: parented
            # under the first sampled stream, cross-linking the rest by id
            now_wall_us = time.time_ns() // 1000
            first = sampled[0]
            _tracing.record_span(
                "serve.decode.step",
                now_wall_us - int(step_s * 1e6), int(step_s * 1e6),
                trace=first.ctx[0], parent=first.ctx[1],
                streams=len(active), fill=len(active) / float(self.max_seqs),
                stream_spans=[s.ctx[1] for s in sampled],
                stream_traces=[s.ctx[0] for s in sampled],
            )
        from raydp_tpu import obs

        obs.flush_throttled()
        return True
