"""Continuous-batching decode engine: autoregressive serving for
``TransformerLM`` checkpoints.

Orca-style iteration-level scheduling: the engine keeps a fixed number of
decode SLOTS and runs one model step per loop iteration; sequences join a
slot the moment one frees (after a prefill pass that warms their pages in
the ``PagedKVCache``) and leave the moment they finish — no bucket-padded
one-shot batches, no head-of-line blocking behind the longest sequence in
an admission batch. The decode step always runs at the fixed compiled shape
``[max_seqs, 1]`` (empty slots carry a pad sequence and are masked by
``kv_len``), so XLA numerics are bit-stable regardless of which sequences
share a step — the property the SIGKILL-mid-decode chaos gate's
token-identity check rests on.

Determinism contract (docs/serving.md): with a float32 cache, a decode step
is bit-identical to a prefill pass over the same tokens (the kernel-family
parity in ``ops/flash_attention.py``), so a stream resumed on another
replica by RE-PREFILLING prompt + already-emitted tokens continues with
exactly the tokens the dead replica would have produced. Sampling is greedy
(argmax) — deterministic by construction.

Admission is vetoed by the memory-watermark plane: the KV arena lives in
shm where ``mem.pressure`` sees it, and new sequences wait while pressure
exceeds the configured ceiling or the page pool cannot hold their worst
case. In-flight sequences always have their pages reserved up front, so a
step can never die on a full pool.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from raydp_tpu import sanitize
from raydp_tpu.obs import metrics
from raydp_tpu.serve.kvcache import PagedKVCache

_PAD_SEQ = "_pad"


@dataclass
class _Stream:
    stream_id: str
    prompt: List[int]
    max_new_tokens: int
    t_submit: float
    tokens: List[int] = field(default_factory=list)
    done: bool = False
    error: Optional[str] = None
    t_first: Optional[float] = None


class DecodeEngine:
    """One process-local continuous-batching loop over a TransformerLM.

    Standalone-constructible (tests run it without any actor around it);
    ``ModelReplica`` hosts one per process behind ``decode_submit`` /
    ``decode_poll`` RPCs. ``model`` must use a non-collective attention
    impl ("flash" recommended — it is the kernel family ``flash_decode``
    is parity-gated against).
    """

    def __init__(
        self,
        model,
        params,
        *,
        capacity_tokens: int = 512,
        page_tokens: int = 128,
        max_seqs: int = 4,
        max_new_tokens: int = 64,
        int8_kv: bool = False,
        eos_token: Optional[int] = None,
        max_mem_pressure: float = 0.95,
    ):
        self._model = model
        self._params = params
        self.capacity_tokens = int(capacity_tokens)
        self.max_seqs = int(max_seqs)
        self.max_new_tokens_cap = int(max_new_tokens)
        self.int8_kv = bool(int8_kv)
        self.eos_token = eos_token
        self.max_mem_pressure = float(max_mem_pressure)

        head_dim = model.d_model // model.num_heads
        self._cache = PagedKVCache(
            layers=model.num_layers,
            heads=model.num_heads,
            head_dim=head_dim,
            capacity_tokens=self.capacity_tokens,
            page_tokens=int(page_tokens),
            max_seqs=self.max_seqs + 1,  # + the pad sequence's page
            int8=self.int8_kv,
        )
        self._cache.alloc(_PAD_SEQ)
        zero = np.zeros((model.num_layers, model.num_heads, 1, head_dim),
                        np.float32)
        self._cache.append(_PAD_SEQ, zero, zero)

        import jax

        self._prefill_fn = jax.jit(
            lambda p, toks: model.apply(p, toks, return_kv=True)
        )
        self._decode_fn = jax.jit(
            lambda p, toks, kv_len, caches: model.apply(
                p, toks, kv_caches=caches, kv_len=kv_len
            )
        )

        self._lock = sanitize.named_lock("serve.decode", threading.Lock())
        # guarded-by: self._lock
        self._pending: deque = deque()
        self._streams: Dict[str, _Stream] = {}
        self._slots: List[Optional[str]] = [None] * self.max_seqs
        self._ids = itertools.count()
        self._closed = False
        self._wake = threading.Event()

        self._m_tokens = metrics.counter("serve.decode.tokens")
        self._m_steps = metrics.counter("serve.decode.steps")
        self._m_prefills = metrics.counter("serve.decode.prefills")
        self._m_vetoed = metrics.counter("serve.decode.admission_vetoed")
        self._g_inflight = metrics.gauge("serve.decode.inflight")
        self._g_queued = metrics.gauge("serve.decode.queued")
        self._h_fill = metrics.histogram("serve.decode.batch_fill")
        self._h_step = metrics.histogram("serve.decode.step_s")
        self._h_ttft = metrics.histogram("serve.ttft_ms")

        self._thread = threading.Thread(
            target=self._loop, name="serve-decode", daemon=True
        )
        self._thread.start()

    # -- client surface ------------------------------------------------

    def submit(
        self,
        prompt_tokens: Sequence[int],
        max_new_tokens: int,
        stream_id: Optional[str] = None,
    ) -> str:
        """Queue a sequence; returns a stream id to ``poll``. The prompt
        must fit the cache with its worst-case continuation."""
        prompt = [int(t) for t in prompt_tokens]
        max_new = min(int(max_new_tokens), self.max_new_tokens_cap)
        if not prompt:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new > self.capacity_tokens:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new ({max_new}) exceeds "
                f"cache capacity {self.capacity_tokens}"
            )
        with self._lock:
            if self._closed:
                raise RuntimeError("decode engine closed")
            sid = stream_id or f"s{next(self._ids)}"
            if sid in self._streams:
                raise ValueError(f"stream {sid!r} already exists")
            stream = _Stream(sid, prompt, max_new, time.monotonic())
            self._streams[sid] = stream
            self._pending.append(stream)
            self._g_queued.set(float(len(self._pending)))
        self._wake.set()
        return sid

    def poll(self, stream_id: str, cursor: int = 0) -> dict:
        """Tokens emitted at or after ``cursor`` plus terminal state —
        the polling half of the streaming API (request/response-shaped so
        it rides the ordinary actor RPC path)."""
        with self._lock:
            stream = self._streams.get(stream_id)
            if stream is None:
                raise KeyError(f"unknown stream {stream_id!r}")
            out = {
                "tokens": list(stream.tokens[int(cursor):]),
                "done": stream.done,
                "error": stream.error,
            }
            if stream.done:
                # terminal poll retires the bookkeeping once drained
                if int(cursor) + len(out["tokens"]) >= len(stream.tokens):
                    self._streams.pop(stream_id, None)
        return out

    def generate(
        self, prompt_tokens: Sequence[int], max_new_tokens: int,
        timeout: float = 60.0,
    ) -> List[int]:
        """Blocking convenience wrapper: submit + drain one stream."""
        sid = self.submit(prompt_tokens, max_new_tokens)
        deadline = time.monotonic() + timeout
        tokens: List[int] = []
        while True:
            res = self.poll(sid, len(tokens))
            tokens.extend(res["tokens"])
            if res["error"]:
                raise RuntimeError(res["error"])
            if res["done"]:
                return tokens
            if time.monotonic() > deadline:
                raise TimeoutError(f"stream {sid} timed out")
            time.sleep(0.002)

    def stats(self) -> dict:
        with self._lock:
            return {
                "inflight": sum(1 for s in self._slots if s is not None),
                "queued": len(self._pending),
                "streams": len(self._streams),
                "kv_pages_free": self._cache.free_pages,
                "kv_bytes": self._cache.nbytes,
            }

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for stream in self._streams.values():
                if not stream.done:
                    stream.done = True
                    stream.error = "decode engine closed"
            self._pending.clear()
        self._wake.set()
        self._thread.join(timeout=10.0)
        self._cache.close()
        self._g_inflight.set(0.0)
        self._g_queued.set(0.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- engine loop ---------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._closed:
                    return
            try:
                worked = self._admit()
                worked = self._step() or worked
            except Exception as exc:  # noqa: BLE001 - engine must not die silently
                from raydp_tpu import obs

                obs.log.warning("decode engine step failed", exc_info=True)
                self._fail_all(exc)
                return
            if not worked:
                self._wake.wait(0.005)
                self._wake.clear()

    def _fail_all(self, exc: BaseException) -> None:
        with self._lock:
            for stream in self._streams.values():
                if not stream.done:
                    stream.done = True
                    stream.error = f"{type(exc).__name__}: {exc}"
            self._pending.clear()
            self._slots = [None] * self.max_seqs
            self._g_inflight.set(0.0)

    def _mem_pressure(self) -> float:
        try:
            from raydp_tpu.obs.profiler import current_mem_pressure

            return float(current_mem_pressure())
        except Exception:  # raydp-lint: disable=swallowed-exceptions (no samples yet = no veto signal)
            return 0.0

    def _admit(self) -> bool:
        """Move pending sequences into free slots: prefill their prompt at
        the fixed [1, capacity] shape, warm their KV pages, and emit the
        first token. Vetoed (not failed) while the page pool or the
        memory-watermark plane says no."""
        admitted = False
        while True:
            with self._lock:
                if not self._pending:
                    break
                try:
                    slot = self._slots.index(None)
                except ValueError:  # raydp-lint: disable=swallowed-exceptions (no free slot is the normal full-batch state, not an error; admission resumes when a stream retires)
                    break
                stream = self._pending[0]
                worst_case = len(stream.prompt) + stream.max_new_tokens
                if not self._cache.can_admit(worst_case):
                    self._m_vetoed.inc()
                    break
                self._pending.popleft()
                self._g_queued.set(float(len(self._pending)))
            if self._mem_pressure() > self.max_mem_pressure:
                # put it back and stop admitting until pressure drains
                with self._lock:
                    self._pending.appendleft(stream)
                    self._g_queued.set(float(len(self._pending)))
                self._m_vetoed.inc()
                break

            t0 = time.perf_counter()
            prompt = stream.prompt
            length = len(prompt)
            toks = np.zeros((1, self.capacity_tokens), np.int32)
            toks[0, :length] = prompt
            import jax.numpy as jnp

            logits, new_kv = self._prefill_fn(self._params, jnp.asarray(toks))
            logits = np.asarray(logits)
            self._cache.alloc(stream.stream_id)
            k_rows = np.stack(
                [np.asarray(k)[0, :, :length] for k, _ in new_kv]
            ).astype(np.float32)
            v_rows = np.stack(
                [np.asarray(v)[0, :, :length] for _, v in new_kv]
            ).astype(np.float32)
            self._cache.append(stream.stream_id, k_rows, v_rows)
            first = int(np.argmax(logits[0, length - 1]))
            self._m_prefills.inc()
            self._emit(stream, first, slot=slot)
            metrics.histogram("serve.decode.prefill_s").observe(
                time.perf_counter() - t0
            )
            admitted = True
        return admitted

    def _emit(self, stream: _Stream, token: int, slot: Optional[int] = None) -> None:
        now = time.monotonic()
        with self._lock:
            stream.tokens.append(int(token))
            if stream.t_first is None:
                stream.t_first = now
                self._h_ttft.observe((now - stream.t_submit) * 1000.0)
            self._m_tokens.inc()
            finished = (
                len(stream.tokens) >= stream.max_new_tokens
                or (self.eos_token is not None and token == self.eos_token)
            )
            if finished:
                stream.done = True
                if slot is None and stream.stream_id in self._slots:
                    slot = self._slots.index(stream.stream_id)
                if slot is not None and self._slots[slot] == stream.stream_id:
                    self._slots[slot] = None
                self._cache.free(stream.stream_id)
            elif slot is not None:
                self._slots[slot] = stream.stream_id
            self._g_inflight.set(
                float(sum(1 for s in self._slots if s is not None))
            )

    def _step(self) -> bool:
        """One continuous-batching decode iteration over every occupied
        slot, at the fixed [max_seqs, 1] shape (pad slots masked out)."""
        with self._lock:
            slots = list(self._slots)
            active = [
                (i, self._streams[sid])
                for i, sid in enumerate(slots) if sid is not None
            ]
        if not active:
            return False

        t0 = time.perf_counter()
        seq_ids = [sid if sid is not None else _PAD_SEQ for sid in slots]
        toks = np.zeros((self.max_seqs, 1), np.int32)
        kv_len = np.ones(self.max_seqs, np.int32)
        for i, stream in active:
            toks[i, 0] = stream.tokens[-1]
            kv_len[i] = self._cache.length(stream.stream_id) + 1

        import jax.numpy as jnp

        gathered = self._cache.gather(seq_ids)
        if self.int8_kv:
            k8, ks, v8, vs = gathered
            caches = [
                (jnp.asarray(k8[ly]), jnp.asarray(ks[ly]),
                 jnp.asarray(v8[ly]), jnp.asarray(vs[ly]))
                for ly in range(k8.shape[0])
            ]
        else:
            k, v = gathered
            caches = [
                (jnp.asarray(k[ly]), jnp.asarray(v[ly]))
                for ly in range(k.shape[0])
            ]

        logits, new_kv = self._decode_fn(
            self._params, jnp.asarray(toks), jnp.asarray(kv_len), caches
        )
        logits = np.asarray(logits)

        for i, stream in active:
            k_rows = np.stack(
                [np.asarray(k)[i] for k, _ in new_kv]
            ).astype(np.float32)
            v_rows = np.stack(
                [np.asarray(v)[i] for _, v in new_kv]
            ).astype(np.float32)
            self._cache.append(stream.stream_id, k_rows, v_rows)
            self._emit(stream, int(np.argmax(logits[i, -1])))

        step_s = time.perf_counter() - t0
        self._m_steps.inc()
        self._h_step.observe(step_s)
        self._h_fill.observe(len(active) / float(self.max_seqs))
        metrics.histogram("serve.decode.token_ms").observe(
            step_s * 1000.0 / len(active)
        )
        from raydp_tpu import obs

        obs.flush_throttled()
        return True
