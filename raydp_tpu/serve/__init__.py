"""raydp_tpu.serve — the online serving plane.

The first workload in this framework that carries a REQUEST path instead of
a batch job: model replica actors (zygote-warm-forked) load a
``JaxEstimator`` checkpoint through the estimator's inference loading path
and hold AOT-compiled inference jits per (model fingerprint, batch bucket);
a dynamic batcher drains an admission queue into size- or deadline-triggered
bucket-padded batches dispatched over the doorbell UDS fast path; an
SLO-aware controller heals dead replicas and (optionally) autoscales on
sustained queue-depth/latency gauges; and failover is ZERO-DROP — a request
whose replica is SIGKILLed mid-flight is re-admitted and re-served
(inference is pure, so re-execution is byte-safe per batch bucket).

Quick start::

    est.fit_on_etl(train_df)                 # writes checkpoint_dir
    dep = raydp_tpu.serve.deploy(est, replicas=2, example=row)
    pred = dep.predict(feature_rows)          # thread-safe, blocking
    dep.reload()                              # rolling checkpoint reload
    dep.close()                               # before cluster shutdown

Decode-native serving (``serve.decode.*`` conf keys) adds a second request
shape: autoregressive token streams. Each replica hosts a continuous-batching
``DecodeEngine`` (iteration-level scheduling over a paged, shm-backed KV
cache; ``serve/decode.py``) and the deployment exposes
``stream(prompt_tokens, max_new)`` / ``generate(...)`` with zero-drop
failover — a stream whose replica dies is re-prefilled on a survivor and
continues bit-identically (f32 cache).

See docs/serving.md for the conf table (``serve.*`` keys), the failover
semantics, and the observability rows.
"""

from __future__ import annotations

from raydp_tpu.serve.batcher import DynamicBatcher
from raydp_tpu.serve.config import ServeConf
from raydp_tpu.serve.decode import DecodeEngine
from raydp_tpu.serve.deployment import Deployment, deploy
from raydp_tpu.serve.kvcache import KVCacheFull, PagedKVCache
from raydp_tpu.serve.replica import ModelReplica, ReplicaSpec

__all__ = [
    "DecodeEngine",
    "Deployment",
    "DynamicBatcher",
    "KVCacheFull",
    "ModelReplica",
    "PagedKVCache",
    "ReplicaSpec",
    "ServeConf",
    "deploy",
]
