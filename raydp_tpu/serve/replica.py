"""Model replica actors: the serving plane's unit of capacity.

A replica is an ordinary cluster actor (zygote-warm-forked like every light
actor — set ``RAYDP_TPU_ZYGOTE_WARM_JAX=1`` before the first ``cluster.init``
on a machine to bake the jax/flax/orbax import set into the fork template and
make replica spin-up fork-bound) that

- loads a ``JaxEstimator`` checkpoint through the estimator's INFERENCE
  loading path (``load_latest_checkpoint``: params only, no optimizer state,
  nothing fit-oriented),
- holds an AOT-compiled inference jit per (model fingerprint, batch-shape
  bucket) — the exact executor-resident-program shape of the PR 6 compiled
  ETL plane: the batcher pads every dispatch to a configured bucket, so the
  cache stays small and every bucket's numerics are bit-stable (XLA lowers
  per shape; at a FIXED shape per-row results are independent of batch
  composition, which is what makes kill/no-kill byte-identity gates honest),
- swaps (fingerprint, params, compiled-cache) ATOMICALLY on ``reload``: the
  old jit serves every in-flight and concurrent request until the new
  weights are restored AND compiled warm, so a rolling checkpoint reload
  never serves half-loaded state.

Inference is pure and stateless between requests — a re-dispatched request
(replica SIGKILLed mid-flight) recomputes the identical answer, which is the
whole basis of the batcher's zero-drop re-admission.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

import numpy as np

from raydp_tpu.exchange.features import f0, fmap


@dataclass
class ReplicaSpec:
    """Everything a replica process needs to build its model and serve it.
    Travels cloudpickled inside the actor spawn spec; deliberately holds NO
    trained weights — the checkpoint directory is the weight channel, which
    is what makes rolling reload and post-crash respawn trivially correct."""

    model: Any  # flax Module instance or zero-arg creator fn
    checkpoint_dir: str
    buckets: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64)
    # optional example feature row(s): lets the replica AOT-compile every
    # bucket at load time (boot and reload both), so no request ever pays a
    # compile. Without it buckets compile lazily on first use.
    example: Any = None
    name: str = "default"
    extra_estimator_kwargs: dict = field(default_factory=dict)
    # DecodeEngine kwargs (serve/decode.py) — non-empty enables the
    # decode_submit/decode_poll streaming surface on each replica
    decode: dict = field(default_factory=dict)


class _ModelState:
    """One immutable generation of servable state. ``infer`` reads the
    replica's ``_active`` reference once and works off this object alone, so
    a concurrent reload (which builds a whole new _ModelState and swaps the
    reference) can never expose a torn view."""

    __slots__ = (
        "fingerprint", "epoch", "step", "params", "jitted", "compiled",
        "flops",
    )

    def __init__(self, fingerprint, epoch, step, params, jitted):
        self.fingerprint = fingerprint
        self.epoch = epoch
        self.step = step
        self.params = params
        self.jitted = jitted
        self.compiled = {}  # shape key -> AOT-compiled executable
        self.flops = {}  # shape key -> XLA-reported FLOPs per call (or None)

    def _shape_key(self, x):
        if isinstance(x, tuple):
            return tuple((a.shape, str(a.dtype)) for a in x)
        return ((x.shape, str(x.dtype)),)

    # with dynamic batching ON the shape set is exactly the bucket ladder;
    # OFF dispatches raw request shapes — bound the cache so an adversarial
    # shape stream cannot grow it without limit (PR 6's executor program
    # cache makes the same call, LRU 32)
    MAX_COMPILED = 32

    def compiled_for(self, x):
        """The AOT executable for this batch's exact shapes, compiling on
        miss. Lock-free: two threads racing the same miss both compile and
        one wins the dict slot — wasteful once, never wrong."""
        key = self._shape_key(x)
        fn = self.compiled.get(key)
        if fn is None:
            import jax

            from raydp_tpu import obs

            def sds(a):
                return jax.ShapeDtypeStruct(a.shape, a.dtype)

            with obs.span("serve.replica_compile", bucket=int(len(f0(x)))):
                fn = self.jitted.lower(
                    jax.tree.map(sds, self.params), fmap(sds, x)
                ).compile()
            obs.metrics.counter("serve.replica.compiles").inc()
            # FLOP-account the new executable HERE, inside the one-time
            # compile path (boot warm / first-touch): cost_analysis() is
            # not free, and charging it to the first REQUEST per bucket
            # puts a one-off spike straight into that request's latency —
            # at bench request counts those few spikes ARE the p99
            from raydp_tpu.obs.costmodel import step_flops_from_compiled

            self.flops[key] = step_flops_from_compiled(fn)
            while len(self.compiled) >= self.MAX_COMPILED:
                try:
                    evicted = next(iter(self.compiled))
                    self.compiled.pop(evicted, None)
                    self.flops.pop(evicted, None)
                except (StopIteration, RuntimeError):  # raydp-lint: disable=swallowed-exceptions (a racing evictor emptied/mutated the dict first; the cache is already under its bound)
                    break
            self.compiled[key] = fn
        return fn

    def flops_for(self, x):
        """XLA's per-call FLOP count for this batch shape (None when the
        backend doesn't report, or before the shape's compile recorded
        it) — the numerator of the live serve.mfu gauge. A pure cache
        read: the request path must never pay the analysis."""
        return self.flops.get(self._shape_key(x))


_PEAK_FLOPS = None


def _device_peak():
    """Cached peak FLOP/s of this replica's device (obs/costmodel.py table;
    None when unknown — the mfu gauge then simply never moves)."""
    global _PEAK_FLOPS
    if _PEAK_FLOPS is None:
        from raydp_tpu.obs.costmodel import device_peak_flops

        _PEAK_FLOPS = device_peak_flops()
    return _PEAK_FLOPS.get("peak")


class ModelReplica:
    """The actor class. Spawned with ``max_concurrency >= 2`` so ``reload``
    (and health probes) proceed while ``infer`` traffic is in flight."""

    def __init__(self, spec: ReplicaSpec):
        self._spec = spec
        self._active: Optional[_ModelState] = None
        # serializes reloads only — infer never takes it (infer reads the
        # _active reference, which swaps atomically)
        from raydp_tpu import sanitize

        self._reload_lock = sanitize.named_lock(
            "serve.replica_reload", threading.Lock()
        )
        # lazy decode engine (serve/decode.py): built on the first
        # decode_submit so non-streaming deployments pay nothing
        self._decode = None
        self._decode_lock = sanitize.named_lock(
            "serve.replica_decode", threading.Lock()
        )
        from raydp_tpu.estimator.jax_estimator import JaxEstimator

        self._est = JaxEstimator(
            model=spec.model,
            checkpoint_dir=spec.checkpoint_dir,
            **dict(spec.extra_estimator_kwargs),
        )
        self._load()  # a replica is never "up but weightless"

    # -- lifecycle -----------------------------------------------------

    def _load(self) -> dict:
        """Restore the newest committed checkpoint and build a fresh
        generation, warming the configured buckets BEFORE the swap: until
        the new state is compiled, ``self._active`` (the old weights) keeps
        serving — the rolling-reload contract."""
        import jax

        from raydp_tpu import obs

        with self._reload_lock:
            epoch, step = self._est.load_latest_checkpoint()
            fingerprint = hashlib.blake2b(
                f"{self._spec.checkpoint_dir}:{epoch}:{step}".encode(),
                digest_size=8,
            ).hexdigest()
            state = _ModelState(
                fingerprint, epoch, step, self._est._params,
                jax.jit(self._est._module.apply),
            )
            if self._spec.example is not None:
                from raydp_tpu.exchange.features import (
                    as_feature_rows,
                    pad_rows,
                )

                rows = as_feature_rows(self._spec.example)
                for bucket in self._spec.buckets:
                    if int(bucket) >= len(f0(rows)):
                        state.compiled_for(pad_rows(rows, int(bucket)))
            self._active = state  # the atomic swap: new weights go live here
            # a live decode engine holds the OLD params captured in its
            # jits — retire it; the next decode_submit rebuilds against
            # the new generation (in-flight streams fail-fast and the
            # client re-prefills, same as a replica death)
            with self._decode_lock:
                stale, self._decode = self._decode, None
            if stale is not None:
                stale.close()
            obs.metrics.counter("serve.replica.reloads").inc()
            obs.flush_throttled()
            return self.info()

    def infer(self, x, n_valid: int):
        """Run the batch through the active generation and return
        ``(rows, compute_s)``: the FIRST ``n_valid`` prediction rows as host
        numpy — padded rows are sliced off server-side, so they cannot leak
        into any response — plus the measured compute seconds (the batcher's
        per-stage latency decomposition and the dispatch-vs-compute split in
        request traces both read it). The ``serve.replica_infer`` span
        parents under the dispatching batch's trace context, which rode in
        on the RPC frame — the replica-side hop of a sampled request
        trace."""
        import time as _time

        from raydp_tpu import obs

        state = self._active
        with obs.span(
            "serve.replica_infer", rows=int(n_valid),
            fingerprint=state.fingerprint,
        ):
            fn = state.compiled_for(x)
            t0 = _time.perf_counter()
            out = np.asarray(fn(state.params, x))[: int(n_valid)]
            compute_s = _time.perf_counter() - t0
        obs.metrics.counter("serve.replica.infers").inc()
        obs.metrics.counter("serve.replica.rows").inc(int(n_valid))
        obs.metrics.histogram("serve.replica.compute_s").observe(compute_s)
        # live serving MFU: XLA-reported FLOPs of this exact compiled shape
        # over measured compute, against the device's table peak — the
        # serving-plane twin of the estimator's fit-loop mfu gauge
        flops = state.flops_for(x)
        peak = _device_peak()
        if flops and peak and compute_s > 0:
            obs.metrics.gauge("serve.mfu").set(flops / compute_s / peak)
        obs.flush_throttled()
        return out, compute_s

    def reload(self) -> dict:
        """Pick up the newest checkpoint (rolling reload entry point). Old
        weights serve until the new generation is restored and warm."""
        return self._load()

    # -- decode serving (docs/serving.md, "Decode serving") ------------

    def _decode_engine(self):
        engine = self._decode
        if engine is not None:
            return engine
        with self._decode_lock:
            if self._decode is None:
                from raydp_tpu.serve.decode import DecodeEngine

                state = self._active
                self._decode = DecodeEngine(
                    self._est._module, state.params,
                    **dict(self._spec.decode or {}),
                )
            return self._decode

    def decode_submit(
        self, prompt_tokens, max_new_tokens: int, stream_id=None,
        trace_ctx=None,
    ) -> str:
        """Queue an autoregressive generation on this replica's
        continuous-batching engine; returns the stream id to poll.
        ``trace_ctx`` is a sampled stream's (trace_id, root_span_id) —
        the engine's prefill + step fan-in spans parent under it, the
        replica-side hop of one stream trace."""
        return self._decode_engine().submit(
            prompt_tokens, max_new_tokens, stream_id, trace_ctx=trace_ctx
        )

    def decode_poll(self, stream_id: str, cursor: int = 0) -> dict:
        """Tokens at/after ``cursor`` plus terminal state for a stream."""
        return self._decode_engine().poll(stream_id, cursor)

    def decode_stats(self) -> dict:
        engine = self._decode
        return engine.stats() if engine is not None else {}

    def decode_explain(self, stream_id=None):
        """The engine-kept timing record for one retired stream (newest by
        default) — fetched by ``deployment.explain_last_stream()``; works
        with tracing off. None when the engine never ran or the record
        aged out."""
        engine = self._decode
        return engine.explain(stream_id) if engine is not None else None

    def warm(self, example) -> int:
        """Precompile every configured bucket for ``example``'s row shape;
        returns the number of compiled entries in the active generation."""
        from raydp_tpu.exchange.features import as_feature_rows, pad_rows

        state = self._active
        rows = as_feature_rows(example)
        for bucket in self._spec.buckets:
            if int(bucket) >= len(f0(rows)):
                state.compiled_for(pad_rows(rows, int(bucket)))
        return len(state.compiled)

    def profile(self, payload=None, out_dir: Optional[str] = None) -> dict:
        """On-demand compute capture of ONE warm inference (the serve
        plane's half of the compute observatory, obs/profiler.py): run
        ``payload`` (default: the deployment's warm ``example``) through
        the active generation under a capture window — ``jax.profiler``
        deep trace when the backend supports it, span-only otherwise —
        and return the capture summary + measured compute. The capture
        runs in THIS replica process; artifacts land in its ``artifacts/``
        dir (``RAYDP_TPU_ARTIFACTS_DIR`` routes them)."""
        import time as _time

        from raydp_tpu.exchange.features import as_feature_rows, pad_rows
        from raydp_tpu.obs.profiler import capture

        source = payload if payload is not None else self._spec.example
        if source is None:
            raise ValueError(
                "profile() needs a payload (deployment has no example=)"
            )
        from raydp_tpu import obs

        from raydp_tpu.exchange.features import f_slice

        rows = as_feature_rows(source)
        # route through the batcher's bucket shapes (pad to the smallest
        # fitting bucket; an oversized payload is TRUNCATED to the largest
        # — the serving path only ever runs bucket shapes, and a raw shape
        # must not compile into the bucket-keyed cache of a live replica)
        # and warm OUTSIDE the window: the capture must show one
        # steady-state inference, not an XLA compile
        n_rows = len(f0(rows))
        if self._spec.buckets:
            fitting = [
                int(b) for b in self._spec.buckets if int(b) >= n_rows
            ]
            if fitting:
                rows = pad_rows(rows, min(fitting))
            else:
                largest = max(int(b) for b in self._spec.buckets)
                rows = f_slice(rows, 0, largest)
                n_rows = largest
        state = self._active
        fn = state.compiled_for(rows)
        np.asarray(fn(state.params, rows))  # uncaptured warm-up call
        with capture(out_dir=out_dir) as cap:
            # a real span inside the window: the span-only fallback arm
            # captures at least the inference interval it exists to show
            with obs.span("serve.replica_profile",
                          fingerprint=state.fingerprint):
                t0 = _time.perf_counter()
                np.asarray(fn(state.params, rows))
                compute_s = _time.perf_counter() - t0
        result = cap.result()
        result.update({
            "compute_ms": round(compute_s * 1000.0, 3),
            "rows": n_rows,
            "batch_rows": len(f0(rows)),  # the bucket shape actually run
            "fingerprint": state.fingerprint,
        })
        return result

    def info(self) -> dict:
        import os

        state = self._active
        return {
            "name": self._spec.name,
            "pid": os.getpid(),
            "fingerprint": state.fingerprint if state else None,
            "epoch": state.epoch if state else None,
            "step": state.step if state else None,
            "buckets_compiled": len(state.compiled) if state else 0,
        }
