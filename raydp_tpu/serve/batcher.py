"""Dynamic batcher: per-deployment admission queue -> bucket-padded batches
-> replica dispatch over the doorbell UDS fast path.

Requests enter ``submit`` (any number of rows, up to the max batch size) and
park on per-request events. A drain thread forms batches on two triggers —
SIZE (enough queued rows to fill the largest bucket) or DEADLINE (the oldest
queued request has waited ``serve.batch_deadline_ms``) — pops whole requests,
and hands each batch to a small dispatcher pool. Dispatchers concatenate the
rows (exchange/features.py is the one row-accounting implementation), pad to
the nearest bucket, pick the least-loaded live replica, and send one
``infer`` actor call; actor dispatch rides the PR 6 doorbell pooled sockets
automatically, so a warm request costs zero connect/handshake round trips.

Zero-drop failover: inference is pure and idempotent, so a dispatch that
dies with its replica (SIGKILL mid-flight, connection reset, actor DEAD) is
RE-ADMITTED at the FRONT of the queue and re-served by a surviving replica —
callers never see the failure unless a request exhausts
``serve.max_retries``. Replica exceptions that are NOT transport failures
(a bad payload) resolve straight to the caller: retrying a deterministic
error forever would hang the client.

Lock discipline (the blocking-under-lock rule): the condition guards queue
and replica-table state only; every RPC, result wait, and pad/concat runs
OUTSIDE it in the dispatcher threads.
"""

from __future__ import annotations

import random as _random
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

from raydp_tpu import sanitize
from raydp_tpu.cluster.common import ClusterError
from raydp_tpu.exchange.features import (
    as_feature_rows,
    f_concat,
    f_rows,
    f_slice,
    pad_rows,
)

# transport-shaped dispatch failures: the request was (possibly) in flight on
# a replica that died or a socket that reset — re-admission is always safe
# because inference is pure
_RETRYABLE = (ClusterError, ConnectionError, EOFError, OSError, TimeoutError)


class _Request:
    __slots__ = ("rows", "n", "done", "value", "error", "retries",
                 "t_enqueue", "t_formed", "t_dispatch", "t_reply", "ctx")

    def __init__(self, rows, n: int):
        self.rows = rows
        self.n = n
        self.done = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None
        self.retries = 0
        self.t_enqueue = time.monotonic()
        # request-path tracing (docs/observability.md "Request traces"):
        # stage stamps are taken for EVERY request (three monotonic reads —
        # they feed the serve.stage.* histograms behind stats()'s latency
        # decomposition); ctx is a minted (trace_id, span_id) for SAMPLED
        # requests only, whose spans are emitted at resolution
        self.t_formed: Optional[float] = None
        self.t_dispatch: Optional[float] = None
        self.t_reply: Optional[float] = None
        self.ctx: Optional[tuple] = None

    def resolve(self, value) -> None:
        self.value = value
        self.done.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.done.set()

    def result(self, timeout: Optional[float] = None):
        if not self.done.wait(timeout):
            raise TimeoutError("serving request timed out")
        if self.error is not None:
            raise self.error
        return self.value


class _ChunkAssembly:
    """Reassembles one oversized request served as several bucket-shaped
    dispatches. Parts resolve independently (possibly on different replicas,
    possibly after re-admission); the parent resolves once every part has,
    with the rows concatenated back in order. Any part failing terminally
    fails the parent — partial results never reach a caller."""

    def __init__(self, parent: _Request):
        self.parent = parent
        self.lock = threading.Lock()
        self.results: List = []
        self.remaining = 0

    def arm(self, n_parts: int) -> None:
        self.results = [None] * n_parts
        self.remaining = n_parts

    def part_resolved(self, index: int, value) -> None:
        with self.lock:
            if self.parent.done.is_set():
                return
            self.results[index] = value
            self.remaining -= 1
            ready = self.remaining == 0
        if ready:
            self.parent.resolve(f_concat(self.results))

    def part_failed(self, error: BaseException) -> None:
        self.parent.fail(error)


class _ChunkPart(_Request):
    """One bucket-sized slice of an oversized request. Behaves exactly like
    a request on the dispatch/requeue path (it can be re-admitted on replica
    failure like any other), but resolution routes through the assembly."""

    __slots__ = ("assembly", "index")

    def __init__(self, rows, n: int, assembly: _ChunkAssembly, index: int):
        super().__init__(rows, n)
        self.assembly = assembly
        self.index = index

    def resolve(self, value) -> None:
        super().resolve(value)
        self.assembly.part_resolved(self.index, value)

    def fail(self, error: BaseException) -> None:
        super().fail(error)
        self.assembly.part_failed(error)


class DynamicBatcher:
    def __init__(
        self,
        conf,
        feature_columns=None,
        on_replica_failure: Optional[Callable] = None,
        admission=None,
    ):
        self._conf = conf
        self._feature_columns = feature_columns
        self._on_replica_failure = on_replica_failure
        # fair-share admission (tenancy/scheduler.py): when the deployment
        # names a tenant (``serve.tenant`` conf), every batch dispatch
        # acquires one admission ticket from the SAME weighted-DRR queue the
        # tenant's ETL stages use — serving and ETL share one quota, and a
        # co-tenant cannot starve this deployment. None = unthrottled.
        self._admission = admission
        self._cond = threading.Condition(
            sanitize.named_lock("serve.queue", threading.Lock())
        )
        # guarded-by: self._cond
        self._queue: deque = deque()
        self._queued_rows = 0
        self._replicas: Dict[str, object] = {}  # actor_id -> handle
        self._draining: set = set()
        self._failed: set = set()
        self._inflight: Dict[str, int] = {}
        self._rr = 0  # round-robin tiebreak among equally-loaded replicas
        self._stop = False
        # recent completion latencies (ms) for the SLO gauges the autoscaler
        # reads; cumulative shape lives in the serve.request_latency_s
        # histogram (now with reservoir p50/p99)
        self._latency_window: deque = deque(maxlen=256)

        from raydp_tpu import obs

        m = obs.metrics
        self._m_requests = m.counter("serve.requests")
        self._m_rows = m.counter("serve.rows")
        self._m_batches = m.counter("serve.batches")
        self._m_padded = m.counter("serve.padded_rows")
        self._m_chunked = m.counter("serve.chunked_dispatches")
        self._m_requeued = m.counter("serve.requeued_requests")
        self._m_dropped = m.counter("serve.dropped_requests")
        self._m_errors = m.counter("serve.dispatch_errors")
        self._m_doorbell = m.counter("serve.doorbell_pooled")
        self._m_fill = m.histogram("serve.batch_fill")
        self._m_latency = m.histogram("serve.request_latency_s")
        self._g_queue = m.gauge("serve.queue_depth")
        self._g_inflight = m.gauge("serve.inflight")
        self._g_p99 = m.gauge("serve.p99_ms")
        # per-stage latency decomposition (every request feeds these; the
        # serve.request trace spans are the sampled mirror of the same
        # stamps): queue_wait = admission→batch pop, batch_form =
        # pop→dispatch send (concat/pad/admission ticket), dispatch =
        # send→reply minus replica compute, compute = replica-reported,
        # respond = reply→caller resolution
        self._h_stages = {
            stage: m.histogram(f"serve.stage.{stage}_s")
            for stage in (
                "queue_wait", "batch_form", "dispatch", "compute", "respond",
            )
        }
        # request-trace sampling (obs.request_sample_rate): spans only ship
        # when tracing is enabled; the rate keeps a 650 req/s closed loop
        # from drowning the span rings
        self._sample_rate = float(getattr(conf, "request_sample_rate", 0.0))

        self._dispatch_slots = threading.Semaphore(conf.dispatchers)
        self._pool = ThreadPoolExecutor(
            max_workers=conf.dispatchers, thread_name_prefix="serve-dispatch"
        )
        self._drain_thread = threading.Thread(
            target=self._drain_loop, name="serve-batcher", daemon=True
        )
        self._drain_thread.start()

    # -- replica membership (called by the deployment/controller) -------

    def add_replica(self, handle) -> None:
        with self._cond:
            self._replicas[handle.actor_id] = handle
            self._inflight.setdefault(handle.actor_id, 0)
            self._failed.discard(handle.actor_id)
            self._cond.notify_all()

    def remove_replica(
        self, actor_id: str, drain: bool = True, timeout: float = 30.0
    ) -> bool:
        """Stop dispatching to a replica; with ``drain`` wait (bounded) for
        its in-flight batches to complete before dropping it — the graceful
        scale-in contract. Returns True when the replica left with zero
        requests in flight."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._draining.add(actor_id)
            while drain and self._inflight.get(actor_id, 0) > 0:
                if time.monotonic() > deadline:
                    break
                self._cond.wait(0.05)
            clean = self._inflight.get(actor_id, 0) == 0
            self._replicas.pop(actor_id, None)
            self._inflight.pop(actor_id, None)
            self._draining.discard(actor_id)
            self._failed.discard(actor_id)
        return clean

    def live_replicas(self) -> List[str]:
        with self._cond:
            return [
                rid for rid in self._replicas
                if rid not in self._draining and rid not in self._failed
            ]

    def failed_ids(self) -> List[str]:
        """Replica ids a dispatcher flagged after a transport failure —
        the controller's heal pass confirms with the head (DEAD: replace;
        ALIVE: the failure was transient, re-admit via add_replica)."""
        with self._cond:
            return list(self._failed)

    # -- client surface -------------------------------------------------

    def submit(self, payload) -> _Request:
        rows = as_feature_rows(payload, feature_columns=self._feature_columns)
        n = f_rows(rows)
        if n == 0:
            raise ValueError("empty serving request")
        if n > self._conf.max_batch_size:
            raise ValueError(
                f"request of {n} rows exceeds serve.max_batch_size="
                f"{self._conf.max_batch_size}"
            )
        req = _Request(rows, n)
        if self._sample_rate > 0.0:
            from raydp_tpu.obs import tracing as _tracing

            if _tracing.enabled() and (
                self._sample_rate >= 1.0
                or _random.random() < self._sample_rate
            ):
                req.ctx = _tracing.mint_context()
        with self._cond:
            if self._stop:
                raise RuntimeError("serving deployment is closed")
            self._queue.append(req)
            self._queued_rows += n
            depth = self._queued_rows
            self._cond.notify_all()
        self._m_requests.inc()
        self._m_rows.inc(n)
        self._g_queue.set(depth)
        return req

    def predict(self, payload, timeout: Optional[float] = None):
        return self.submit(payload).result(
            timeout if timeout is not None else self._conf.request_timeout_s * 2
        )

    # -- internals ------------------------------------------------------

    def _pop_batch_locked(self) -> List[_Request]:
        """Pop whole requests up to the largest bucket's row budget (exactly
        one request with dynamic batching off). guarded-by: self._cond held"""
        budget = (
            self._conf.max_batch_size if self._conf.dynamic_batching else 0
        )
        batch: List[_Request] = [self._queue.popleft()]
        taken = batch[0].n
        while (
            self._queue
            and self._conf.dynamic_batching
            and taken + self._queue[0].n <= budget
        ):
            req = self._queue.popleft()
            taken += req.n
            batch.append(req)
        self._queued_rows -= taken
        return batch

    def _has_candidate_locked(self) -> bool:
        # guarded-by: self._cond held
        return any(
            rid not in self._draining and rid not in self._failed
            for rid in self._replicas
        )

    def _drain_loop(self) -> None:
        conf = self._conf
        while True:
            # backpressure: a dispatch slot is claimed BEFORE forming a
            # batch, so under overload requests accumulate in the admission
            # queue (where size-triggered batches fill properly) instead of
            # exploding into half-full batches parked on the pool queue
            if not self._dispatch_slots.acquire(timeout=0.05):
                with self._cond:
                    if self._stop and not self._queue:
                        return
                continue
            batch: List[_Request] = []
            with self._cond:
                while True:
                    if self._stop and not self._queue:
                        self._dispatch_slots.release()
                        return
                    if self._queue and self._has_candidate_locked():
                        age_ms = (
                            time.monotonic() - self._queue[0].t_enqueue
                        ) * 1000.0
                        if (
                            not conf.dynamic_batching
                            or self._stop
                            or self._queued_rows >= conf.max_batch_size
                            or age_ms >= conf.batch_deadline_ms
                        ):
                            batch = self._pop_batch_locked()
                            break
                        wait_s = min(
                            (conf.batch_deadline_ms - age_ms) / 1000.0, 0.05
                        )
                    else:
                        wait_s = 0.05
                    self._cond.wait(max(wait_s, 0.001))
                depth = self._queued_rows
            self._g_queue.set(depth)
            t_formed = time.monotonic()
            for req in batch:
                req.t_formed = t_formed
            self._pool.submit(self._dispatch, batch)

    def _pick_replica(self):
        with self._cond:
            candidates = [
                rid for rid in self._replicas
                if rid not in self._draining and rid not in self._failed
            ]
            if not candidates:
                return None
            self._rr += 1
            best = min(
                candidates,
                key=lambda rid: (self._inflight.get(rid, 0),
                                 (self._rr + hash(rid)) % len(candidates)),
            )
            self._inflight[best] = self._inflight.get(best, 0) + 1
            handle = self._replicas[best]
            total = sum(self._inflight.values())
        self._g_inflight.set(total)
        return handle

    def _release_replica(self, actor_id: str) -> None:
        with self._cond:
            if actor_id in self._inflight:
                self._inflight[actor_id] = max(
                    0, self._inflight[actor_id] - 1
                )
            self._cond.notify_all()  # drain waiters watch in-flight counts

    def _requeue_front(self, batch: List[_Request], charge_retry: bool,
                       error: Optional[BaseException]) -> None:
        """Re-admit a failed batch's requests at the queue FRONT (their
        deadline clock keeps running from original admission). Requests out
        of retries resolve the error to their caller instead."""
        survivors: List[_Request] = []
        for req in batch:
            if charge_retry:
                req.retries += 1
            if error is not None and req.retries > self._conf.max_retries:
                req.fail(error)
                self._m_dropped.inc()
            else:
                survivors.append(req)
        if not survivors:
            return
        with self._cond:
            stopped = self._stop
            if not stopped:
                for req in reversed(survivors):
                    self._queue.appendleft(req)
                    self._queued_rows += req.n
                self._cond.notify_all()
        if stopped:
            # close() already cleared the queue and the drain thread is
            # gone — re-admitting here would strand these callers until
            # their predict timeout; fail fast like every pending request
            closed = RuntimeError("serving deployment closed")
            for req in survivors:
                req.fail(closed)
            return
        if charge_retry:
            self._m_requeued.inc(len(survivors))

    def _dispatch(self, batch: List[_Request]) -> None:
        try:
            self._dispatch_inner(batch)
        except BaseException as exc:  # noqa: BLE001 - backstop: no request may strand
            # a dispatch bug must never leave a caller parked on an event
            # that nobody will set (the pool future would swallow this)
            for req in batch:
                if not req.done.is_set():
                    req.fail(exc)
            from raydp_tpu import obs

            obs.log.error("serve dispatch failed unexpectedly",
                          exc_info=True)
        finally:
            self._dispatch_slots.release()

    def _dispatch_inner(self, batch: List[_Request]) -> None:
        conf = self._conf
        # form the batch BEFORE claiming a replica: a formation error
        # (mixed payload containers, a misconfigured bucket ladder) then
        # fails the requests without ever inflating a replica's in-flight
        # count
        try:
            rows = (
                batch[0].rows if len(batch) == 1
                else f_concat([r.rows for r in batch])
            )
            n = sum(r.n for r in batch)
            chunk_to = None
            padded = None
            if conf.dynamic_batching:
                bucket = next((b for b in conf.buckets if b >= n), None)
                if bucket is None and conf.buckets:
                    # oversized payload (a hand-built ladder whose largest
                    # bucket is below max_batch_size): chunk it to the
                    # largest bucket — a raw shape must never compile into
                    # a live replica's bucket-keyed cache (the same hazard
                    # replica.profile() truncates against)
                    chunk_to = max(conf.buckets)
                else:
                    # a resolve()d ladder always contains max_batch_size;
                    # an empty hand-built one falls back to no padding
                    bucket = bucket if bucket is not None else n
                    padded = pad_rows(rows, bucket)
                    self._m_padded.inc(bucket - n)
                    self._m_fill.observe(n / bucket)
            else:
                padded = rows
                self._m_fill.observe(1.0)
        except Exception as exc:
            self._m_errors.inc()
            for req in batch:
                req.fail(exc)
            return
        ticket = None
        if self._admission is not None:
            from raydp_tpu.tenancy.scheduler import TenantQuotaError

            try:
                # bounded by the request timeout: a tenant parked behind a
                # co-tenant's backlog is backpressure (the dispatcher thread
                # waits, requests fill the admission queue); a wait that
                # outlives the request budget resolves the TYPED quota
                # error to the callers instead of wedging the queue
                ticket = self._admission.acquire(
                    1, timeout_s=conf.request_timeout_s
                )
            except TenantQuotaError as exc:
                self._m_errors.inc()
                for req in batch:
                    req.fail(exc)
                return
        try:
            if chunk_to is not None:
                self._dispatch_chunked(batch, chunk_to)
            else:
                self._dispatch_to_replica(batch, n, padded)
        finally:
            if self._admission is not None:
                self._admission.release(ticket)

    def _dispatch_chunked(self, batch: List[_Request], largest: int) -> None:
        """Serve an over-bucket formation as a series of bucket-shaped
        dispatches: whole requests group greedily up to ``largest``; a
        single request bigger than ``largest`` splits into parts whose rows
        reassemble before its caller sees anything. Every dispatched shape
        is a real bucket shape."""
        groups: List[List[_Request]] = []
        current: List[_Request] = []
        cur_n = 0
        for req in batch:
            if req.n > largest:
                if current:
                    groups.append(current)
                    current, cur_n = [], 0
                assembly = _ChunkAssembly(req)
                parts = []
                offset = 0
                while offset < req.n:
                    k = min(largest, req.n - offset)
                    parts.append(_ChunkPart(
                        f_slice(req.rows, offset, offset + k),
                        k, assembly, len(parts),
                    ))
                    offset += k
                assembly.arm(len(parts))
                groups.extend([p] for p in parts)
            elif cur_n + req.n > largest:
                groups.append(current)
                current, cur_n = [req], req.n
            else:
                current.append(req)
                cur_n += req.n
        if current:
            groups.append(current)
        self._m_chunked.inc(len(groups))
        conf = self._conf
        t_formed = time.monotonic()
        for group in groups:
            g_n = sum(r.n for r in group)
            rows = (
                group[0].rows if len(group) == 1
                else f_concat([r.rows for r in group])
            )
            bucket = next((b for b in conf.buckets if b >= g_n), g_n)
            padded = pad_rows(rows, bucket)
            for req in group:
                if req.t_formed is None:
                    req.t_formed = t_formed
            self._m_padded.inc(bucket - g_n)
            self._m_fill.observe(g_n / bucket)
            self._dispatch_to_replica(group, g_n, padded)

    def _dispatch_to_replica(self, batch: List[_Request], n: int, padded) -> None:
        conf = self._conf
        handle = self._pick_replica()
        if handle is None:
            # no live replica RIGHT NOW (all draining/failed — the
            # controller is replacing them): park briefly off-lock and
            # re-admit without charging a retry
            time.sleep(0.02)
            self._requeue_front(batch, charge_retry=False, error=None)
            return
        # fan-in trace node: ONE serve.batch span parents the dispatch and
        # the replica's compute span (the RPC frame carries its context),
        # and links every sampled request in the batch via args — emitted
        # after the reply, when its duration is known
        from raydp_tpu.obs import tracing as _tracing

        sampled = [req for req in batch if req.ctx is not None]
        batch_ctx = None
        if sampled and _tracing.enabled():
            import uuid as _uuid

            batch_ctx = (sampled[0].ctx[0], _uuid.uuid4().hex[:16])
        t_dispatch = time.monotonic()
        for req in batch:
            req.t_dispatch = t_dispatch
        try:
            with _tracing.use_context(batch_ctx):
                out = handle.infer.options(
                    timeout=conf.request_timeout_s
                ).remote(padded, n).result()
        except _RETRYABLE as exc:
            self._release_replica(handle.actor_id)
            self._m_errors.inc()
            self._note_failure(handle)
            self._requeue_front(batch, charge_retry=True, error=exc)
            return
        except BaseException as exc:  # noqa: BLE001 - deterministic replica error
            self._release_replica(handle.actor_id)
            self._m_errors.inc()
            for req in batch:
                req.fail(exc)
            return
        t_reply = time.monotonic()
        compute_s = 0.0
        if isinstance(out, tuple) and len(out) == 2:
            # replicas report their on-device compute seconds alongside the
            # rows (an older replica returning a bare array still works)
            out, compute_s = out
        for req in batch:
            req.t_reply = t_reply
        self._release_replica(handle.actor_id)
        self._m_batches.inc()
        # doorbell evidence: a completed dispatch returns its pooled socket
        # to THIS thread's doorbell table — count it so the fast path is
        # observable (tests + docs/serving.md assert on it)
        from raydp_tpu.cluster import api as _capi

        conns = getattr(_capi._doorbell_tls, "conns", None)
        sock = getattr(handle, "_cached_sock", None)
        if conns and sock and sock in conns:
            self._m_doorbell.inc()
        now = time.monotonic()
        offset = 0
        latencies = []
        for req in batch:
            req.resolve(f_slice(out, offset, offset + req.n))
            offset += req.n
            latency_s = now - req.t_enqueue
            self._m_latency.observe(latency_s)
            latencies.append(latency_s * 1000.0)
        self._observe_stages(batch, now, compute_s)
        if batch_ctx is not None:
            self._emit_request_spans(
                batch, sampled, batch_ctx, now, compute_s,
                replica=handle.actor_id, batch_rows=n,
            )
        # the window deque is shared across dispatcher threads: mutate AND
        # snapshot it under the condition (a deque mutated mid-iteration
        # raises, which would silently starve the SLO gauge under exactly
        # the load where it matters)
        with self._cond:
            self._latency_window.extend(latencies)
            window = sorted(self._latency_window)
        if window:
            self._g_p99.set(window[min(len(window) - 1,
                                       int(len(window) * 0.99))])

    def _observe_stages(self, batch: List[_Request], t_done: float,
                        compute_s: float) -> None:
        """Feed the per-stage latency histograms from one resolved batch's
        stamps (dispatch = wire+wait minus the replica's reported compute)."""
        h = self._h_stages
        for req in batch:
            if req.t_formed is None or req.t_dispatch is None or req.t_reply is None:
                continue
            h["queue_wait"].observe(max(0.0, req.t_formed - req.t_enqueue))
            h["batch_form"].observe(max(0.0, req.t_dispatch - req.t_formed))
            h["dispatch"].observe(
                max(0.0, req.t_reply - req.t_dispatch - compute_s)
            )
            h["compute"].observe(max(0.0, compute_s))
            h["respond"].observe(max(0.0, t_done - req.t_reply))

    def _emit_request_spans(self, batch: List[_Request],
                            sampled: List[_Request], batch_ctx: tuple,
                            t_done: float, compute_s: float,
                            replica: str, batch_rows: int) -> None:
        """Emit the sampled request-path trace for one batch: per request a
        ``serve.request`` root with queue_wait / batch_form / dispatch /
        respond children, plus ONE ``serve.batch`` fan-in span (parented
        under the first sampled request, linking every sampled request span
        by id) whose context already rode the replica RPC — the replica's
        ``serve.replica_infer`` span lands under it."""
        from raydp_tpu.obs.tracing import record_span

        now_wall_us = time.time_ns() // 1000
        now_mono = time.monotonic()

        def wall(stamp: Optional[float]) -> int:
            if stamp is None:
                return now_wall_us
            return now_wall_us - int((now_mono - stamp) * 1e6)

        first = sampled[0]
        record_span(
            "serve.batch",
            wall(first.t_dispatch), int((first.t_reply - first.t_dispatch) * 1e6),
            trace=batch_ctx[0], span_id=batch_ctx[1], parent=first.ctx[1],
            rows=int(batch_rows), requests=len(batch), replica=replica,
            compute_s=round(compute_s, 6),
            request_spans=[req.ctx[1] for req in sampled],
            request_traces=[req.ctx[0] for req in sampled],
        )
        for req in sampled:
            trace, span_id = req.ctx
            record_span(
                "serve.request", wall(req.t_enqueue),
                int((t_done - req.t_enqueue) * 1e6),
                trace=trace, span_id=span_id, parent=None,
                rows=req.n, retries=req.retries, batch_span=batch_ctx[1],
            )
            for name, lo, hi in (
                ("serve.queue_wait", req.t_enqueue, req.t_formed),
                ("serve.batch_form", req.t_formed, req.t_dispatch),
                ("serve.dispatch", req.t_dispatch, req.t_reply),
                ("serve.respond", req.t_reply, t_done),
            ):
                if lo is None or hi is None:
                    continue
                record_span(
                    name, wall(lo), int((hi - lo) * 1e6),
                    trace=trace, parent=span_id,
                    batch_span=batch_ctx[1],
                )

    def _note_failure(self, handle) -> None:
        with self._cond:
            self._failed.add(handle.actor_id)
        callback = self._on_replica_failure
        if callback is not None:
            try:
                callback(handle)
            except Exception:
                from raydp_tpu import obs

                obs.log.error(
                    "replica-failure callback raised", exc_info=True,
                    actor_id=handle.actor_id,
                )

    # -- introspection / lifecycle --------------------------------------

    def stats(self) -> dict:
        with self._cond:
            out = {
                "queued_rows": self._queued_rows,
                "queued_requests": len(self._queue),
                "inflight": sum(self._inflight.values()),
                "replicas": len(self._replicas),
                "draining": len(self._draining),
                "failed": len(self._failed),
            }
        # per-stage latency decomposition (docs/observability.md): the same
        # stamps the sampled request traces are built from, as cumulative
        # histograms — p50/mean per stage in milliseconds
        stages = {}
        for stage, hist in self._h_stages.items():
            if hist.count:
                p50 = hist.quantile(0.50)
                stages[stage] = {
                    "p50_ms": round((p50 or 0.0) * 1e3, 3),
                    "mean_ms": round(hist.sum / hist.count * 1e3, 3),
                    "count": hist.count,
                }
        out["stage_latency"] = stages
        return out

    def queued_rows(self) -> int:
        with self._cond:
            return self._queued_rows

    def inflight_total(self) -> int:
        with self._cond:
            return sum(self._inflight.values())

    def close(self, timeout: float = 30.0) -> None:
        with self._cond:
            if self._stop:
                return
            self._stop = True
            pending = list(self._queue)
            self._queue.clear()
            self._queued_rows = 0
            self._cond.notify_all()
        for req in pending:
            req.fail(RuntimeError("serving deployment closed"))
        self._drain_thread.join(timeout)
        self._pool.shutdown(wait=True)
        self._g_queue.set(0)
        self._g_inflight.set(0)
