"""Serving-plane configuration: one place that parses the ``serve.*`` conf
keys (the same string-keyed conf convention as the ETL session's
``etl.dynamicAllocation.*`` family — docs/serving.md has the full table)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple


def _flag(value, default: bool = True) -> bool:
    if value is None:
        return default
    return str(value).lower() in ("1", "true", "yes")


def _buckets(value, max_batch: int) -> Tuple[int, ...]:
    """The batch-shape bucket ladder. Default: powers of two up to
    ``max_batch`` (small jit cache, low padding waste). Accepts a sequence
    or a comma-separated string; always sorted, deduped, capped at
    max_batch, and containing max_batch itself so every admissible batch
    has a bucket. A SINGLE bucket (``serve.batch_buckets = [N]``) makes
    every dispatch one fixed shape — the deterministic-shapes mode the
    chaos/recovery byte-identity gates run under (XLA numerics are
    bit-stable per shape, not across shapes)."""
    if value is None:
        ladder = []
        b = 1
        while b < max_batch:
            ladder.append(b)
            b *= 2
        ladder.append(max_batch)
        return tuple(ladder)
    if isinstance(value, str):
        value = [int(v) for v in value.replace(",", " ").split()]
    ladder = sorted({int(v) for v in value if 0 < int(v) <= max_batch})
    if not ladder or ladder[-1] != max_batch:
        ladder.append(max_batch)
    return tuple(ladder)


@dataclass
class ServeConf:
    """Resolved serving knobs for one deployment."""

    # -- batching policy ------------------------------------------------
    dynamic_batching: bool = True  # off = one dispatch per request, unpadded
    max_batch_size: int = 64
    batch_deadline_ms: float = 5.0  # oldest queued request's max wait
    buckets: Tuple[int, ...] = ()
    # -- dispatch / failover -------------------------------------------
    dispatchers: int = 4  # concurrent in-flight batches (doorbell conns)
    max_retries: int = 8  # re-admissions per request before it errors out
    request_timeout_s: float = 60.0  # per-dispatch RPC timeout
    # -- autoscaling ----------------------------------------------------
    autoscale: bool = False
    min_replicas: int = 1
    max_replicas: int = 4
    tick_s: float = 0.25
    sustained_ticks: int = 3  # the etl.dynamicAllocation.sustainedStages shape
    target_queue_per_replica: float = 8.0  # rows of sustained backlog each
    slo_p99_ms: Optional[float] = None  # latency SLO; breach => scale out
    # scale-out is REFUSED while host memory pressure (the mem.pressure
    # watermark gauge, obs/profiler.py) exceeds this — a hot deployment
    # must not fork replicas into an OOM (conf: autoscale.max_mem_pressure)
    max_mem_pressure: float = 0.95
    # -- replicas -------------------------------------------------------
    replica_light: bool = True  # zygote warm fork (python -S); see docs
    replica_max_concurrency: int = 4
    # -- decode serving (docs/serving.md, "Decode serving") -------------
    # continuous-batching autoregressive decode on each replica: a paged
    # KV cache in shm plus a fixed-slot decode loop (serve/decode.py).
    # Opt-in — a deployment that never streams pays nothing for it.
    decode: bool = False
    decode_capacity_tokens: int = 512  # per-sequence max prompt+generation
    decode_page_tokens: int = 128  # KV page granularity
    decode_max_seqs: int = 4  # concurrent decode slots per replica
    decode_max_new_tokens: int = 64  # per-request generation cap
    decode_int8_kv: bool = False  # int8 K/V pages + in-kernel dequant
    decode_eos_token: Optional[int] = None  # early-stop token id
    # per-token deadline SLOs (docs/observability.md, "Decode observatory"):
    # set either and every emitted token is judged against its deadline —
    # first token vs TTFT, token k vs t_first + (k-1)*TPOT — feeding the
    # serve.decode.goodput gauge + good/late token counters. None = no
    # deadline accounting (the default; goodput stays unreported).
    decode_ttft_slo_ms: Optional[float] = None
    decode_tpot_slo_ms: Optional[float] = None
    # -- request-path tracing (docs/observability.md) -------------------
    # fraction of requests that mint a trace context and emit the sampled
    # serve.request / serve.batch / replica span chain (only when tracing
    # is enabled — RAYDP_TPU_TRACE); the per-stage latency HISTOGRAMS are
    # always on regardless. Conf key: ``obs.request_sample_rate``.
    request_sample_rate: float = 0.01
    # -- tenancy (docs/multitenancy.md) ---------------------------------
    # name a tenant and this deployment's batch dispatches ride the same
    # fair-share admission queue as that tenant's ETL stages — serving and
    # ETL traffic from one tenant share one quota, and a co-tenant's heavy
    # shuffle cannot starve this deployment's batches (or vice versa).
    # Empty = unthrottled, the single-tenant behavior.
    tenant: str = ""
    extra: dict = field(default_factory=dict)

    @classmethod
    def resolve(cls, conf: Optional[dict]) -> "ServeConf":
        """Merge precedence: defaults < active ETL session configs (its
        ``serve.*`` keys, so one conf dict can describe a whole app) < the
        ``conf`` argument passed to ``deploy``."""
        merged: dict = {}
        try:
            from raydp_tpu.etl.session import active_session

            session = active_session()
            if session is not None:
                merged.update(
                    {k: v for k, v in session.configs.items()
                     if k.startswith(("serve.", "obs."))}
                )
        except Exception:  # raydp-lint: disable=swallowed-exceptions (serving works without any ETL session)
            pass
        merged.update(conf or {})

        def get(key, default=None):
            return merged.get(f"serve.{key}", default)

        max_batch = int(get("max_batch_size", 64))
        out = cls(
            dynamic_batching=_flag(get("dynamic_batching"), True),
            max_batch_size=max_batch,
            batch_deadline_ms=float(get("batch_deadline_ms", 5.0)),
            buckets=_buckets(get("batch_buckets"), max_batch),
            dispatchers=max(1, int(get("dispatchers", 4))),
            max_retries=int(get("max_retries", 8)),
            request_timeout_s=float(get("request_timeout_s", 60.0)),
            autoscale=_flag(get("autoscale.enabled"), False),
            min_replicas=max(1, int(get("autoscale.min_replicas", 1))),
            max_replicas=max(1, int(get("autoscale.max_replicas", 4))),
            tick_s=float(get("autoscale.tick_s", 0.25)),
            sustained_ticks=max(1, int(get("autoscale.sustained_ticks", 3))),
            target_queue_per_replica=float(
                get("autoscale.target_queue_per_replica", 8.0)
            ),
            max_mem_pressure=float(get("autoscale.max_mem_pressure", 0.95)),
            slo_p99_ms=(
                float(get("slo_p99_ms")) if get("slo_p99_ms") is not None
                else None
            ),
            decode=_flag(get("decode.enabled"), False),
            decode_capacity_tokens=int(get("decode.capacity_tokens", 512)),
            decode_page_tokens=int(get("decode.page_tokens", 128)),
            decode_max_seqs=max(1, int(get("decode.max_seqs", 4))),
            decode_max_new_tokens=int(get("decode.max_new_tokens", 64)),
            decode_int8_kv=_flag(get("decode.int8_kv"), False),
            decode_eos_token=(
                int(get("decode.eos_token"))
                if get("decode.eos_token") is not None else None
            ),
            decode_ttft_slo_ms=(
                float(get("decode.ttft_slo_ms"))
                if get("decode.ttft_slo_ms") is not None else None
            ),
            decode_tpot_slo_ms=(
                float(get("decode.tpot_slo_ms"))
                if get("decode.tpot_slo_ms") is not None else None
            ),
            replica_light=_flag(get("replica_light"), True),
            replica_max_concurrency=max(
                2, int(get("replica_max_concurrency", 4))
            ),
            request_sample_rate=float(
                merged.get("obs.request_sample_rate", 0.01)
            ),
            tenant=str(get("tenant", "") or ""),
            extra=merged,
        )
        return out
