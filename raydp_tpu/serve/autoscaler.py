"""Serving controller: SLO-aware autoscaling + replica self-healing.

One background thread per deployment, ticking every ``serve.autoscale.tick_s``
seconds:

- **healing** (always on): replicas the batcher marked failed — or the head
  reports DEAD — are replaced with fresh spawns (warm zygote forks), keeping
  the deployment at its target count. The batcher keeps serving with the
  survivors meanwhile; this is the actuator half of zero-drop failover.
- **autoscaling** (``serve.autoscale.enabled``): the decision inputs are the
  ``obs`` gauges the batcher maintains — ``serve.queue_depth`` (rows of
  admission backlog) and ``serve.p99_ms`` (windowed completion latency) —
  evaluated with the SUSTAINED-signal shape of the ETL plane's
  ``etl.dynamicAllocation.sustainedStages``: only ``sustained_ticks``
  CONSECUTIVE over-threshold ticks scale out (one burst must not fork
  replicas that idle-drain seconds later), and only as many consecutive
  fully-idle ticks scale back in. Scale-out spawns (bounded by
  ``max_replicas``); scale-in picks the youngest replica and DRAINS it —
  the batcher stops routing to it, its in-flight batches complete, then it
  is killed (bounded by ``min_replicas``).

The signal read is injectable (``signal_fn``) so policy decisions are unit-
testable without load generation.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from raydp_tpu import obs


class ServeController:
    def __init__(self, deployment, conf,
                 signal_fn: Optional[Callable[[], dict]] = None):
        self._deployment = deployment
        self._conf = conf
        self._signal_fn = signal_fn or self._default_signals
        self._stop = threading.Event()
        self._hot_streak = 0
        self._idle_streak = 0
        self._thread = threading.Thread(
            target=self._run, name="serve-controller", daemon=True
        )
        self._thread.start()

    def _default_signals(self) -> dict:
        """The decision inputs, read from the WINDOWED time-series mirror
        (obs/timeseries.py) with the live gauges as the freshness floor:
        the p99 signal is the max over the recent window — one sub-tick dip
        between flushes must not reset a sustained-breach streak — and a
        scrape of the head shows a controller-shaped consumer the exact
        same series (docs/observability.md "Time series")."""
        from raydp_tpu.obs import timeseries as _ts

        p99 = obs.metrics.gauge("serve.p99_ms").value
        # window ~2 ticks: wide enough to bridge a sub-tick dip between
        # flushes, NARROWER than the sustained period — a single spiky
        # flush must not read as hot for >= sustained_ticks consecutive
        # ticks (that would convert one burst sample into a scale-out,
        # the exact failure the sustained-signal shape exists to prevent)
        window_s = max(2.0 * self._conf.tick_s, 0.5)
        windowed = _ts.windowed_local("serve.p99_ms", window_s=window_s)
        if windowed["series"]:
            p99 = max(p99, windowed["max"] or 0.0)
        from raydp_tpu.obs.profiler import current_mem_pressure

        return {
            "queue_rows": obs.metrics.gauge("serve.queue_depth").value,
            "inflight": self._deployment.batcher.inflight_total(),
            "p99_ms": p99,
            # memory watermark plane: scale-out is vetoed while the host
            # is under memory pressure (tick() reads this)
            "mem_pressure": current_mem_pressure(window_s=window_s),
        }

    def _run(self) -> None:
        while not self._stop.wait(self._conf.tick_s):
            try:
                self.tick()
                # the serving driver's ~1s telemetry tick: ship the batcher
                # gauges/histograms so the head TSDB (scrape endpoint,
                # query_metrics) stays live under request load — and feed
                # this process's own windowed mirror for the signals above
                obs.flush_throttled(1.0)
            except Exception:
                obs.log.error("serve controller tick failed", exc_info=True)

    def tick(self) -> Optional[str]:
        """One control decision; returns "out"/"in"/None (tests call this
        directly with an injected signal_fn)."""
        deployment = self._deployment
        deployment.heal()
        if not self._conf.autoscale:
            return None
        signals = self._signal_fn()
        replicas = max(1, deployment.replica_count())
        backlog = signals.get("queue_rows", 0.0) / replicas
        p99 = signals.get("p99_ms", 0.0)
        slo = self._conf.slo_p99_ms
        hot = backlog > self._conf.target_queue_per_replica or (
            slo is not None and p99 > slo
        )
        idle = (
            signals.get("queue_rows", 0.0) == 0
            and signals.get("inflight", 0) == 0
            and (slo is None or p99 < slo / 2)
        )
        self._hot_streak = self._hot_streak + 1 if hot else 0
        self._idle_streak = self._idle_streak + 1 if idle else 0
        if (
            self._hot_streak >= self._conf.sustained_ticks
            and replicas < self._conf.max_replicas
        ):
            pressure = signals.get("mem_pressure", 0.0) or 0.0
            if pressure > self._conf.max_mem_pressure:
                # hot but the HOST is out of memory headroom: forking a
                # replica would trade latency for an OOM — hold, keep the
                # streak hot, and leave a visible marker
                obs.metrics.counter("serve.scale_out_vetoed_mem").inc()
                obs.instant("serve.autoscale_veto_mem",
                            mem_pressure=round(pressure, 4))
                return None
            self._hot_streak = 0
            deployment.scale_to(replicas + 1)  # counts serve.scale_out
            obs.instant("serve.autoscale_out", replicas=replicas + 1,
                        backlog=backlog, p99_ms=p99)
            return "out"
        if (
            self._idle_streak >= self._conf.sustained_ticks
            and replicas > self._conf.min_replicas
        ):
            self._idle_streak = 0
            deployment.scale_to(replicas - 1)  # counts serve.scale_in
            obs.instant("serve.autoscale_in", replicas=replicas - 1)
            return "in"
        return None

    def close(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._thread.join(timeout)
