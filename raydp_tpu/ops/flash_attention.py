"""Flash attention as a pallas TPU kernel.

Blockwise attention with online-softmax accumulators held in VMEM scratch:
the grid iterates (batch·head, q-block, k-block) with the k-block axis
innermost, so the per-q-block statistics (running max m, denominator l,
unnormalized output o) persist across k iterations and the full [T, T] score
matrix never materializes — O(T) memory instead of O(T²). Scores run on the
MXU (`preferred_element_type=f32`); masking and the softmax update run on the
VPU. Causal masking uses global positions (runtime offsets from SMEM), and
k-blocks entirely in the future are skipped outright (~2x causal throughput).

One kernel serves two surfaces:
- ``flash_attention``: normalized output, offsets 0 — the single-device /
  per-shard attention op (custom VJP recomputes through the exact reference).
- ``flash_attention_stats``: UNNORMALIZED output + (m, l) stats with caller
  offsets — the per-ring-step block product `parallel.ring_attention`
  merges across devices (``use_flash=True``).

Off-TPU the same kernel runs in interpret mode, so CPU-mesh tests exercise
the identical code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _flash_kernel(
    q_off_ref, k_off_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
    o_acc, m_acc, l_acc, *, scale, causal, block_q, block_k, normalize,
):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, NEG_INF)
        l_acc[:] = jnp.zeros_like(l_acc)

    # causal: a k-block entirely in the future contributes nothing — skip its
    # matmul + update outright (~2x causal throughput). Offsets are runtime
    # values (SMEM), so the predicate is computed at runtime too.
    if causal:
        q_last = q_off_ref[0] + qi * block_q + block_q - 1
        k_first = k_off_ref[0] + ki * block_k
        block_live = q_last >= k_first
    else:
        block_live = ki >= 0

    @pl.when(block_live)
    def _accumulate():
        q = q_ref[0]  # [BQ, D]
        k = k_ref[0]  # [BK, D]
        v = v_ref[0]  # [BK, D]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK]

        if causal:
            q_pos = q_off_ref[0] + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = k_off_ref[0] + ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)

        m_prev = m_acc[:, :1]  # [BQ, 1] (stats broadcast across lanes)
        l_prev = l_acc[:, :1]
        block_max = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, block_max)
        p = jnp.exp(scores - m_new)  # rows that are all -inf give p == 0
        if causal:
            p = jnp.where(scores > NEG_INF / 2, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        o_new = alpha * o_acc[:] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

        o_acc[:] = o_new
        m_acc[:] = jnp.broadcast_to(m_new, m_acc.shape)
        l_acc[:] = jnp.broadcast_to(l_new, l_acc.shape)

    @pl.when(ki == num_k - 1)
    def _finalize():
        if normalize:
            o_ref[0] = (
                o_acc[:] / jnp.maximum(l_acc[:, :1], 1e-30)
            ).astype(o_ref.dtype)
        else:
            o_ref[0] = o_acc[:].astype(o_ref.dtype)
        m_ref[0] = m_acc[:, :1]
        l_ref[0] = l_acc[:, :1]


def _union_vma(*arrays):
    vmas = [getattr(jax.typeof(a), "vma", None) for a in arrays]
    if any(v is not None for v in vmas):
        return frozenset().union(*[v for v in vmas if v is not None])
    return None


def _pvary_scalar(x, axis_name):
    from jax import lax

    try:
        return lax.pcast(x, (axis_name,), to="varying")
    except (AttributeError, ValueError):
        try:
            return lax.pvary(x, (axis_name,))
        except (AttributeError, ValueError):
            return x


def _flash_call(
    q, k, v, q_offset, k_offset, causal, block_q, block_k, interpret, normalize
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, t, d = q.shape
    tk = k.shape[2]
    block_q = min(block_q, t)
    block_k = min(block_k, tk)
    if t % block_q or tk % block_k:
        raise ValueError(
            f"sequence lengths ({t}, {tk}) must divide blocks ({block_q}, {block_k})"
        )
    bh = b * h
    qf = q.reshape(bh, t, d)
    kf = k.reshape(bh, tk, d)
    vf = v.reshape(bh, tk, d)

    kernel = functools.partial(
        _flash_kernel,
        scale=d**-0.5, causal=causal, block_q=block_q, block_k=block_k,
        normalize=normalize,
    )
    # under shard_map (manual partitioning — the only way Mosaic kernels run
    # multi-device) out_shape must carry the UNION of the inputs' varying axes
    union = _union_vma(qf, kf, vf)

    def sds(shape, dtype):
        if union is not None:
            return jax.ShapeDtypeStruct(shape, dtype, vma=union)
        return jax.ShapeDtypeStruct(shape, dtype)

    q_off = jnp.asarray([q_offset], jnp.int32)
    k_off = jnp.asarray([k_offset], jnp.int32)
    if union is not None:  # SMEM scalars must match the kernel vma too
        for axis in union:
            q_off = _pvary_scalar(q_off, axis)
            k_off = _pvary_scalar(k_off, axis)

    out_dtype = q.dtype if normalize else jnp.float32
    o, m, l = pl.pallas_call(  # noqa: E741
        kernel,
        out_shape=(
            sds((bh, t, d), out_dtype),
            sds((bh, t, 1), jnp.float32),
            sds((bh, t, 1), jnp.float32),
        ),
        grid=(bh, t // block_q, tk // block_k),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b_, i, j: (b_, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q_off, k_off, qf, kf, vf)
    return (
        o.reshape(b, h, t, d),
        m.reshape(b, h, t),
        l.reshape(b, h, t),
    )


def flash_attention_stats(
    q, k, v, q_offset, k_offset, causal: bool = False,
    block_q: int = 128, block_k: int = 128, interpret: bool | None = None,
):
    """One blockwise-attention pass returning (o_unnormalized, m, l).

    Shapes: q [B,H,Tq,D], k/v [B,H,Tk,D]; offsets are scalars (traced OK)
    giving the blocks' global positions for causal masking. Outputs:
    o [B,H,Tq,D] (unnormalized, f32), m and l [B,H,Tq] — merge across passes
    with the standard flash merge, divide by l at the end.
    """
    return _flash_call(
        q, k, v, q_offset, k_offset, causal, block_q, block_k, interpret,
        normalize=False,
    )


def _flash_forward(
    q, k, v, causal: bool, block_q: int, block_k: int, interpret: bool | None
):
    o, _, _ = _flash_call(
        q, k, v, 0, 0, causal, block_q, block_k, interpret, normalize=True
    )
    return o


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q, k, v, causal: bool = False, block_q: int = 128, block_k: int = 128,
    interpret: bool | None = None,
):
    """Fused attention: q,k,v [B, H, T, D] → [B, H, T, D]."""
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _reference(q, k, v, causal):
    # single source of truth for exact attention (gradients recompute
    # through this, so it must stay in lockstep with the parallel layer)
    from raydp_tpu.parallel.ring_attention import full_attention

    return full_attention(q, k, v, causal=causal)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q_, k_, v_: _reference(q_, k_, v_, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
