"""Flash attention as a pallas TPU kernel.

Blockwise attention with online-softmax accumulators held in VMEM scratch:
the grid iterates (batch·head, q-block, k-block) with the k-block axis
innermost, so the per-q-block statistics (running max m, denominator l,
unnormalized output o) persist across k iterations and the full [T, T] score
matrix never materializes — O(T) memory instead of O(T²). Scores run on the
MXU (`preferred_element_type=f32`); masking and the softmax update run on the
VPU.

Composes with the sequence-parallel layer: ring attention's per-device block
product (parallel/ring_attention._block_attn) is exactly one (q-block,
k-block) tile of this kernel, so ``flash_attention`` is the single-device /
per-shard compute path and the ring provides the cross-device reduction.

Backward: gradients recompute through the exact jnp reference (attention
gradients via autodiff of the stable softmax) — the standard
recompute-in-backward trade; fine for the sequence lengths a single device
holds.

Off-TPU the same kernel runs in interpret mode, so CPU-mesh tests exercise
the identical code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, o_acc, m_acc, l_acc, *, scale, causal,
    block_q, block_k,
):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, NEG_INF)
        l_acc[:] = jnp.zeros_like(l_acc)

    # causal: a k-block entirely in the future contributes nothing — skip its
    # matmul + update outright (~2x causal throughput)
    block_live = (
        qi * block_q + block_q - 1 >= ki * block_k if causal else ki >= 0
    )

    @pl.when(block_live)
    def _accumulate():
        q = q_ref[0]  # [BQ, D]
        k = k_ref[0]  # [BK, D]
        v = v_ref[0]  # [BK, D]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK]

        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)

        m_prev = m_acc[:, :1]  # [BQ, 1] (stats broadcast across lanes)
        l_prev = l_acc[:, :1]
        block_max = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, block_max)
        p = jnp.exp(scores - m_new)  # rows that are all -inf give p == 0
        if causal:
            p = jnp.where(scores > NEG_INF / 2, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        o_new = alpha * o_acc[:] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

        o_acc[:] = o_new
        m_acc[:] = jnp.broadcast_to(m_new, m_acc.shape)
        l_acc[:] = jnp.broadcast_to(l_new, l_acc.shape)

    @pl.when(ki == num_k - 1)
    def _finalize():
        o_ref[0] = (o_acc[:] / jnp.maximum(l_acc[:, :1], 1e-30)).astype(o_ref.dtype)


def _flash_forward(
    q, k, v, causal: bool, block_q: int, block_k: int, interpret: bool | None
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, t, d = q.shape
    tk = k.shape[2]
    block_q = min(block_q, t)
    block_k = min(block_k, tk)
    if t % block_q or tk % block_k:
        raise ValueError(
            f"sequence lengths ({t}, {tk}) must divide blocks ({block_q}, {block_k})"
        )
    bh = b * h
    qf = q.reshape(bh, t, d)
    kf = k.reshape(bh, tk, d)
    vf = v.reshape(bh, tk, d)
    scale = d**-0.5

    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
    )
    # under shard_map (manual partitioning — the only way Mosaic kernels run
    # multi-device) the out_shape must carry the UNION of the inputs'
    # varying-axes sets (any operand may be the sharded one)
    out_sds = jax.ShapeDtypeStruct((bh, t, d), q.dtype)
    vmas = [getattr(jax.typeof(a), "vma", None) for a in (qf, kf, vf)]
    if any(v is not None for v in vmas):
        union = frozenset().union(*[v for v in vmas if v is not None])
        out_sds = jax.ShapeDtypeStruct((bh, t, d), q.dtype, vma=union)
    out = pl.pallas_call(
        kernel,
        out_shape=out_sds,
        grid=(bh, t // block_q, tk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q, k, v, causal: bool = False, block_q: int = 128, block_k: int = 128,
    interpret: bool | None = None,
):
    """Fused attention: q,k,v [B, H, T, D] → [B, H, T, D]."""
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _reference(q, k, v, causal):
    # single source of truth for exact attention (gradients recompute
    # through this, so it must stay in lockstep with the parallel layer)
    from raydp_tpu.parallel.ring_attention import full_attention

    return full_attention(q, k, v, causal=causal)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q_, k_, v_: _reference(q_, k_, v_, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
