"""Flash attention as a pallas TPU kernel.

Blockwise attention with online-softmax accumulators held in VMEM scratch:
the grid iterates (batch·head, q-block, k-block) with the k-block axis
innermost, so the per-q-block statistics (running max m, denominator l,
unnormalized output o) persist across k iterations and the full [T, T] score
matrix never materializes — O(T) memory instead of O(T²). Scores run on the
MXU (`preferred_element_type=f32`); masking and the softmax update run on the
VPU. Causal masking uses global positions (runtime offsets from SMEM), and
k-blocks entirely in the future are skipped outright (~2x causal throughput).

One kernel family serves three surfaces:
- ``flash_attention``: normalized output, offsets 0 — the single-device /
  per-shard attention op. Its custom VJP is a blockwise FlashAttention-2
  backward (two pallas kernels over the saved output + logsumexp), so
  TRAINING is O(T) memory too — no [T, T] matrix in either direction.
- ``flash_attention_stats``: UNNORMALIZED output + (m, l) stats with caller
  offsets — the per-ring-step block product `parallel.ring_attention`
  merges across devices (``use_flash=True``).
- ``flash_decode``: incremental-decode attention of a few new query rows
  against a KV cache with per-sequence valid lengths (SMEM), sharing the
  same online-softmax update — so decode-vs-prefill is bit-identical at a
  fixed shape. Optional int8 K/V with on-the-fly per-row dequant.

Two forward kernel bodies implement the same math: ``_flash_kernel`` (the
r05 two-term update — reference) and ``_flash_kernel_onepass`` (default),
which folds the per-block rescale of the [BQ, D] accumulator out of the VPU
hot loop by predicating it on the running max actually moving. When the max
is stable (the common case once a few blocks have been seen) the rescale is
skipped outright; when it fires, the skipped-row multiplies are ×exp(0)=1,
so the two kernels are bit-identical by construction — the parity gate in
the bench is exact equality, not allclose.

Off-TPU the same kernels run in interpret mode, so CPU-mesh tests exercise
the identical code path.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def use_onepass_default() -> bool:
    """Whether the one-pass (deferred-rescale) forward kernel is the default.
    Env escape hatch ``RAYDP_TPU_FLASH_ONEPASS=0`` pins the reference kernel
    (bisecting a numerics report; the two are bit-identical by design)."""
    return os.environ.get("RAYDP_TPU_FLASH_ONEPASS", "1").lower() not in (
        "0", "false", "off"
    )


def _causal_block_live(q_off_ref, k_off_ref, qi, ki, block_q, block_k, causal):
    """Whether a (q-block, k-block) pair has any unmasked entry. Causal: a
    k-block entirely in the future contributes nothing — skip its matmul +
    update outright (~2x causal throughput). Offsets are runtime values
    (SMEM), so the predicate is computed at runtime too."""
    if not causal:
        return ki >= 0
    q_last = q_off_ref[0] + qi * block_q + block_q - 1
    k_first = k_off_ref[0] + ki * block_k
    return q_last >= k_first


def _causal_mask(s, q_off_ref, k_off_ref, qi, ki, block_q, block_k):
    """Mask scores s [BQ, BK] to NEG_INF where global k position > q
    position. Shared by the forward and both backward kernels so the mask
    semantics can never diverge between them."""
    q_pos = q_off_ref[0] + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = k_off_ref[0] + ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _flash_kernel(
    q_off_ref, k_off_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
    o_acc, m_acc, l_acc, *, scale, causal, block_q, block_k, normalize,
):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, NEG_INF)
        l_acc[:] = jnp.zeros_like(l_acc)

    block_live = _causal_block_live(
        q_off_ref, k_off_ref, qi, ki, block_q, block_k, causal
    )

    @pl.when(block_live)
    def _accumulate():
        q = q_ref[0]  # [BQ, D]
        k = k_ref[0]  # [BK, D]
        v = v_ref[0]  # [BK, D]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK]

        if causal:
            scores = _causal_mask(
                scores, q_off_ref, k_off_ref, qi, ki, block_q, block_k
            )

        m_prev = m_acc[:, :1]  # [BQ, 1] (stats broadcast across lanes)
        l_prev = l_acc[:, :1]
        block_max = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, block_max)
        p = jnp.exp(scores - m_new)  # rows that are all -inf give p == 0
        if causal:
            p = jnp.where(scores > NEG_INF / 2, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        o_new = alpha * o_acc[:] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

        o_acc[:] = o_new
        m_acc[:] = jnp.broadcast_to(m_new, m_acc.shape)
        l_acc[:] = jnp.broadcast_to(l_new, l_acc.shape)

    @pl.when(ki == num_k - 1)
    def _finalize():
        if normalize:
            o_ref[0] = (
                o_acc[:] / jnp.maximum(l_acc[:, :1], 1e-30)
            ).astype(o_ref.dtype)
        else:
            o_ref[0] = o_acc[:].astype(o_ref.dtype)
        m_ref[0] = m_acc[:, :1]
        l_ref[0] = l_acc[:, :1]


def _flash_kernel_onepass(
    q_off_ref, k_off_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
    o_acc, m_acc, l_acc, *, scale, causal, block_q, block_k, normalize,
):
    """One-pass online softmax with the accumulator rescale deferred.

    The r05 roofline blames the per-block ``alpha * o_acc`` rescale — a
    [BQ, D] VPU multiply every k iteration — for the LM attention VPU wall.
    Here the rescale (of both o and l) only runs when the running max
    actually moved (``any(block_max > m_prev)``); otherwise alpha == exp(0)
    == 1 exactly and the multiply is dead weight. Normalization stays
    deferred to the finalize step, so the hot loop is: one MXU score dot,
    one exp, one MXU p·v dot, one add. Bit-identical to ``_flash_kernel``
    (the gated multiplies are exactly ×1.0 when skipped)."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, NEG_INF)
        l_acc[:] = jnp.zeros_like(l_acc)

    block_live = _causal_block_live(
        q_off_ref, k_off_ref, qi, ki, block_q, block_k, causal
    )

    @pl.when(block_live)
    def _accumulate():
        q = q_ref[0]  # [BQ, D]
        k = k_ref[0]  # [BK, D]
        v = v_ref[0]  # [BK, D]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK]

        if causal:
            scores = _causal_mask(
                scores, q_off_ref, k_off_ref, qi, ki, block_q, block_k
            )

        m_prev = m_acc[:, :1]  # [BQ, 1]
        l_prev = l_acc[:, :1]
        block_max = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, block_max)
        p = jnp.exp(scores - m_new)
        if causal:
            p = jnp.where(scores > NEG_INF / 2, p, 0.0)

        p_sum = jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        moved = jnp.any(block_max > m_prev)

        # the rescale branch keeps the reference kernel's exact expression
        # shape (alpha·acc + new in one statement) so XLA's fusion decisions
        # — FMA contraction in particular — can't introduce 1-ulp drift; the
        # skip branch drops the ×1.0 multiplies outright (exact identity)
        @pl.when(moved)
        def _rescale():
            alpha = jnp.exp(m_prev - m_new)
            l_acc[:] = jnp.broadcast_to(alpha * l_prev + p_sum, l_acc.shape)
            o_acc[:] = alpha * o_acc[:] + pv

        @pl.when(jnp.logical_not(moved))
        def _no_rescale():
            l_acc[:] = jnp.broadcast_to(l_prev + p_sum, l_acc.shape)
            o_acc[:] = o_acc[:] + pv

        m_acc[:] = jnp.broadcast_to(m_new, m_acc.shape)

    @pl.when(ki == num_k - 1)
    def _finalize():
        if normalize:
            o_ref[0] = (
                o_acc[:] / jnp.maximum(l_acc[:, :1], 1e-30)
            ).astype(o_ref.dtype)
        else:
            o_ref[0] = o_acc[:].astype(o_ref.dtype)
        m_ref[0] = m_acc[:, :1]
        l_ref[0] = l_acc[:, :1]


def _union_vma(*arrays):
    # jax.typeof (and the vma tracking it exposes) only exists on modern jax;
    # on older releases (0.4.x) there is no varying-manual-axes machinery to
    # reconcile, so "no vma anywhere" is the correct answer — not a crash
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return None
    vmas = [getattr(typeof(a), "vma", None) for a in arrays]
    if any(v is not None for v in vmas):
        return frozenset().union(*[v for v in vmas if v is not None])
    return None


def _pvary_scalar(x, axis_name):
    from jax import lax

    try:
        return lax.pcast(x, (axis_name,), to="varying")
    except (AttributeError, ValueError):
        try:
            return lax.pvary(x, (axis_name,))
        except (AttributeError, ValueError):
            return x


def _flash_call(
    q, k, v, q_offset, k_offset, causal, block_q, block_k, interpret,
    normalize, onepass=None,
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if onepass is None:
        onepass = use_onepass_default()
    b, h, t, d = q.shape
    tk = k.shape[2]
    auto_q, auto_k = pick_blocks(t, tk, head_dim=d)
    block_q = min(block_q or auto_q, t)
    block_k = min(block_k or auto_k, tk)
    if t % block_q or tk % block_k:
        raise ValueError(
            f"sequence lengths ({t}, {tk}) must divide blocks ({block_q}, {block_k})"
        )
    bh = b * h
    qf = q.reshape(bh, t, d)
    kf = k.reshape(bh, tk, d)
    vf = v.reshape(bh, tk, d)

    kernel = functools.partial(
        _flash_kernel_onepass if onepass else _flash_kernel,
        scale=d**-0.5, causal=causal, block_q=block_q, block_k=block_k,
        normalize=normalize,
    )
    # under shard_map (manual partitioning — the only way Mosaic kernels run
    # multi-device) out_shape must carry the UNION of the inputs' varying axes
    union = _union_vma(qf, kf, vf)

    def sds(shape, dtype):
        if union is not None:
            return jax.ShapeDtypeStruct(shape, dtype, vma=union)
        return jax.ShapeDtypeStruct(shape, dtype)

    q_off = jnp.asarray([q_offset], jnp.int32)
    k_off = jnp.asarray([k_offset], jnp.int32)
    if union is not None:  # SMEM scalars must match the kernel vma too
        for axis in union:
            q_off = _pvary_scalar(q_off, axis)
            k_off = _pvary_scalar(k_off, axis)

    out_dtype = q.dtype if normalize else jnp.float32
    o, m, l = pl.pallas_call(  # noqa: E741
        kernel,
        out_shape=(
            sds((bh, t, d), out_dtype),
            sds((bh, t, 1), jnp.float32),
            sds((bh, t, 1), jnp.float32),
        ),
        grid=(bh, t // block_q, tk // block_k),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b_, i, j: (b_, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q_off, k_off, qf, kf, vf)
    return (
        o.reshape(b, h, t, d),
        m.reshape(b, h, t),
        l.reshape(b, h, t),
    )


def flash_attention_stats(
    q, k, v, q_offset, k_offset, causal: bool = False,
    block_q: int | None = None, block_k: int | None = None,
    interpret: bool | None = None,
):
    """One blockwise-attention pass returning (o_unnormalized, m, l).

    Shapes: q [B,H,Tq,D], k/v [B,H,Tk,D]; offsets are scalars (traced OK)
    giving the blocks' global positions for causal masking. Outputs:
    o [B,H,Tq,D] (unnormalized, f32), m and l [B,H,Tq] — merge across passes
    with the standard flash merge, divide by l at the end.
    """
    return _flash_call(
        q, k, v, q_offset, k_offset, causal, block_q, block_k, interpret,
        normalize=False,
    )


def _flash_forward(
    q, k, v, causal: bool, block_q: int, block_k: int, interpret: bool | None
):
    o, _, _ = _flash_call(
        q, k, v, 0, 0, causal, block_q, block_k, interpret, normalize=True
    )
    return o


# ---------------------------------------------------------------------------
# backward kernels (FlashAttention-2): blockwise dq/dk/dv from the saved
# normalized output and per-row logsumexp — O(T) memory for TRAINING too, not
# just the forward. Two kernels because TPU has no cross-block atomics:
# dq iterates k-blocks innermost (accumulating one q-block's dq in VMEM),
# dk/dv iterates q-blocks innermost (accumulating one k-block's dk+dv).
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_off_ref, k_off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
    dq_ref, dq_acc, *, scale, causal, block_q, block_k,
):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    block_live = _causal_block_live(
        q_off_ref, k_off_ref, qi, ki, block_q, block_k, causal
    )

    @pl.when(block_live)
    def _accumulate():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]  # [BQ, 1]
        dsum = dsum_ref[0]  # [BQ, 1]  rowsum(do * o)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = _causal_mask(s, q_off_ref, k_off_ref, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse)  # masked entries: exp(NEG_INF - lse) == 0
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - dsum) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == num_k - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_off_ref, k_off_ref, k_ref, v_ref, q_ref, do_ref, lse_ref, dsum_ref,
    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, block_q, block_k,
):
    from jax.experimental import pallas as pl

    kj = pl.program_id(1)
    qi = pl.program_id(2)
    num_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    block_live = _causal_block_live(
        q_off_ref, k_off_ref, qi, kj, block_q, block_k, causal
    )

    @pl.when(block_live)
    def _accumulate():
        k = k_ref[0]
        v = v_ref[0]
        q = q_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        dsum = dsum_ref[0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = _causal_mask(s, q_off_ref, k_off_ref, qi, kj, block_q, block_k)
        p = jnp.exp(s - lse)  # [BQ, BK]
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - dsum) * scale  # [BQ, BK]
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(
    q, k, v, o, lse, g, causal, block_q, block_k, interpret
):
    """Blockwise dq/dk/dv for the single-device surface (offsets 0).
    lse: [B,H,T] logsumexp of the scaled scores; o: normalized forward
    output; g: cotangent of o."""
    dsum = jnp.sum(
        g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )
    return flash_backward_blocks(
        q, k, v, lse, dsum, g, 0, 0, causal, block_q, block_k, interpret
    )


def flash_backward_blocks(
    q, k, v, lse, dsum, g, q_offset, k_offset, causal: bool = False,
    block_q: int | None = None, block_k: int | None = None,
    interpret: bool | None = None,
):
    """One blockwise-backward pass: (dq, dk, dv) partials of q [B,H,Tq,D]
    against k/v [B,H,Tk,D], given the GLOBAL per-row logsumexp ``lse`` and
    ``dsum = rowsum(do·o)`` [B,H,Tq] and the blocks' global positions for
    causal masking — the per-ring-step counterpart of
    ``flash_attention_stats``: `parallel.ring_attention` sums these partials
    as K/V (and their gradient accumulators) rotate around the ring."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, t, d = q.shape
    tk = k.shape[2]
    auto_q, auto_k = pick_blocks(t, tk)
    block_q = min(block_q or auto_q, t)
    block_k = min(block_k or auto_k, tk)
    if t % block_q or tk % block_k:
        raise ValueError(
            f"sequence lengths ({t}, {tk}) must divide blocks ({block_q}, {block_k})"
        )
    bh = b * h
    scale = d**-0.5

    qf = q.reshape(bh, t, d)
    kf = k.reshape(bh, tk, d)
    vf = v.reshape(bh, tk, d)
    dof = g.reshape(bh, t, d)
    lsef = lse.reshape(bh, t, 1)
    dsumf = dsum.astype(jnp.float32).reshape(bh, t, 1)

    union = _union_vma(qf, kf, vf, dof)

    def sds(shape, dtype):
        if union is not None:
            return jax.ShapeDtypeStruct(shape, dtype, vma=union)
        return jax.ShapeDtypeStruct(shape, dtype)

    q_off = jnp.asarray([q_offset], jnp.int32).reshape(1)
    k_off = jnp.asarray([k_offset], jnp.int32).reshape(1)
    if union is not None:
        for axis in union:
            q_off = _pvary_scalar(q_off, axis)
            k_off = _pvary_scalar(k_off, axis)

    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    q_spec = pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0))
    k_spec_dq = pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0))
    stat_spec_dq = pl.BlockSpec((1, block_q, 1), lambda b_, i, j: (b_, i, 0))

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        ),
        out_shape=sds((bh, t, d), q.dtype),
        grid=(bh, t // block_q, tk // block_k),
        in_specs=[
            smem, smem, q_spec, k_spec_dq, k_spec_dq, q_spec,
            stat_spec_dq, stat_spec_dq,
        ],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q_off, k_off, qf, kf, vf, dof, lsef, dsumf)

    # dk/dv: k-block outer, q-block inner
    k_spec = pl.BlockSpec((1, block_k, d), lambda b_, j, i: (b_, j, 0))
    q_spec_kv = pl.BlockSpec((1, block_q, d), lambda b_, j, i: (b_, i, 0))
    stat_spec_kv = pl.BlockSpec((1, block_q, 1), lambda b_, j, i: (b_, i, 0))

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        ),
        out_shape=(sds((bh, tk, d), k.dtype), sds((bh, tk, d), v.dtype)),
        grid=(bh, tk // block_k, t // block_q),
        in_specs=[
            smem, smem, k_spec, k_spec, q_spec_kv, q_spec_kv,
            stat_spec_kv, stat_spec_kv,
        ],
        out_specs=(k_spec, k_spec),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q_off, k_off, kf, vf, qf, dof, lsef, dsumf)

    return (
        dq.reshape(b, h, t, d),
        dk.reshape(b, h, tk, d),
        dv.reshape(b, h, tk, d),
    )


def pick_blocks(t_q: int, t_k: int, head_dim: int | None = None) -> tuple:
    """Largest power-of-two blocks (≤1024 each) dividing the sequence
    lengths. Measured on TPU v5e at T=8k/head_dim 128: 1024×1024 runs the
    fwd+bwd pair ~1.4x faster than the old 512×1024 caps (26.5→18.4ms per
    layer — the BACKWARD kernel wants the larger q tile) with forward a
    touch faster too, and still beats both the einsum reference and jax's
    bundled flash kernel; 2048 tiles fail to compile (VMEM). Tiny sequences
    just clamp to themselves.

    ``head_dim`` tunes the cap to the lane width: the 1024 cap was measured
    at D=128 (one lane-width), and the VMEM footprint of a tile scales with
    block·D — so past 128 the cap halves per doubling of D, keeping the
    tile footprint (and the compile success envelope) constant."""

    cap = 1024
    if head_dim is not None:
        while cap > 128 and cap * head_dim > 1024 * 128:
            cap //= 2

    def _block(t, cap):
        b = cap
        while b > 1 and t % b:
            b //= 2
        return b

    return _block(t_q, cap), _block(t_k, cap)


def _reference(q, k, v, causal):
    # single source of truth for exact attention (the gradcheck oracle; must
    # stay in lockstep with the parallel layer)
    from raydp_tpu.parallel.ring_attention import full_attention

    return full_attention(q, k, v, causal=causal)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q, k, v, causal: bool = False, block_q: int | None = None,
    block_k: int | None = None, interpret: bool | None = None,
):
    """Fused attention: q,k,v [B, H, T, D] → [B, H, T, D]. ``block_q`` /
    ``block_k`` default to ``pick_blocks`` (measured-fastest large tiles);
    pass explicit sizes only to pin a tiling (tests / VMEM-constrained
    shard_map bodies)."""
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    o, m, l = _flash_call(  # noqa: E741
        q, k, v, 0, 0, causal, block_q, block_k, interpret, normalize=True
    )
    # residuals are O(T): inputs + normalized output + per-row logsumexp
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return o, (q, k, v, o, lse)


def _bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v, o, lse = residuals
    return _flash_backward(
        q, k, v, o, lse, g, causal, block_q, block_k, interpret
    )


flash_attention.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------------------
# decode: a few new query rows against a KV cache. Same online-softmax
# update as the prefill kernel (deferred rescale + deferred normalization),
# same masking predicate (keep k_pos <= q_pos), same NEG_INF/p-zeroing
# semantics — so a decode step at a fixed shape is bit-identical to the
# matching rows of a prefill pass over the same (dequantized) cache when
# block_k agrees. Grid is (batch·head, k-block) with per-sequence valid
# lengths in SMEM; k-blocks entirely past a sequence's length are skipped.
# ---------------------------------------------------------------------------


def _decode_body(
    kv_len_ref, q_ref, load_kv, o_ref, o_acc, m_acc, l_acc,
    *, scale, block_k, heads, tq,
):
    """Shared decode kernel body. ``load_kv()`` materializes this k-block's
    [BK, D] f32 K and V (identity for f32/bf16 caches, per-row dequant for
    int8) — kept behind a thunk so dead blocks skip the dequant too."""
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    ki = pl.program_id(1)
    num_k = pl.num_programs(1)
    kv_len = kv_len_ref[bh // heads]

    @pl.when(ki == 0)
    def _init():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, NEG_INF)
        l_acc[:] = jnp.zeros_like(l_acc)

    @pl.when(ki * block_k < kv_len)
    def _accumulate():
        q = q_ref[0]  # [TQ, D] — last TQ positions of the sequence
        k, v = load_kv()  # [BK, D] f32 each
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [TQ, BK]

        # global positions: query rows are the last TQ positions (front
        # padding, if any, lands on negative q_pos and masks to nothing)
        q_pos = kv_len - tq + jax.lax.broadcasted_iota(
            jnp.int32, (tq, block_k), 0
        )
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (tq, block_k), 1
        )
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)

        m_prev = m_acc[:, :1]
        l_prev = l_acc[:, :1]
        block_max = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, block_max)
        p = jnp.exp(scores - m_new)
        p = jnp.where(scores > NEG_INF / 2, p, 0.0)

        p_sum = jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        moved = jnp.any(block_max > m_prev)

        @pl.when(moved)
        def _rescale():
            alpha = jnp.exp(m_prev - m_new)
            l_acc[:] = jnp.broadcast_to(alpha * l_prev + p_sum, l_acc.shape)
            o_acc[:] = alpha * o_acc[:] + pv

        @pl.when(jnp.logical_not(moved))
        def _no_rescale():
            l_acc[:] = jnp.broadcast_to(l_prev + p_sum, l_acc.shape)
            o_acc[:] = o_acc[:] + pv

        m_acc[:] = jnp.broadcast_to(m_new, m_acc.shape)

    @pl.when(ki == num_k - 1)
    def _finalize():
        o_ref[0] = (
            o_acc[:] / jnp.maximum(l_acc[:, :1], 1e-30)
        ).astype(o_ref.dtype)


def _decode_kernel(
    kv_len_ref, q_ref, k_ref, v_ref, o_ref, o_acc, m_acc, l_acc,
    *, scale, block_k, heads, tq,
):
    def load_kv():
        return (
            k_ref[0].astype(jnp.float32),
            v_ref[0].astype(jnp.float32),
        )

    _decode_body(
        kv_len_ref, q_ref, load_kv, o_ref, o_acc, m_acc, l_acc,
        scale=scale, block_k=block_k, heads=heads, tq=tq,
    )


def _decode_kernel_int8(
    kv_len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
    o_acc, m_acc, l_acc, *, scale, block_k, heads, tq,
):
    def load_kv():
        # per-row dequant on the fly (rows = cache positions): int8 values
        # carry a [BK, 1] f32 scale each for K and V — the layout
        # ops.quantization.quantize_int8 emits
        return (
            k_ref[0].astype(jnp.float32) * ks_ref[0],
            v_ref[0].astype(jnp.float32) * vs_ref[0],
        )

    _decode_body(
        kv_len_ref, q_ref, load_kv, o_ref, o_acc, m_acc, l_acc,
        scale=scale, block_k=block_k, heads=heads, tq=tq,
    )


def flash_decode(
    q, k, v, kv_len, *, k_scale=None, v_scale=None,
    block_k: int | None = None, interpret: bool | None = None,
):
    """Decode attention: the last ``Tq`` query rows of each sequence attend
    a KV cache with per-sequence valid lengths.

    q: [B, H, Tq, D] — queries for the newest Tq positions (usually 1).
    k, v: [B, H, Tk, D] — cache at fixed capacity Tk (f32/bf16; or int8
        with ``k_scale``/``v_scale`` [B, H, Tk] per-row scales from
        ``ops.quantization.quantize_int8``).
    kv_len: [B] int32 — valid lengths INCLUDING the Tq new positions.

    Returns [B, H, Tq, D] normalized attention output. Positions at or past
    ``kv_len`` are masked; k-blocks entirely past a sequence's length are
    skipped. Tq is padded up to the 8-sublane tile at the FRONT (pad rows
    get out-of-range q_pos and are sliced off), so callers can pass Tq=1.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    int8_kv = k_scale is not None
    if int8_kv != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be provided together")
    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_k = min(block_k or pick_blocks(tq, tk, head_dim=d)[1], tk)
    if tk % block_k:
        raise ValueError(f"cache capacity {tk} must divide block_k {block_k}")

    tq_pad = max(8, -(-tq // 8) * 8)
    if tq_pad != tq:
        q = jnp.concatenate(
            [jnp.broadcast_to(q[:, :, :1], (b, h, tq_pad - tq, d)), q], axis=2
        )
    bh = b * h
    qf = q.reshape(bh, tq_pad, d)
    kf = k.reshape(bh, tk, d)
    vf = v.reshape(bh, tk, d)
    kv_len_arr = jnp.asarray(kv_len, jnp.int32).reshape(b)

    kernel_kwargs = dict(scale=d**-0.5, block_k=block_k, heads=h, tq=tq_pad)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    q_spec = pl.BlockSpec((1, tq_pad, d), lambda b_, j: (b_, 0, 0))
    kv_spec = pl.BlockSpec((1, block_k, d), lambda b_, j: (b_, j, 0))
    scale_spec = pl.BlockSpec((1, block_k, 1), lambda b_, j: (b_, j, 0))

    if int8_kv:
        kernel = functools.partial(_decode_kernel_int8, **kernel_kwargs)
        in_specs = [smem, q_spec, kv_spec, kv_spec, scale_spec, scale_spec]
        operands = (
            kv_len_arr, qf, kf, vf,
            k_scale.reshape(bh, tk, 1).astype(jnp.float32),
            v_scale.reshape(bh, tk, 1).astype(jnp.float32),
        )
    else:
        kernel = functools.partial(_decode_kernel, **kernel_kwargs)
        in_specs = [smem, q_spec, kv_spec, kv_spec]
        operands = (kv_len_arr, qf, kf, vf)

    o = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, tq_pad, d), q.dtype),
        grid=(bh, tk // block_k),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((tq_pad, d), jnp.float32),
            pltpu.VMEM((tq_pad, 128), jnp.float32),
            pltpu.VMEM((tq_pad, 128), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    return o.reshape(b, h, tq_pad, d)[:, :, tq_pad - tq:]
