"""Quantization kernels: int8 with per-row scales, stochastic rounding.

Host→device bandwidth and HBM footprint both shrink 4× when exchange blocks
or activations travel as int8 + f32 scales. On TPU the stochastic path is a
row-tiled pallas kernel (per-core PRNG, mantissa bit-trick uniform); off-TPU
the same math runs via jax.random (the TPU PRNG primitives have no CPU
lowering, interpreted or otherwise — the kernel itself is validated on real
hardware). Deterministic rounding is a plain jnp path, exactly invertible to
within one quantum.

Stochastic rounding is unbiased only if the seed varies per call — derive it
from a step counter; reusing one seed correlates the rounding error across
steps and accumulates bias on slowly-changing tensors.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def quantize_int8(x: jnp.ndarray, seed: int | None = None, stochastic: bool = False,
                  block_rows: int = 256):
    """[N, D] f32 → (int8 values [N, D], f32 scales [N, 1]); row-wise scales.
    ``seed`` is required when ``stochastic=True`` (vary it per step)."""
    if not stochastic:
        scales = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0
        scales = jnp.maximum(scales, 1e-12)
        values = jnp.clip(jnp.round(x / scales), -127, 127).astype(jnp.int8)
        return values, scales
    if seed is None:
        raise ValueError("stochastic quantization requires a per-step seed")
    if jax.default_backend() != "tpu":
        scales = jnp.maximum(
            jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0, 1e-12
        )
        uniform = jax.random.uniform(jax.random.PRNGKey(seed), x.shape)
        values = jnp.clip(jnp.floor(x / scales + uniform), -127, 127).astype(jnp.int8)
        return values, scales
    return _quantize_pallas(x, seed, block_rows)


def dequantize_int8(values: jnp.ndarray, scales: jnp.ndarray) -> jnp.ndarray:
    return values.astype(jnp.float32) * scales


@jax.custom_vjp
def int8_matmul(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """``x [..., K] @ w [K, M]`` computed on the MXU's int8 path (2x the
    bf16 rate on v5e/v5p): activations quantize per-row, weights per-column,
    the dot runs int8xint8->int32, and the output dequantizes by the outer
    product of scales. Training-safe via the straight-through estimator —
    the backward pass differentiates the EXACT matmul at the float inputs
    (standard int8-forward training recipe), so gradients are the bf16
    matmul gradients, not zero (quantize's round has no gradient).

    Quantization error is bounded by the per-row/column max-abs scaling
    (~0.4% relative per operand); intended for the MLP blocks where the
    4d contraction amortizes the quantize/dequantize VPU work."""
    xq, xs = quantize_int8(x.reshape(-1, x.shape[-1]).astype(jnp.float32))
    wq, ws = quantize_int8(w.T.astype(jnp.float32))  # per-COLUMN scales of w
    y = jax.lax.dot_general(
        xq, wq.T,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = y.astype(jnp.float32) * xs * ws.T
    return out.reshape(x.shape[:-1] + (w.shape[-1],))


def _int8_matmul_fwd(x, w):
    return int8_matmul(x, w), (x, w)


def _int8_matmul_bwd(res, g):
    x, w = res
    # straight-through: grads of the exact float matmul, in the inputs'
    # dtypes (bf16 keeps the backward on the MXU's bf16 path)
    gx = jnp.einsum("...m,km->...k", g.astype(x.dtype), w.astype(x.dtype))
    gw = jnp.einsum(
        "...k,...m->km",
        x.astype(jnp.float32),
        g.astype(jnp.float32),
    ).astype(w.dtype)
    return gx.astype(x.dtype), gw


int8_matmul.defvjp(_int8_matmul_fwd, _int8_matmul_bwd)


def int8_dot_general(
    lhs, rhs, dimension_numbers, precision=None, preferred_element_type=None
):
    """Drop-in ``dot_general`` for ``flax.linen.Dense(dot_general=...)``:
    routes the Dense contraction ([..., K] x [K, M]) through int8_matmul
    (output cast back to the promoted input dtype so downstream activations
    keep the module's dtype); any other contraction falls through to lax.
    Using it keeps the param tree IDENTICAL to a plain Dense, so bf16 and
    int8-forward checkpoints interchange freely."""
    ((lc, rc), (lb, rb)) = dimension_numbers
    if tuple(lc) == (lhs.ndim - 1,) and tuple(rc) == (0,) and not lb and not rb:
        return int8_matmul(lhs, rhs).astype(lhs.dtype)
    return jax.lax.dot_general(
        lhs, rhs, dimension_numbers,
        precision=precision, preferred_element_type=preferred_element_type,
    )


def _quant_kernel(x_ref, seed_ref, values_ref, scales_ref):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # fold the row-block index into the seed so tiles draw independent noise
    pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
    x = x_ref[:]
    abs_max = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(abs_max / 127.0, 1e-12)
    scaled = x / scale
    # uniform [0,1) via the mantissa bit-trick (Mosaic lacks uint32→f32 cast):
    # top 23 random bits + exponent of 1.0 bitcast to f32 ∈ [1,2), minus 1
    random_bits = pltpu.bitcast(pltpu.prng_random_bits(scaled.shape), jnp.uint32)
    mantissa = (random_bits >> 9) | jnp.uint32(0x3F800000)
    uniform = pltpu.bitcast(mantissa, jnp.float32) - 1.0
    rounded = jnp.floor(scaled + uniform)
    values_ref[:] = jnp.clip(rounded, -127, 127).astype(jnp.int8)
    scales_ref[:] = jnp.broadcast_to(scale, scales_ref.shape)


def _quantize_pallas(x: jnp.ndarray, seed: int, block_rows: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, d = x.shape
    block_rows = min(block_rows, n)
    if n % block_rows:  # pad rows so the grid divides evenly
        pad = block_rows - n % block_rows
        x = jnp.concatenate([x, jnp.zeros((pad, d), x.dtype)], axis=0)
    padded_n = x.shape[0]
    grid = (padded_n // block_rows,)
    values, scales = pl.pallas_call(
        _quant_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((padded_n, d), jnp.int8),
            jax.ShapeDtypeStruct((padded_n, 1), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ),
    )(x, jnp.asarray([seed], jnp.int32))
    return values[:n], scales[:n]
