"""TPU ops: fused kernels (pallas) with XLA fallbacks."""

from raydp_tpu.ops.embedding import (
    embedding_lookup_vocab_sharded,
    sharded_embedding_lookup,
)
from raydp_tpu.ops.flash_attention import flash_attention
from raydp_tpu.ops.interaction import dot_interaction, dot_interaction_pallas

__all__ = [
    "dot_interaction",
    "dot_interaction_pallas",
    "flash_attention",
    "embedding_lookup_vocab_sharded",
    "sharded_embedding_lookup",
]
