"""TPU ops: fused kernels (pallas) with XLA fallbacks."""

from raydp_tpu.ops.embedding import (
    embedding_lookup_vocab_sharded,
    sharded_embedding_lookup,
)
from raydp_tpu.ops.interaction import dot_interaction, dot_interaction_pallas

__all__ = [
    "dot_interaction",
    "dot_interaction_pallas",
    "embedding_lookup_vocab_sharded",
    "sharded_embedding_lookup",
]
