"""TPU ops: fused kernels (pallas) with XLA fallbacks."""

from raydp_tpu.ops.embedding import (
    embedding_lookup_vocab_sharded,
    sharded_embedding_lookup,
)
from raydp_tpu.ops.flash_attention import flash_attention, flash_decode
from raydp_tpu.ops.interaction import dot_interaction, dot_interaction_pallas
from raydp_tpu.ops.quantization import (
    dequantize_int8,
    int8_matmul,
    quantize_int8,
)

__all__ = [
    "dequantize_int8",
    "dot_interaction",
    "dot_interaction_pallas",
    "flash_attention",
    "flash_decode",
    "int8_matmul",
    "quantize_int8",
    "embedding_lookup_vocab_sharded",
    "sharded_embedding_lookup",
]
