"""Embedding lookup over vocab-sharded tables.

BASELINE.md's DLRM config asks for sharded embedding tables (the reference
trains DLRM pure-DP with replicated tables — its only model-parallel-adjacent
need). Two idiomatic TPU paths:

- **GSPMD (default)**: shard the table with ``NamedSharding(P("model", None))``
  and just ``jnp.take`` — XLA partitions the gather and inserts the collective.
  This is what models/dlrm.py uses via param_sharding_rules.
- **Explicit (this module)**: a shard_map mask-gather-psum, for when you want
  the collective schedule pinned down rather than left to the partitioner
  (e.g. to overlap with other compute, or under a ``shard_map``-only step).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from raydp_tpu.parallel.mesh import axis_env_size


def embedding_lookup_vocab_sharded(
    table: jnp.ndarray, ids: jnp.ndarray, axis_name: str
) -> jnp.ndarray:
    """Per-device body (call inside shard_map): ``table`` is the local vocab
    shard [V/N, D]; ``ids`` are global ids (replicated). Each device gathers
    the ids that fall in its shard and a psum assembles full rows."""
    n = axis_env_size(axis_name)
    my = lax.axis_index(axis_name)
    local_v = table.shape[0]
    start = my * local_v
    local_ids = ids - start
    in_range = (local_ids >= 0) & (local_ids < local_v)
    safe_ids = jnp.clip(local_ids, 0, local_v - 1)
    rows = jnp.take(table, safe_ids, axis=0)
    rows = jnp.where(in_range[..., None], rows, 0.0)
    return lax.psum(rows, axis_name)


def sharded_embedding_lookup(
    table: jnp.ndarray, ids: jnp.ndarray, mesh, axis: str = "model"
) -> jnp.ndarray:
    """Global-array convenience wrapper: table sharded [V, D] over ``axis``,
    ids replicated; returns replicated rows."""
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    return shard_map(
        partial(embedding_lookup_vocab_sharded, axis_name=axis),
        mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=P(),
    )(table, ids)
