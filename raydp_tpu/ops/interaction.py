"""DLRM dot-interaction op.

The pairwise-feature-interaction at the heart of DLRM (the reference ships it
inside the pytorch_dlrm notebook's model as a python loop over torch ops): for
stacked per-feature embeddings T = [B, F, D], compute all pairwise dot
products and return the strict lower triangle, [B, F*(F-1)/2].

Two paths:
- ``dot_interaction``: XLA einsum + static gather — lowers to one batched MXU
  matmul; the fallback and autodiff path.
- ``dot_interaction_pallas``: fused pallas kernel (batch-tiled; keeps T in
  VMEM, runs the F×F Gram matmul on the MXU, selects the triangle in-register
  and writes only the packed output). Runs ``interpret=True`` off-TPU so tests
  exercise the same kernel on the CPU mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _tril_indices(f: int):
    rows, cols = np.tril_indices(f, k=-1)
    return rows.astype(np.int32), cols.astype(np.int32)


def dot_interaction(stacked: jnp.ndarray) -> jnp.ndarray:
    """[B, F, D] -> [B, F*(F-1)/2] pairwise dots (XLA path)."""
    gram = jnp.einsum("bfd,bgd->bfg", stacked, stacked)
    rows, cols = _tril_indices(stacked.shape[1])
    return gram[:, rows, cols]


def _interaction_kernel(t_ref, out_ref):
    t = t_ref[:]  # [BB, F, D]
    gram = jax.lax.dot_general(
        t, t, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )  # [BB, F, F] — one batched MXU matmul
    f = t.shape[1]
    # pack the strict lower triangle with static slices (F is small and
    # static, so this unrolls; no dynamic gather, which pallas disallows)
    offset = 0
    for i in range(1, f):
        out_ref[:, offset : offset + i] = gram[:, i, :i].astype(out_ref.dtype)
        offset += i


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def dot_interaction_pallas(
    stacked: jnp.ndarray, block_batch: int = 128, interpret: bool | None = None
) -> jnp.ndarray:
    """Fused pallas version (1.4-1.5x the XLA path at Criteo scale on v5e).
    Falls back to interpret mode off-TPU. Differentiable: the backward pass
    scatters the packed cotangent back into the symmetric Gram gradient."""
    return _interaction_forward(stacked, block_batch, interpret)


def _interaction_fwd(stacked, block_batch, interpret):
    return _interaction_forward(stacked, block_batch, interpret), stacked


def _interaction_bwd(block_batch, interpret, stacked, g):
    b, f, d = stacked.shape
    rows, cols = _tril_indices(f)
    gram_grad = jnp.zeros((b, f, f), g.dtype)
    gram_grad = gram_grad.at[:, rows, cols].set(g)
    sym = gram_grad + jnp.swapaxes(gram_grad, 1, 2)  # d(T Tᵀ) is symmetric
    return (jnp.einsum("bfg,bgd->bfd", sym, stacked),)


dot_interaction_pallas.defvjp(_interaction_fwd, _interaction_bwd)


def _active_mesh():
    """The mesh governing the current trace: the new-style context
    (``jax.set_mesh`` / ``use_abstract_mesh``) or the legacy ``with mesh:``
    block. Returns None when no multi-device mesh is active."""
    get_abstract = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_abstract is not None:
        mesh = get_abstract()
        if mesh is not None and mesh.shape:
            return mesh
    try:
        from jax._src.mesh import thread_resources

        physical = thread_resources.env.physical_mesh
        if not physical.empty:
            return physical
    except Exception:  # raydp-lint: disable=swallowed-exceptions (optional fast path; caller falls back)
        pass
    return None


def dot_interaction_fused(
    stacked: jnp.ndarray,
    batch_axes: Sequence[str] = ("data", "dp", "batch"),
    block_batch: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """The pallas interaction kernel, runnable under MULTI-DEVICE jit.

    Mosaic kernels cannot be auto-partitioned by XLA, so under a multi-device
    mesh the kernel is wrapped in ``shard_map`` over the batch axes (the
    op is embarrassingly parallel in B): each device runs the fused kernel on
    its local [B/dp, F, D] shard and the surrounding jit keeps dp×tp layouts
    untouched. Single-device (or no active mesh) falls through to the plain
    pallas call. ``batch_axes`` lists mesh-axis names that may shard B; any
    other axes see replicated data."""
    mesh = _active_mesh()
    if mesh is None:
        if jax.device_count() > 1:
            # a multi-device jit with NO mesh context (plain in_shardings
            # style) would hand the Mosaic kernel to the auto-partitioner,
            # which raises NotImplementedError — use the einsum path there
            return dot_interaction(stacked)
        return dot_interaction_pallas(stacked, block_batch, interpret)
    if int(np.prod(list(mesh.shape.values()))) == 1:
        return dot_interaction_pallas(stacked, block_batch, interpret)
    from jax.sharding import PartitionSpec as P

    from raydp_tpu.parallel.sharding import shard_map_compat

    present = tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1)
    fn = shard_map_compat(
        partial(dot_interaction_pallas, block_batch=block_batch, interpret=interpret),
        mesh=mesh,
        in_specs=P(present if present else None, None, None),
        out_specs=P(present if present else None, None),
        # the pallas interpreter can't reconcile invariant grid slices with
        # varying operands; numerics are test-validated against the einsum
        check_vma=False,
    )
    return fn(stacked)


def _interaction_forward(
    stacked: jnp.ndarray, block_batch: int = 128, interpret: bool | None = None
) -> jnp.ndarray:
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, f, d = stacked.shape
    out_f = f * (f - 1) // 2
    block_batch = min(block_batch, b)
    if b % block_batch:
        # pad batch so the grid divides evenly (static shapes for the MXU)
        pad = block_batch - b % block_batch
        stacked = jnp.concatenate(
            [stacked, jnp.zeros((pad, f, d), stacked.dtype)], axis=0
        )
    padded_b = stacked.shape[0]
    grid = (padded_b // block_batch,)
    out = pl.pallas_call(
        _interaction_kernel,
        out_shape=jax.ShapeDtypeStruct((padded_b, out_f), stacked.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_batch, f, d), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((block_batch, out_f), lambda i: (i, 0)),
        interpret=interpret,
    )(stacked)
    return out[:b]
