"""Cluster runtime: process actors, placement groups, virtual nodes, ownership.

The native control-plane substrate of the framework — what Ray core is to the
reference (SURVEY.md L1). See common.py for the wire protocol, head.py for the
control-plane service, worker.py for the actor host, api.py for the client API.
"""

from raydp_tpu.cluster.api import (
    ActorHandle,
    PlacementGroup,
    add_node,
    available_resources,
    create_placement_group,
    dump_metrics,
    export_trace,
    get,
    get_actor,
    head_rpc,
    init,
    is_initialized,
    kill_all_matching,
    list_actors,
    nodes,
    placement_group_table,
    remove_node,
    remove_placement_group,
    session_dir,
    shutdown,
    spawn,
    total_resources,
)
from raydp_tpu.cluster.common import (
    ActorDiedError,
    ActorState,
    ClusterError,
    OwnerDiedError,
)
from raydp_tpu.cluster.worker import current_context, exit_actor

__all__ = [
    "ActorDiedError",
    "ActorHandle",
    "ActorState",
    "ClusterError",
    "OwnerDiedError",
    "PlacementGroup",
    "add_node",
    "available_resources",
    "create_placement_group",
    "current_context",
    "dump_metrics",
    "exit_actor",
    "export_trace",
    "get",
    "get_actor",
    "head_rpc",
    "init",
    "is_initialized",
    "kill_all_matching",
    "list_actors",
    "nodes",
    "placement_group_table",
    "remove_node",
    "remove_placement_group",
    "session_dir",
    "shutdown",
    "spawn",
    "total_resources",
]
