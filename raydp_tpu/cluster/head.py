"""The head process: cluster control plane.

One small native-substrate service per session holding all mutable cluster
state: virtual nodes + resources, actor lifecycle (spawn / crash-detect /
restart-with-same-identity), placement groups, and the object-ownership table
used by the exchange layer. It fills the role Ray's GCS + raylet play under the
reference (SURVEY.md L1) and of the reference's RayAppMaster actor-bookkeeping
(RayAppMaster.scala:127-205) — but is engine-agnostic: the ETL session, the
estimators and the SPMD launcher are all just clients.

Runs as its own OS process (see head_main) so driver-side JAX compilation can
never starve the control plane.
"""

from __future__ import annotations

import difflib
import itertools as _itertools
import os
import signal
import socket
import socketserver
import subprocess
import threading
import time
import traceback
import uuid
from typing import Any, Dict, List, Optional

from raydp_tpu.cluster.common import (
    HEAD_TCP_FILE,
    SESSION_ENV,
    ActorDiedError,
    ActorRecord,
    ActorSpec,
    ActorState,
    ClusterError,
    NodeRecord,
    OwnerDiedError,
    TenantQuotaError,
    actor_sock_path,
    connect,
    head_sock_path,
    host_id as common_host_id,
    recv_frame,
    rpc,
    send_frame,
    tenant_of_object,
    unwrap_traced,
)
from raydp_tpu import sanitize
from raydp_tpu.obs import instant as obs_instant
from raydp_tpu.obs import log as obs_log
from raydp_tpu.obs import metrics as obs_metrics
from raydp_tpu.obs import span as obs_span
from raydp_tpu.obs import use_context as obs_use_context

_EPS = 1e-9


class _Bundle:
    def __init__(self, index: int, resources: Dict[str, float]):
        self.index = index
        self.resources = dict(resources)
        self.remaining = dict(resources)
        self.node_id: Optional[str] = None


class _PlacementGroup:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]], strategy: str):
        self.pg_id = pg_id
        self.strategy = strategy
        self.bundles = [_Bundle(i, b) for i, b in enumerate(bundles)]
        self.next_bundle = 0  # round-robin cursor (parity: RayAppMaster.getNextBundleIndex, scala:315-323)


class _Actor:
    def __init__(self, spec: ActorSpec):
        self.spec = spec
        self.state = ActorState.PENDING
        self.incarnation = 0
        self.sock_path: Optional[str] = None
        self.node_id: Optional[str] = None
        self.scheduled_bundle: int = -1  # bundle actually charged at schedule time
        self.restarts_used = 0
        self.proc: Optional[subprocess.Popen] = None
        self.intentional_exit = False
        self.error: Optional[str] = None
        self.pending_respawn = False

    def record(self, node_ip: Optional[str]) -> ActorRecord:
        return ActorRecord(
            actor_id=self.spec.actor_id,
            name=self.spec.name,
            state=self.state,
            incarnation=self.incarnation,
            sock_path=self.sock_path,
            node_id=self.node_id,
            node_ip=node_ip,
            restarts_used=self.restarts_used,
            error=self.error,
            resources=dict(self.spec.resources),
        )


class _ObjectMeta:
    """Ownership record for one object-store entry (payload lives in /dev/shm,
    managed by raydp_tpu.store). Parity target: Ray ownership + the reference's
    ownership-transfer path (ObjectStoreWriter.scala:64-85, dataset.py:135-171)."""

    def __init__(
        self, object_id: str, owner: str, shm_name: str, size: int,
        node_id: str, shm_ns: str = "",
    ):
        self.object_id = object_id
        self.owner = owner
        self.shm_name = shm_name
        self.size = size
        self.node_id = node_id
        self.shm_ns = shm_ns
        self.owner_died = False


class Head:
    def __init__(self, session_dir: str, driver_pid: int, default_resources: Dict[str, float]):
        self.session_dir = session_dir
        self.driver_pid = driver_pid
        self.lock = sanitize.named_lock("head.lock", threading.RLock())
        # woken whenever an actor reaches ALIVE or DEAD — lets clients block
        # in handle_wait_actor_ready instead of sleep-polling get_actor
        # (polling put ~1.1s of pure sleep on session startup's critical path).
        # Wrapping the lockdep proxy keeps cond and lock ONE lockdep node —
        # they are the same mutex.
        self.actor_state_cond = threading.Condition(self.lock)
        # shared cluster state, mutated by handler threads AND the monitor
        # loop — every access must hold self.lock (the condition below wraps
        # the same lock). Machine-checked: tools/analyze guarded-by rule.
        self.nodes: Dict[str, NodeRecord] = {}  # guarded-by: self.lock|self.actor_state_cond
        self.node_available: Dict[str, Dict[str, float]] = {}  # guarded-by: self.lock|self.actor_state_cond
        self.actors: Dict[str, _Actor] = {}  # guarded-by: self.lock|self.actor_state_cond
        self.named: Dict[str, str] = {}  # name -> actor_id; guarded-by: self.lock|self.actor_state_cond
        self.pgs: Dict[str, _PlacementGroup] = {}  # guarded-by: self.lock|self.actor_state_cond
        self.objects: Dict[str, _ObjectMeta] = {}  # guarded-by: self.lock|self.actor_state_cond
        # owner-kind metadata: (shm namespace, tenant) -> block-service
        # actor id (one per host per TENANT — every virtual node on a
        # machine shares /dev/shm, so the namespace is the host key; the
        # tenant key is what keeps one session's stop from tombstoning
        # blocks another session's handoffs adopted, the multi-tenant
        # isolation contract). Registrations flagged ``handoff`` are
        # recorded under the writing tenant's LIVE service instead of the
        # writing executor, which is what makes executor death lose zero
        # blocks (store/block_service.py; docs/fault_tolerance.md). A
        # tenant-less registration (key ("", "") — the pre-tenancy shape)
        # serves as the fallback for any tenant in its namespace.
        self.block_services: Dict[tuple, str] = {}  # guarded-by: self.lock|self.actor_state_cond
        # tenant table (raydp_tpu.tenancy, docs/multitenancy.md): one record
        # per named tenant — active flag, fair-share weight, block-bytes
        # quota, and live bytes/blocks accounting charged from the object
        # table by id prefix. Passive records (active=False) accumulate for
        # unregistered tenants so accounting never silently drops bytes.
        self.tenants: Dict[str, dict] = {}  # guarded-by: self.lock|self.actor_state_cond
        # owner-death tombstones: object_id -> dead owner. When an owner
        # dies, its metas are POPPED (proactive unregister — they used to
        # linger as owner_died records until a reader tripped over them)
        # and tombstoned so reads still raise OwnerDiedError (the parity
        # semantics) instead of a clean not-found. Bounded FIFO; a lineage
        # rebind or a delete clears the tombstone.
        import collections as _tomb_collections

        self.owner_tombstones: "_tomb_collections.OrderedDict" = (
            _tomb_collections.OrderedDict()
        )  # guarded-by: self.lock|self.actor_state_cond
        # staged chunks of in-flight proxied puts + per-object last-activity
        # stamps (the TTL sweep in monitor_loop GCs abandoned uploads)
        self._proxy_staging: Dict[str, Dict[int, bytes]] = {}  # guarded-by: self.lock|self.actor_state_cond
        self._proxy_staging_ts: Dict[str, float] = {}  # guarded-by: self.lock|self.actor_state_cond
        self.shutting_down = False
        self._next_ip = 2
        self.tcp_addr: Optional[str] = None  # set by run_head once bound
        # observability aggregation point: every process ships its span ring
        # buffer + metrics snapshot here (obs_ingest); export_trace /
        # dump_metrics read them back (obs_dump). Bounded: the oldest spans
        # drop first, with the drop counted, so a chatty run degrades to a
        # truncated trace instead of unbounded head memory.
        import collections as _collections

        # capacity: ``obs.head_ring_spans`` session conf (obs_configure op)
        # with the legacy env var as the pre-conf fallback
        self.obs_spans: "_collections.deque" = _collections.deque(
            maxlen=int(os.environ.get("RAYDP_TPU_TRACE_HEAD_CAP", "200000"))
        )
        self.obs_dropped = 0
        self.obs_metrics: Dict[str, dict] = {}
        # telemetry plane v2 (docs/observability.md): the ring TSDB behind
        # the Prometheus scrape endpoint + query_metrics, and the flight
        # recorder behind crash dossiers. Both have their own LEAF locks —
        # fed after obs_ingest releases self.lock, read by the scrape
        # thread / dossier writers without ever touching self.lock.
        from raydp_tpu.obs.recorder import DOSSIER_DIR_ENV, FlightRecorder
        from raydp_tpu.obs.timeseries import SeriesStore

        self.tsdb = SeriesStore()
        self.flight = FlightRecorder()
        self.dossier_dir = os.environ.get(DOSSIER_DIR_ENV) or os.path.join(
            session_dir, "dossiers"
        )
        self.scrape_server = None  # guarded-by: self._scrape_lock
        self._scrape_lock = sanitize.named_lock(
            "head.scrape", threading.Lock()
        )
        if default_resources:
            self._add_node(default_resources)

    # ---------- nodes ----------

    def _add_node(  # guarded-by: self.lock|self.actor_state_cond held
        self,
        resources: Dict[str, float],
        node_ip: Optional[str] = None,
        agent_addr: Optional[str] = None,
        shm_ns: str = "",
        host: str = "",
    ) -> str:
        node_id = f"node-{uuid.uuid4().hex[:8]}"
        if node_ip is None:
            node_ip = f"127.0.0.{self._next_ip}"
            self._next_ip += 1
        res = dict(resources)
        res.setdefault("CPU", 1.0)
        res.setdefault("memory", float(4 << 30))
        res[f"node:{node_ip}"] = 1.0
        # host axis: agent-backed nodes report theirs (real box or simulated
        # namespace); head-local virtual nodes share the head's own host
        if not host and not agent_addr:
            host = common_host_id()
        self.nodes[node_id] = NodeRecord(
            node_id, node_ip, res, agent_addr=agent_addr, shm_ns=shm_ns,
            host=host or shm_ns,
        )
        self.node_available[node_id] = dict(res)
        return node_id

    def handle_add_node(self, resources: Dict[str, float], node_ip: Optional[str] = None):
        with self.lock:
            return self._add_node(resources, node_ip)

    def handle_register_agent(
        self,
        resources: Dict[str, float],
        node_ip: str,
        agent_addr: str,
        shm_ns: str,
        host: str = "",
    ):
        """A node agent (another host, or a separate-shm process standing in
        for one) joins the cluster: its actors spawn through the agent and
        its blocks are served by the agent's block server — the multi-host
        parity of the reference's Ray nodes (SURVEY.md L1). ``host`` is the
        agent's position on the host axis (``RAYDP_TPU_HOST_ID``, falling
        back to its shm namespace — docs/cluster.md "Multi-host topology")."""
        with self.lock:
            return self._add_node(
                resources, node_ip, agent_addr=agent_addr, shm_ns=shm_ns,
                host=host,
            )

    def handle_remove_node(self, node_id: str, only_if_empty: bool = False):
        """Kill a virtual node and every actor process on it (elasticity testing,
        parity: ray.cluster_utils.Cluster.remove_node used at reference
        test_spark_cluster.py:166-196). ``only_if_empty`` makes it a safe
        RETIREMENT instead: if any non-DEAD actor sits on the node, return
        False and touch nothing — the tenancy attach-node cleanup path,
        where a co-tenant's actor may have been scheduled onto the capacity
        this tenant added and must never be collateral."""
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                raise ClusterError(f"unknown or dead node {node_id}")
            if only_if_empty and any(
                a.node_id == node_id and a.state != ActorState.DEAD
                for a in self.actors.values()
            ):
                return False
            node.alive = False
            obs_log.warning(
                "node removed", node_id=node_id, node_ip=node.node_ip,
                agent=bool(node.agent_addr),
            )
            obs_instant("cluster.node_removed", node_id=node_id)
            self.node_available[node_id] = {}
            for actor in self.actors.values():
                if actor.node_id == node_id and actor.state in (
                    ActorState.ALIVE,
                    ActorState.PENDING,
                ):
                    self._kill_proc(actor)
                    if actor.proc is None:
                        # agent-hosted actor: there is no local proc for the
                        # monitor to observe (and a dead agent will never
                        # report) — recycle it here
                        self._on_actor_death(actor)
            # the monitor observes local-proc deaths and handles restart/cleanup
        return True

    def handle_nodes(self):
        with self.lock:
            return [n for n in self.nodes.values()]

    def handle_total_resources(self):
        with self.lock:
            return {n.node_id: dict(n.resources) for n in self.nodes.values() if n.alive}

    def handle_available_resources(self):
        with self.lock:
            return {
                n_id: dict(avail)
                for n_id, avail in self.node_available.items()
                if self.nodes[n_id].alive
            }

    # ---------- resource math ----------

    @staticmethod
    def _fits(avail: Dict[str, float], req: Dict[str, float]) -> bool:
        return all(avail.get(k, 0.0) + _EPS >= v for k, v in req.items())

    @staticmethod
    def _sub(avail: Dict[str, float], req: Dict[str, float]) -> None:
        for k, v in req.items():
            avail[k] = avail.get(k, 0.0) - v

    @staticmethod
    def _add(avail: Dict[str, float], req: Dict[str, float]) -> None:
        for k, v in req.items():
            avail[k] = avail.get(k, 0.0) + v

    def _alive_nodes(self) -> List[str]:  # guarded-by: self.lock|self.actor_state_cond held
        return [n_id for n_id, n in self.nodes.items() if n.alive]

    # ---------- placement groups ----------

    def handle_create_placement_group(
        self, bundles: List[Dict[str, float]], strategy: str
    ) -> str:
        """Reserve bundle resources per strategy. Parity: Ray placement groups as
        used by the reference (context.py:94-113, mpi_job.py:192-222)."""
        strategy = strategy.upper()
        if strategy not in ("PACK", "STRICT_PACK", "SPREAD", "STRICT_SPREAD"):
            raise ClusterError(f"unknown placement strategy {strategy}")
        with self.lock:
            pg = _PlacementGroup(f"pg-{uuid.uuid4().hex[:8]}", bundles, strategy)
            placed: List[tuple] = []  # (bundle, node_id) for rollback

            def place(bundle: _Bundle, node_id: str) -> None:  # guarded-by: self.lock|self.actor_state_cond held
                self._sub(self.node_available[node_id], bundle.resources)
                bundle.node_id = node_id
                placed.append((bundle, node_id))

            def rollback() -> None:  # guarded-by: self.lock|self.actor_state_cond held
                for bundle, node_id in placed:
                    self._add(self.node_available[node_id], bundle.resources)

            try:
                if strategy == "STRICT_PACK":
                    for node_id in self._alive_nodes():
                        avail = dict(self.node_available[node_id])
                        ok = True
                        for b in pg.bundles:
                            if not self._fits(avail, b.resources):
                                ok = False
                                break
                            self._sub(avail, b.resources)
                        if ok:
                            for b in pg.bundles:
                                place(b, node_id)
                            break
                    else:
                        raise ClusterError("STRICT_PACK: no single node fits all bundles")
                elif strategy == "STRICT_SPREAD":
                    used: set = set()
                    for b in pg.bundles:
                        for node_id in self._alive_nodes():
                            if node_id not in used and self._fits(
                                self.node_available[node_id], b.resources
                            ):
                                place(b, node_id)
                                used.add(node_id)
                                break
                        else:
                            raise ClusterError(
                                "STRICT_SPREAD: not enough distinct nodes with capacity"
                            )
                else:  # PACK / SPREAD: best effort orderings
                    node_order = self._alive_nodes()
                    for b in pg.bundles:
                        candidates = [
                            n for n in node_order if self._fits(self.node_available[n], b.resources)
                        ]
                        if not candidates:
                            raise ClusterError("placement group does not fit cluster")
                        if strategy == "SPREAD":
                            counts = {n: 0 for n in node_order}
                            for pb, pn in placed:
                                if pn in counts:
                                    counts[pn] += 1
                            candidates.sort(key=lambda n: counts[n])
                        place(b, candidates[0])
            except Exception:
                rollback()
                raise
            self.pgs[pg.pg_id] = pg
            return pg.pg_id

    def handle_remove_placement_group(self, pg_id: str):
        with self.lock:
            pg = self.pgs.pop(pg_id, None)
            if pg is None:
                return False
            for b in pg.bundles:
                if b.node_id is not None and self.nodes[b.node_id].alive:
                    # return whatever of the reservation is still unconsumed
                    self._add(self.node_available[b.node_id], b.remaining)
            return True

    def handle_placement_group_table(self):
        with self.lock:
            return {
                pg_id: {
                    "strategy": pg.strategy,
                    "bundles": [
                        {"index": b.index, "node_id": b.node_id, "resources": b.resources}
                        for b in pg.bundles
                    ],
                }
                for pg_id, pg in self.pgs.items()
            }

    # raydp-lint: disable=rpc-protocol,rpc-closure (round-robin bundle
    # cursor: public PG scheduling surface for Ray-parity callers; no
    # in-tree call site)
    def handle_pg_next_bundle(self, pg_id: str) -> int:
        with self.lock:
            pg = self.pgs[pg_id]
            index = pg.next_bundle % len(pg.bundles)
            pg.next_bundle += 1
            return index

    # ---------- actors ----------

    def _schedule(self, actor: _Actor) -> str:  # guarded-by: self.lock|self.actor_state_cond held
        """Pick a node for the actor and charge resources; raises if nothing fits.
        Records which bundle was charged so death can credit the same bundle."""
        spec = actor.spec
        if spec.placement_group is not None:
            pg = self.pgs.get(spec.placement_group)
            if pg is None:
                raise ClusterError(f"placement group {spec.placement_group} not found")
            index = spec.bundle_index
            if index < 0:
                index = pg.next_bundle % len(pg.bundles)
            bundle = pg.bundles[index]
            if bundle.node_id is None or not self.nodes[bundle.node_id].alive:
                raise ClusterError("placement bundle's node is gone")
            if not self._fits(bundle.remaining, spec.resources):
                raise ClusterError(
                    f"bundle {index} of {pg.pg_id} lacks {spec.resources}, has {bundle.remaining}"
                )
            self._sub(bundle.remaining, spec.resources)
            if spec.bundle_index < 0:
                pg.next_bundle += 1  # advance round-robin only on success
            actor.scheduled_bundle = index
            return bundle.node_id
        for node_id in self._alive_nodes():
            if self._fits(self.node_available[node_id], spec.resources):
                self._sub(self.node_available[node_id], spec.resources)
                actor.scheduled_bundle = -1
                return node_id
        raise ClusterError(
            f"no node can host actor {spec.name or spec.actor_id} "
            f"requiring {spec.resources}; available={self.handle_available_resources()}"
        )

    def _spawn(self, actor: _Actor) -> None:  # guarded-by: self.lock|self.actor_state_cond held
        spec = actor.spec
        node = self.nodes[actor.node_id]
        if node.agent_addr is not None:
            # remote node: the agent forks the worker on its host. The RPC
            # runs on a thread — _spawn is called under the head lock, and a
            # slow/dead agent must not freeze the whole control plane. A
            # failed delivery flips the actor back to pending_respawn, which
            # the monitor retries (and the agent watchdog will kill the node
            # if it stays unreachable).
            agent_addr = node.agent_addr
            incarnation = actor.incarnation
            head_addr = self.tcp_addr

            def _remote_spawn():
                try:
                    rpc(
                        agent_addr,
                        (
                            "spawn_actor",
                            {
                                "spec": spec,
                                "incarnation": incarnation,
                                "head_addr": head_addr,
                            },
                        ),
                        timeout=15,
                    )
                except Exception:
                    fenced = False
                    with self.lock:
                        # ALIVE proves the spawn WAS delivered and the worker
                        # registered — only the RPC reply was lost; fencing
                        # would kill a healthy serving actor
                        if actor.incarnation == incarnation and actor.state not in (
                            ActorState.DEAD,
                            ActorState.ALIVE,
                        ):
                            # credit back what _schedule charged: the retry
                            # path re-schedules (and re-charges) from scratch
                            self._release_actor_resources(actor)
                            # The RPC may have been DELIVERED despite the
                            # timeout: a twin worker could be coming up on the
                            # agent. Fence it out by bumping the incarnation
                            # before the retry respawns — handle_actor_ready /
                            # handle_actor_exited guards then reject the stale
                            # twin, which cannot route calls or recycle the
                            # replacement.
                            actor.incarnation += 1
                            actor.pending_respawn = True
                            fenced = True
                    # Best-effort reap of the possible twin (outside the
                    # lock), keyed by the STALE incarnation: the monitor may
                    # respawn onto this same agent before the kill lands, and
                    # an id-only kill would hit the healthy replacement.
                    if fenced:
                        try:
                            rpc(
                                agent_addr,
                                (
                                    "kill_actor",
                                    {
                                        "actor_id": spec.actor_id,
                                        "incarnation": incarnation,
                                    },
                                ),
                                timeout=3,
                            )
                        except Exception:  # raydp-lint: disable=swallowed-exceptions (best-effort kill of a spawn that lost the incarnation race)
                            pass

            threading.Thread(target=_remote_spawn, daemon=True).start()
            actor.proc = None
            return
        env = dict(os.environ)
        env.update(spec.env)
        env[SESSION_ENV] = self.session_dir
        env["RAYDP_TPU_ACTOR_ID"] = spec.actor_id
        env["RAYDP_TPU_NODE_ID"] = actor.node_id
        env["RAYDP_TPU_NODE_IP"] = node.node_ip
        from raydp_tpu.cluster.common import HEAD_ADDR_ENV, launch_worker

        # head-local workers resolve the head from the env too: a handle
        # pickled by a tcp:// client embeds the CLIENT's local dir, which
        # has no head socket (and on another machine doesn't exist at all)
        env.setdefault(HEAD_ADDR_ENV, head_sock_path(self.session_dir))

        # the fork itself runs OFF the head lock on a thread: a zygote fork
        # of a warmed template costs tens of ms on small boxes (page-table
        # copy), and paying it synchronously under the lock serialized every
        # create_actor behind it — the dominant term of session boot. The
        # same deferred-proc discipline as agent spawns applies: proc lands
        # under the lock when the fork completes, and a kill that raced the
        # spawn reaps the fresh process the moment it is recorded.
        incarnation = actor.incarnation

        def _local_spawn():
            try:
                proc = launch_worker(spec, incarnation, self.session_dir, env)
            except OSError:
                with self.lock:
                    if actor.incarnation == incarnation and actor.state not in (
                        ActorState.DEAD,
                        ActorState.ALIVE,
                    ):
                        self._release_actor_resources(actor)
                        actor.pending_respawn = True
                return
            stale = False
            with self.lock:
                if (
                    actor.incarnation != incarnation
                    or actor.intentional_exit
                    or actor.state == ActorState.DEAD
                ):
                    stale = True  # killed/fenced while forking
                else:
                    actor.proc = proc
            if stale:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except OSError:  # raydp-lint: disable=swallowed-exceptions (kill of an already-dead raced spawn is idempotent)
                    pass

        threading.Thread(target=_local_spawn, daemon=True).start()
        actor.proc = None

    def handle_create_actor(self, spec: ActorSpec) -> str:
        with self.lock:
            if spec.name is not None and spec.name in self.named:
                # a DEAD holder releases its name (Ray semantics: names are
                # reusable after the actor dies; get_actor keeps reporting the
                # dead record only until someone takes the name again). An
                # actor with a no-restart kill in flight counts as dead too —
                # its name can never serve requests again.
                existing = self.actors.get(self.named[spec.name])
                if (
                    existing is None
                    or existing.state == ActorState.DEAD
                    or existing.intentional_exit
                ):
                    del self.named[spec.name]
                else:
                    raise ClusterError(f"actor name {spec.name!r} already taken")
            actor = _Actor(spec)
            actor.node_id = self._schedule(actor)
            try:
                spec_path = os.path.join(self.session_dir, f"a-{spec.actor_id}.spec")
                with open(spec_path, "wb") as f:
                    import cloudpickle

                    cloudpickle.dump(spec, f)
                self.actors[spec.actor_id] = actor
                if spec.name is not None:
                    self.named[spec.name] = spec.actor_id
                self._spawn(actor)
            except BaseException:
                # roll back so a failed spawn doesn't leak resources or the name
                self._release_actor_resources(actor)
                self.actors.pop(spec.actor_id, None)
                if spec.name is not None and self.named.get(spec.name) == spec.actor_id:
                    del self.named[spec.name]
                raise
            return spec.actor_id

    def handle_actor_ready(self, actor_id: str, incarnation: int, sock_path: str):
        with self.lock:
            actor = self.actors[actor_id]
            if incarnation != actor.incarnation:
                return False  # stale incarnation raced with a respawn
            actor.sock_path = sock_path
            actor.state = ActorState.ALIVE
            self.actor_state_cond.notify_all()
            return True

    def handle_wait_actor_ready(self, actor_id: str, timeout: float = 30.0):
        """Block until the actor is ALIVE or DEAD (or the timeout lapses) and
        return its record — the event-driven replacement for clients polling
        get_actor in a sleep loop. Runs on the connection's handler thread;
        the condition wait releases the head lock. The short re-check period
        guards against any state transition that forgets to notify."""
        deadline = time.monotonic() + timeout
        with self.lock:
            while True:
                actor = self.actors.get(actor_id)
                if actor is not None and actor.state in (
                    ActorState.ALIVE,
                    ActorState.DEAD,
                ):
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self.actor_state_cond.wait(min(remaining, 0.25))
            if actor is None:
                return None
            ip = self.nodes[actor.node_id].node_ip if actor.node_id else None
            return actor.record(ip)

    def handle_actor_init_failed(self, actor_id: str, incarnation: int, error: str):
        with self.lock:
            actor = self.actors.get(actor_id)
            if actor is not None and incarnation == actor.incarnation:
                actor.error = error
                actor.intentional_exit = True  # init failure: don't retry-loop
            return True

    def handle_get_actor(self, actor_id: Optional[str] = None, name: Optional[str] = None):
        with self.lock:
            if actor_id is None:
                if name is None or name not in self.named:
                    return None
                actor_id = self.named[name]
            actor = self.actors.get(actor_id)
            if actor is None:
                return None
            ip = self.nodes[actor.node_id].node_ip if actor.node_id else None
            return actor.record(ip)

    def handle_list_actors(self):
        with self.lock:
            return [
                a.record(self.nodes[a.node_id].node_ip if a.node_id else None)
                for a in self.actors.values()
            ]

    def handle_actor_exited(self, actor_id: str, incarnation: int):
        """Agent-reported death of a remote actor (local actors are observed
        directly via proc.poll in the monitor loop)."""
        with self.lock:
            actor = self.actors.get(actor_id)
            if (
                actor is not None
                and actor.incarnation == incarnation
                and actor.state not in (ActorState.DEAD,)
                and not actor.pending_respawn
            ):
                self._on_actor_death(actor)
            return True

    def handle_mark_intentional_exit(self, actor_id: str):
        """Called by an actor about to exit on purpose so the monitor does not
        restart it (parity: Ray.exitActor used precisely for this,
        reference ApplicationInfo.scala:119-124)."""
        with self.lock:
            actor = self.actors.get(actor_id)
            if actor is not None:
                actor.intentional_exit = True
            return True

    def _kill_proc(self, actor: _Actor) -> None:  # guarded-by: self.lock|self.actor_state_cond held
        if actor.proc is not None and actor.proc.poll() is None:
            try:
                os.killpg(actor.proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):  # raydp-lint: disable=swallowed-exceptions (kill of an already-dead process is idempotent)
                pass
            return
        if actor.proc is None and actor.node_id:
            node = self.nodes.get(actor.node_id)
            if node is not None and node.agent_addr is not None:
                agent_addr = node.agent_addr
                actor_id = actor.spec.actor_id

                def _remote_kill():  # off-lock: agents can be slow/dead
                    try:
                        rpc(
                            agent_addr,
                            ("kill_actor", {"actor_id": actor_id}),
                            timeout=10,
                        )
                    except Exception:  # raydp-lint: disable=swallowed-exceptions (agent gone: the node is dead anyway)
                        pass  # agent gone: the node is dead anyway

                threading.Thread(target=_remote_kill, daemon=True).start()

    def handle_kill_actor(self, actor_id: str, no_restart: bool = True):
        with self.lock:
            actor = self.actors.get(actor_id)
            if actor is None:
                return False
            if no_restart:
                actor.intentional_exit = True
            self._kill_proc(actor)
            if actor.proc is not None:
                # fast-path reap: an intentional kill is otherwise only
                # noticed by the 50ms monitor cadence — session stop drains
                # on DEAD state, so observe the SIGKILL promptly off-lock
                threading.Thread(
                    target=self._reap_after_kill, args=(actor,), daemon=True
                ).start()
            else:
                node = self.nodes.get(actor.node_id) if actor.node_id else None
                if node is None or node.agent_addr is None:
                    # local actor whose async fork hasn't landed yet: there
                    # is no process to reap (the spawn thread SIGKILLs the
                    # raced fork when it records the kill) — run the death
                    # bookkeeping now so state() drains to DEAD promptly
                    if actor.state != ActorState.DEAD and not actor.pending_respawn:
                        self._on_actor_death(actor)
            return True

    def _reap_after_kill(self, actor: "_Actor") -> None:
        """Wait (bounded) for a just-SIGKILLed local actor to exit, then run
        the death bookkeeping immediately instead of on the next monitor
        poll. Racing the monitor is safe: both transition under the lock and
        skip actors already DEAD."""
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            # snapshot once per iteration: a concurrent respawn can set
            # actor.proc = None between a check and a .poll() on the bare
            # attribute, AttributeError-ing this reaper thread
            proc = actor.proc
            if proc is None or proc.poll() is not None:
                with self.lock:
                    proc = actor.proc
                    if (
                        actor.state != ActorState.DEAD
                        and not actor.pending_respawn
                        and (proc is None or proc.poll() is not None)
                    ):
                        self._on_actor_death(actor)
                return
            time.sleep(0.005)

    def _release_actor_resources(self, actor: _Actor) -> None:  # guarded-by: self.lock|self.actor_state_cond held
        spec = actor.spec
        if spec.placement_group is not None:
            pg = self.pgs.get(spec.placement_group)
            if pg is not None and 0 <= actor.scheduled_bundle < len(pg.bundles):
                self._add(pg.bundles[actor.scheduled_bundle].remaining, spec.resources)
            actor.scheduled_bundle = -1
            return
        if actor.node_id is not None and self.nodes[actor.node_id].alive:
            self._add(self.node_available[actor.node_id], spec.resources)

    def _on_actor_death(self, actor: _Actor) -> None:  # guarded-by: self.lock|self.actor_state_cond held
        """Monitor-thread callback when an actor process has exited."""
        self._release_actor_resources(actor)
        old_sock = actor.sock_path
        actor.sock_path = None
        if old_sock and not old_sock.startswith("tcp://"):
            try:
                os.unlink(old_sock)
            except OSError:  # raydp-lint: disable=swallowed-exceptions (actor socket may already be unlinked)
                pass
        if actor.intentional_exit or actor.restarts_used >= actor.spec.max_restarts:
            actor.state = ActorState.DEAD
            if not actor.intentional_exit:
                # a crash past max_restarts is a real loss — attributable in
                # the head log AND visible in the trace timeline
                obs_log.error(
                    "actor dead (restarts exhausted)",
                    actor_id=actor.spec.actor_id, name=actor.spec.name,
                    restarts_used=actor.restarts_used, error=actor.error,
                )
            obs_instant(
                "cluster.actor_dead",
                actor_id=actor.spec.actor_id,
                intentional=actor.intentional_exit,
            )
            obs_metrics.counter("cluster.actor_deaths").inc()
            if not self.shutting_down:
                # flight recorder: every terminal actor death (executor,
                # replica, block service — SIGKILLed or crashed) gets a
                # crash dossier with the victim's last shipped rings.
                # Teardown kills are excluded by the shutting_down guard;
                # the write runs on a detached thread (file I/O never under
                # self.lock).
                self._write_crash_dossier(
                    reason=(
                        "actor_killed" if actor.intentional_exit
                        else "actor_crashed"
                    ),
                    victim={
                        "actor_id": actor.spec.actor_id,
                        "name": actor.spec.name,
                        "pid": actor.proc.pid if actor.proc is not None else None,
                        "intentional": actor.intentional_exit,
                        "restarts_used": actor.restarts_used,
                        "error": str(actor.error)[:300] if actor.error else None,
                    },
                    needle=actor.spec.actor_id,
                )
            self.actor_state_cond.notify_all()
            self._on_owner_dead(actor.spec.actor_id)
            # a DEAD block service must not keep adopting registrations —
            # drop its owner-kind entries so handoffs fall back to executor
            # ownership (lineage then covers those blocks, the PR 8 tier)
            for key in [
                key
                for key, a in self.block_services.items()
                if a == actor.spec.actor_id
            ]:
                del self.block_services[key]
            if actor.spec.name is not None:
                # keep the name → id mapping so get_actor(name) reports DEAD
                pass
            return
        actor.restarts_used += 1
        actor.incarnation += 1
        actor.state = ActorState.RESTARTING
        actor.pending_respawn = True
        obs_log.warning(
            "actor crashed; restarting",
            actor_id=actor.spec.actor_id, name=actor.spec.name,
            incarnation=actor.incarnation, restarts_used=actor.restarts_used,
        )
        obs_instant(
            "cluster.actor_restart",
            actor_id=actor.spec.actor_id, incarnation=actor.incarnation,
        )
        obs_metrics.counter("cluster.actor_restarts").inc()
        self._try_respawn(actor)

    def _try_respawn(self, actor: _Actor) -> None:
        try:
            actor.node_id = self._schedule(actor)
        except ClusterError:
            return  # stays pending; retried by the monitor when capacity returns
        actor.pending_respawn = False
        try:
            self._spawn(actor)
        except OSError:
            self._release_actor_resources(actor)
            actor.pending_respawn = True

    # ---------- block services (per-host owner-of-record actors) ----------

    def handle_block_service_register(self, actor_id: str, tenant: str = ""):
        """Adopt a spawned BlockService actor as the owner of record for its
        node's shared-memory namespace (scoped to ``tenant`` when given —
        the multi-tenant shape; a tenant-less registration is the namespace
        fallback any tenant's handoffs may adopt, the pre-tenancy behavior).
        Returns the namespace it serves."""
        with self.lock:
            actor = self.actors.get(actor_id)
            if actor is None:
                raise ClusterError(f"unknown block-service actor {actor_id}")
            node = self.nodes.get(actor.node_id) if actor.node_id else None
            ns = node.shm_ns if node is not None else ""
            self.block_services[(ns, tenant or "")] = actor_id
        obs_instant(
            "block_service.registered", actor_id=actor_id, shm_ns=ns,
            tenant=tenant or "",
        )
        return ns

    def handle_block_service_unregister(self, actor_id: str):
        """Drop a service from the owner-kind table (A/B toggle; its already-
        owned blocks keep their owner — only FUTURE handoffs fall back)."""
        with self.lock:
            for key in [
                key for key, a in self.block_services.items() if a == actor_id
            ]:
                del self.block_services[key]
        return True

    def handle_block_service_lookup(self, shm_ns: str = "", tenant: str = ""):
        with self.lock:
            return self._service_for(shm_ns, tenant)

    def handle_block_service_peers(self):
        """Every LIVE, tcp-reachable block service with its host-axis row —
        the spill-to-remote tier's target list (store._remote_spill_peer).
        Only ALIVE services with a tcp socket qualify: a remote writer must
        be able to dial the address it gets back right now."""
        with self.lock:
            rows = []
            for (ns, tenant), actor_id in self.block_services.items():
                actor = self.actors.get(actor_id)
                if (
                    actor is None
                    or actor.state != ActorState.ALIVE
                    or not actor.sock_path
                    or not actor.sock_path.startswith("tcp://")
                ):
                    continue
                node = self.nodes.get(actor.node_id) if actor.node_id else None
                rows.append({
                    "actor_id": actor_id,
                    "shm_ns": ns,
                    "tenant": tenant,
                    "host": node.host if node is not None else ns,
                    "service_addr": actor.sock_path,
                })
            return rows

    def _service_for(self, shm_ns: str, tenant: str) -> Optional[str]:  # guarded-by: self.lock|self.actor_state_cond held
        """The block service serving (namespace, tenant): the tenant-scoped
        entry first, then the namespace's tenant-less fallback. A tenant-
        scoped service NEVER serves another tenant — that is what keeps
        tenant A's session stop (which kills A's service and tombstones its
        blocks) from ever owning B's blocks."""
        service = self.block_services.get((shm_ns, tenant or ""))
        if service is None and tenant:
            service = self.block_services.get((shm_ns, ""))
        return service

    def _effective_owner(  # guarded-by: self.lock|self.actor_state_cond held
        self, owner: str, shm_ns: str, handoff: bool, tenant: str = ""
    ) -> str:
        """The owner of record for a new registration: the (namespace,
        tenant)'s LIVE block service when the writer flagged the entry for
        handoff, else the writer itself. Deciding HERE (the head knows actor
        liveness authoritatively) means a dead/bouncing service degrades
        registrations to executor ownership instead of parking blocks on a
        corpse owner that no death event will ever GC."""
        if not handoff:
            return owner
        service = self._service_for(shm_ns, tenant)
        if service is None or service == owner:
            return owner
        actor = self.actors.get(service)
        if (
            actor is None
            or actor.state == ActorState.DEAD
            or actor.intentional_exit
        ):
            return owner
        obs_metrics.counter("block_service.adopted_blocks").inc()
        return service

    # ---------- tenant table (raydp_tpu.tenancy) ----------

    def handle_tenant_register(
        self, name: str, weight: float = 1.0, max_block_bytes: int = 0,
    ):
        """Admit a named tenant (one ``init_etl(app_name=...)`` attach).
        Rejects a duplicate ACTIVE registration — the cross-driver half of
        the session-singleton guard; re-registering a stopped tenant keeps
        its accumulated byte accounting (blocks can outlive a session via
        ownership transfer)."""
        with self.lock:
            record = self.tenants.get(name)
            if record is not None and record.get("active"):
                raise ClusterError(
                    f"tenant {name!r} is already running on this cluster; "
                    "stop it (or pick another app_name) first"
                )
            if record is None:
                record = {"name": name, "bytes_stored": 0, "blocks": 0}
                self.tenants[name] = record
            record.update(
                active=True,
                weight=float(weight),
                max_block_bytes=int(max_block_bytes),
            )
            # the gauge exists from registration on, so dump_metrics carries
            # the per-tenant key even before the first block lands (pinned-
            # schema tests and dashboards rely on the keys existing)
            self._tenant_gauge(record).set(record["bytes_stored"])
        obs_instant("tenant.registered", tenant=name)
        obs_metrics.counter("tenant.registrations").inc()
        return name

    def handle_tenant_unregister(self, name: str):
        """Mark a tenant inactive (its session stopped). The record — and
        its byte accounting — survives: transferred blocks may outlive the
        session, and a later re-attach under the same name resumes it."""
        with self.lock:
            record = self.tenants.get(name)
            if record is not None:
                record["active"] = False
        obs_instant("tenant.unregistered", tenant=name)
        return record is not None

    def handle_tenant_list(self):
        with self.lock:
            return {
                name: {k: v for k, v in r.items() if not k.startswith("_")}
                for name, r in self.tenants.items()
            }

    @staticmethod
    def _tenant_gauge(record: dict):  # guarded-by: self.lock|self.actor_state_cond held
        """The tenant's bytes_stored gauge, cached ON the record: the
        charge/credit paths run per block under the head lock (a wide
        shuffle batch registers thousands of entries in one hold) and must
        not pay an f-string build + registry-locked lookup each time."""
        gauge = record.get("_gauge")
        if gauge is None:
            gauge = record["_gauge"] = obs_metrics.gauge(
                f"tenant.{record['name']}.bytes_stored"
            )
        return gauge

    def _tenant_record(self, tenant: str) -> Optional[dict]:  # guarded-by: self.lock|self.actor_state_cond held
        if not tenant:
            return None
        record = self.tenants.get(tenant)
        if record is None:
            # unregistered writer (transferred survivors, out-of-band
            # tools): account passively, enforce nothing
            record = {
                "name": tenant, "bytes_stored": 0, "blocks": 0,
                "active": False, "weight": 1.0, "max_block_bytes": 0,
            }
            self.tenants[tenant] = record
        return record

    def _tenant_charge(  # guarded-by: self.lock|self.actor_state_cond held
        self, object_id: str, size: int, enforce: bool = True
    ) -> None:
        """Charge a registration against its tenant's block-bytes quota
        BEFORE inserting the meta; raises the typed quota error instead of
        admitting the block (the writer's registration fails cleanly and
        its segment is unlinked by the seal/batch failure paths).
        ``enforce=False`` moves accounting without the quota check — the
        rebind path, which re-registers bytes that were ALREADY admitted
        (a quota raise there would drop the popped meta mid-recovery)."""
        record = self._tenant_record(tenant_of_object(object_id))
        if record is None:
            return
        limit = int(record.get("max_block_bytes") or 0) if enforce else 0
        if limit and record["bytes_stored"] + size > limit:
            obs_metrics.counter(
                f"tenant.{record['name']}.quota_rejections"
            ).inc()
            err = TenantQuotaError(
                f"tenant {record['name']!r} block-bytes quota exceeded: "
                f"{record['bytes_stored']} stored + {size} new > {limit}"
            )
            err.tenant = record["name"]
            raise err
        record["bytes_stored"] += size
        record["blocks"] += 1
        self._tenant_gauge(record).set(record["bytes_stored"])

    def _tenant_credit(self, meta: "_ObjectMeta") -> None:  # guarded-by: self.lock|self.actor_state_cond held
        record = self.tenants.get(tenant_of_object(meta.object_id))
        if record is None:
            return
        record["bytes_stored"] = max(0, record["bytes_stored"] - meta.size)
        record["blocks"] = max(0, record["blocks"] - 1)
        self._tenant_gauge(record).set(record["bytes_stored"])

    # ---------- object ownership table ----------

    def handle_object_put(
        self, object_id: str, owner: str, shm_name: str, size: int,
        node_id: str, shm_ns: str = "", handoff: bool = False,
    ):
        """Register one block. Returns the EFFECTIVE owner (the writing
        tenant's block service for handoff entries) so the writer can
        correct its location cache and the metas it pushes to peers."""
        with self.lock:
            self._tenant_charge(object_id, size)
            owner = self._effective_owner(
                owner, shm_ns, handoff, tenant_of_object(object_id)
            )
            self.objects[object_id] = _ObjectMeta(
                object_id, owner, shm_name, size, node_id, shm_ns
            )
            return owner

    # a proxied put whose client died between chunk RPCs and commit would
    # otherwise pin up to the full object size in head memory forever; the
    # monitor GCs staging entries idle longer than this (each arriving chunk
    # refreshes the stamp, so slow-but-live uploads are never collected)
    PROXY_STAGING_TTL_S = 300.0

    def handle_object_put_proxy_chunk(self, object_id: str, seq: int, payload: bytes):
        """One chunk of a large proxied put (the client chunks to stay under
        the frame cap); staged until commit."""
        with self.lock:
            self._proxy_staging.setdefault(object_id, {})[seq] = payload
            self._proxy_staging_ts[object_id] = time.monotonic()
        return True

    def _gc_proxy_staging(self, now: float) -> None:  # guarded-by: self.lock|self.actor_state_cond held
        """Drop staged proxied-put chunks whose client went silent (lock held)."""
        for object_id in [
            o
            for o, t in self._proxy_staging_ts.items()
            if now - t > self.PROXY_STAGING_TTL_S
        ]:
            self._proxy_staging_ts.pop(object_id, None)
            self._proxy_staging.pop(object_id, None)

    def handle_object_put_proxy_abort(self, object_id: str):
        """Client-initiated cleanup of a partially staged proxied put."""
        with self.lock:
            self._proxy_staging.pop(object_id, None)
            self._proxy_staging_ts.pop(object_id, None)
        return True

    def handle_object_put_proxy_commit(
        self, object_id: str, owner: str, total_chunks: int,
        storage: str = "auto",
    ):
        with self.lock:
            chunks = self._proxy_staging.pop(object_id, {})
            self._proxy_staging_ts.pop(object_id, None)
        if len(chunks) != total_chunks:
            raise ClusterError(
                f"proxied put {object_id}: {len(chunks)}/{total_chunks} "
                "chunks arrived"
            )
        payload = b"".join(chunks[i] for i in range(total_chunks))
        return self.handle_object_put_proxy(object_id, payload, owner, storage)

    def handle_object_put_proxy(
        self, object_id: str, payload: bytes, owner: str, storage: str = "auto"
    ):
        """Host a tcp:// client's block on the HEAD node (ray-client put
        parity: the reference's client drivers proxy ``ray.put`` through the
        server). The client has no block server, so the head writes the
        bytes into its own shm (or disk tier) and serves every read; the
        metadata registers under the head's node for locality."""
        from raydp_tpu.store.object_store import host_block_locally

        shm_name = host_block_locally(
            object_id, payload,
            spill_dir=os.path.join(self.session_dir, "spill"),
            storage=storage,
        )
        try:
            with self.lock:
                self._tenant_charge(object_id, len(payload))
                # registered as a DRIVER block, exactly like a put from a
                # local driver: readable everywhere (object_lookup's
                # fetch_addr falls back to the head, which holds the bytes),
                # and invisible to locality-aware dispatch — proxied source
                # blocks must not pin every consumer task onto the head node
                self.objects[object_id] = _ObjectMeta(
                    object_id, owner, shm_name, len(payload), "driver", ""
                )
        except TenantQuotaError:
            # the bytes were already hosted: an over-quota rejection must
            # not leak the just-written segment on the head node
            self._unlink_shm(shm_name)
            raise
        return True

    def _meta_view(self, object_id: str, meta: "_ObjectMeta") -> dict:  # guarded-by: self.lock|self.actor_state_cond held
        """Client-facing lookup record for one object (lock held). Where a
        non-local reader can pull the bytes: the owning node's agent, or the
        head itself for head-node objects (parity: plasma locality +
        RayDatasetRDD owner addresses, SURVEY §2.2 S8). The WRITER-recorded
        namespace is authoritative — a tcp client's blocks carry its
        namespace even though its "node" is the driver."""
        if meta.owner_died:
            self._raise_owner_died(object_id, meta.owner)
        node = self.nodes.get(meta.node_id)
        if node is not None and node.agent_addr is not None:
            fetch_addr = node.agent_addr
        else:
            fetch_addr = self.tcp_addr
        view = {
            "shm_name": meta.shm_name,
            "size": meta.size,
            "owner": meta.owner,
            "node_id": meta.node_id,
            "shm_ns": meta.shm_ns,
            # host axis: readers attribute bytes-over-wire per host edge
            # and the planner scores placement against it
            "host": node.host if node is not None else meta.shm_ns,
            "fetch_addr": fetch_addr,
        }
        # service-owned block: advertise the owner's own socket so remote
        # readers can pull from the first-class owner (TCP only — same-host
        # readers map shm directly and never fetch). fetch_addr stays the
        # agent/head fallback for the service's restart window.
        if meta.owner == self._service_for(
            meta.shm_ns, tenant_of_object(meta.object_id)
        ):
            actor = self.actors.get(meta.owner)
            if (
                actor is not None
                and actor.state == ActorState.ALIVE
                and actor.sock_path
                and actor.sock_path.startswith("tcp://")
            ):
                view["service_addr"] = actor.sock_path
        return view

    def handle_object_lookup(self, object_id: str):
        with self.lock:
            meta = self.objects.get(object_id)
            if meta is None:
                owner = self.owner_tombstones.get(object_id)
                if owner is not None:
                    self._raise_owner_died(object_id, owner)
                return None
            return self._meta_view(object_id, meta)

    def handle_object_put_batch(self, entries: List[dict]):
        """Vectorized registration: one RPC frame registers every block a
        task batch produced (the per-block object_put is the hot metadata
        call of the shuffle map side — M×R frames collapse to one per
        task). Returns ``{object_id: effective_owner}`` for the entries the
        block-service handoff reassigned (empty on the non-handoff path),
        so the writer's cache stays truthful in the same round trip."""
        reassigned: Dict[str, str] = {}
        with self.lock:
            for e in entries:
                # quota check first: a mid-batch rejection leaves earlier
                # entries registered — the writer's batched_registration
                # failure path deletes the whole batch through the head,
                # which credits them back
                self._tenant_charge(e["object_id"], e["size"])
                owner = self._effective_owner(
                    e["owner"], e.get("shm_ns", ""), bool(e.get("handoff")),
                    tenant_of_object(e["object_id"]),
                )
                if owner != e["owner"]:
                    reassigned[e["object_id"]] = owner
                self.objects[e["object_id"]] = _ObjectMeta(
                    e["object_id"], owner, e["shm_name"], e["size"],
                    e["node_id"], e.get("shm_ns", ""),
                )
        return reassigned

    def _batch_meta(self, oid: str, lease: bool):  # guarded-by: self.lock|self.actor_state_cond held
        """One batch entry. Tombstones were already handled: both callers
        pre-raise via _raise_tombstoned_batch (which names EVERY tombstoned
        id of the batch), so an absent id here is a plain None."""
        meta = self.objects.get(oid)
        if meta is None:
            return None
        view = self._meta_view(oid, meta)
        if lease:
            view["lease_s"] = self.LOCATION_LEASE_S
        return view

    def handle_object_lookup_batch(self, object_ids: List[str]):
        """Vectorized lookup: {object_id: meta-or-None} in one frame (the
        reduce side resolves every input slice's block with a single RPC).
        An owner-died object raises, exactly like the single lookup — with
        EVERY tombstoned id of the batch named in the error."""
        with self.lock:
            self._raise_tombstoned_batch(object_ids)
            return {
                oid: self._batch_meta(oid, lease=False) for oid in object_ids
            }

    # how long a client may act on a served location without re-asking: the
    # head-bypass contract (store.cached_location honors it; expired entries
    # take the miss path back here)
    LOCATION_LEASE_S = 120.0

    def handle_object_lookup_lease(self, object_ids: List[str]):
        """Vectorized lookup returning lease-stamped location records:
        ``{object_id: meta-or-None}`` where each meta carries ``lease_s`` —
        the head's promise that acting on the location for that long without
        re-asking is safe (blocks never move; deletion/owner-death makes a
        stale read FAIL, and the reader's fallback re-asks the head, which
        is authoritative). The miss path of the executors' peer-to-peer
        block resolution (store.lookup_many)."""
        with self.lock:
            self._raise_tombstoned_batch(object_ids)
            return {
                oid: self._batch_meta(oid, lease=True) for oid in object_ids
            }

    def handle_object_locations(self, object_ids: List[str]):
        """Batch block→node lookup for locality-aware task dispatch (parity:
        getPreferredLocations, reference RayDatasetRDD.scala:53-55)."""
        with self.lock:
            return {
                oid: self.objects[oid].node_id
                for oid in object_ids
                if oid in self.objects and not self.objects[oid].owner_died
            }

    def handle_object_hosts(self, object_ids: List[str]):
        """Batch block→(host, size) lookup — the host-axis twin of
        ``object_locations`` the planner's reduce/exchange placement scorer
        consumes (obs/costmodel.exchange_placement): it needs BYTES per
        host, not just node ids, to put a reducer where its input lives."""
        with self.lock:
            out: Dict[str, tuple] = {}
            for oid in object_ids:
                meta = self.objects.get(oid)
                if meta is None or meta.owner_died:
                    continue
                node = self.nodes.get(meta.node_id)
                host = node.host if node is not None else meta.shm_ns
                out[oid] = (host, meta.size)
            return out

    def handle_block_fetch(self, shm_name: str, offset: int = 0, length: int = -1):
        """Serve a head-node block's bytes to a remote reader (the head plays
        block server for namespace-'' objects; agents serve their own).
        ``offset``/``length`` let readers pull huge blocks in chunks under
        the frame-size cap."""
        from raydp_tpu.cluster.common import serve_block_bytes

        return serve_block_bytes(shm_name, offset, length)

    def handle_object_transfer_owner(self, object_ids: List[str], new_owner: str):
        """Ownership transfer: data outlives the engine that produced it
        (parity: _use_owner path, reference dataset.py:157-171 +
        ObjectStoreWriter.scala:70-79)."""
        with self.lock:
            for object_id in object_ids:
                meta = self.objects.get(object_id)
                if meta is not None and not meta.owner_died:
                    meta.owner = new_owner
            return True

    def handle_object_delete(self, object_ids: List[str]):
        with self.lock:
            metas = [
                meta
                for object_id in object_ids
                if (meta := self.objects.pop(object_id, None)) is not None
            ]
            for meta in metas:
                self._tenant_credit(meta)
            for object_id in object_ids:
                # deleting a tombstoned id makes later reads a clean
                # not-found (deliberate deletion), not OwnerDiedError
                self.owner_tombstones.pop(object_id, None)
        self._unlink_objects(metas)
        return True

    def handle_object_rebind(self, mapping: Dict[str, str]):
        """Lineage-recovery rebind: re-register each freshly regenerated
        block (``new_id``, just written + registered by a surviving
        executor) under its ORIGINAL object id, clearing the owner-death
        tombstone — in-flight readers holding the old refs re-resolve and
        find live bytes. Returns how many ids were rebound; a missing
        new-id entry (racing deletion) is skipped and reflected in the
        count so the recovery driver can fail loudly instead of serving a
        half-rebound exchange."""
        rebound = 0
        duplicates: List[_ObjectMeta] = []
        with self.lock:
            for old_id, new_id in mapping.items():
                meta = self.objects.pop(new_id, None)
                if meta is None:
                    continue
                # accounting moves with the id: credit the regenerated id,
                # charge the original UNENFORCED (these bytes were already
                # admitted at registration; a re-attach that shrank the
                # quota below live bytes must not make recovery drop the
                # popped meta mid-loop)
                self._tenant_credit(meta)
                live = self.objects.get(old_id)
                if live is not None and not live.owner_died:
                    # duplicate recovery: another recoverer already rebound
                    # this id — the old ref is LIVE. Keep the winner's meta
                    # and unlink THIS duplicate's freshly written segment
                    # (overwriting would orphan one segment either way);
                    # counted as rebound because the caller's goal — the
                    # old id resolves to live bytes — holds.
                    duplicates.append(meta)
                    rebound += 1
                    continue
                self._tenant_charge(old_id, meta.size, enforce=False)
                meta.object_id = old_id
                self.objects[old_id] = meta
                self.owner_tombstones.pop(old_id, None)
                rebound += 1
        if duplicates:
            # off-lock like every unlink path (agent RPCs can be slow)
            self._unlink_objects(duplicates)
        if rebound:
            obs_metrics.counter("head.objects_rebound").inc(rebound)
            obs_instant("lineage.rebound", blocks=rebound)
        return rebound

    def _unlink_objects(self, metas: List["_ObjectMeta"], wait: bool = False) -> None:
        """Release segments, routing remote-node objects through their agent.
        Never called under the lock (agent RPCs can be slow). ``wait=True``
        (shutdown path) performs the agent RPCs synchronously — fire-and-
        forget threads would race the agents' own teardown and leak
        /dev/shm segments."""
        by_agent: Dict[str, List[str]] = {}
        with self.lock:  # snapshot: the routing loop itself stays off-lock
            nodes = dict(self.nodes)
        for meta in metas:
            node = nodes.get(meta.node_id)
            if node is not None and node.agent_addr is not None:
                by_agent.setdefault(node.agent_addr, []).append(meta.shm_name)
            else:
                self._unlink_shm(meta.shm_name)
        for agent_addr, names in by_agent.items():
            def _fire(addr=agent_addr, shm_names=names):
                try:
                    rpc(addr, ("unlink_shm", {"shm_names": shm_names}), timeout=10)
                except Exception:
                    # agent gone: its /dev/shm died with the node — but a
                    # LIVE node failing unlinks would leak segments, so
                    # count it (the store.delete_failures lesson)
                    obs_metrics.counter("head.unlink_shm_failures").inc(
                        len(shm_names)
                    )

            if wait:
                _fire()
            else:
                threading.Thread(target=_fire, daemon=True).start()

    def handle_object_reown_all(self, old_owner: str, new_owner: str) -> int:
        """Transfer EVERY live object owned by ``old_owner`` to ``new_owner``
        — the graceful-scale-down primitive: executors killed by dynamic
        allocation (or kill_executors) must not take still-referenced blocks
        with them (their shm segments/spill files survive the process; only
        owner-death GC would destroy them)."""
        moved = 0
        with self.lock:
            for meta in self.objects.values():
                if meta.owner == old_owner and not meta.owner_died:
                    meta.owner = new_owner
                    moved += 1
        return moved

    def handle_object_owner_of(self, object_id: str):
        with self.lock:
            meta = self.objects.get(object_id)
            return None if meta is None else meta.owner

    @staticmethod
    def _unlink_shm(shm_name: str) -> None:
        from raydp_tpu.cluster.common import unlink_block

        unlink_block(shm_name)

    TOMBSTONE_CAP = 16384

    def _tombstone(self, object_id: str, owner: str) -> None:  # guarded-by: self.lock|self.actor_state_cond held
        self.owner_tombstones[object_id] = owner
        self.owner_tombstones.move_to_end(object_id)
        while len(self.owner_tombstones) > self.TOMBSTONE_CAP:
            self.owner_tombstones.popitem(last=False)

    def _raise_owner_died(self, object_id: str, owner: str) -> None:
        """OwnerDiedError carrying structured fields: the client's lineage
        recovery reads ``object_ids`` and its dead-owner fast path reads
        ``owner`` (BaseException pickling preserves the instance dict)."""
        err = OwnerDiedError(
            f"object {object_id}: owner {owner!r} died and the object was "
            "not transferred before the owner exited"
        )
        err.object_ids = [object_id]
        err.owner = owner
        raise err

    def _raise_tombstoned_batch(self, object_ids: List[str]) -> None:  # guarded-by: self.lock|self.actor_state_cond held
        """Raise for a batch naming EVERY tombstoned id in it, not just the
        first: the client's lineage recovery re-executes the whole named set
        in one round — one-id-at-a-time errors would burn one retry attempt
        per lost block and exhaust the task ladder on wide losses."""
        dead = {
            oid: owner
            for oid in object_ids
            if oid not in self.objects
            and (owner := self.owner_tombstones.get(oid)) is not None
        }
        if not dead:
            return
        err = OwnerDiedError(
            f"object(s) {list(dead)[:3]}{'…' if len(dead) > 3 else ''}: "
            f"owner(s) died and the objects were not transferred "
            f"({len(dead)} of {len(object_ids)} requested)"
        )
        err.object_ids = list(dead)
        err.owner = next(iter(dead.values()))
        raise err

    def _on_owner_dead(self, owner: str) -> None:  # guarded-by: self.lock|self.actor_state_cond held
        dead = []
        for meta in list(self.objects.values()):
            if meta.owner == owner and not meta.owner_died:
                meta.owner_died = True
                dead.append(meta)
                # proactive unregister: pop the record NOW (an intentional
                # kill_executors/stop used to leave owner-died metas in the
                # table forever) and tombstone the id so reads keep raising
                # OwnerDiedError until a lineage rebind revives it
                del self.objects[meta.object_id]
                self._tenant_credit(meta)
                self._tombstone(meta.object_id, owner)
        if dead:
            obs_metrics.counter("head.objects_unregistered").inc(len(dead))
            # called under the lock (monitor/death paths): release segments
            # from a thread so a slow/dead agent can't stall the head
            threading.Thread(
                target=self._unlink_objects, args=(dead,), daemon=True
            ).start()

    # ---------- observability (obs layer aggregation) ----------

    def handle_obs_ingest(
        self, proc: dict, spans: List[dict], metrics_snapshot: dict,
        logs: Optional[List[dict]] = None,
    ):
        """A process flushed its span ring buffer + metrics registry (+ its
        flight-recorder log ring) here. Metrics snapshots are cumulative per
        process — replace, keyed by (role, pid); spans append into the
        bounded deque, with evictions counted PER ROLE
        (``obs.ingest_evictions.<role>``) so a chatty role squeezing the
        others out of the trace ring is visible in ``dump_metrics``."""
        role = proc.get("role", "proc")
        key = f"{role}:{proc.get('pid', 0)}"
        with self.lock:
            if spans:
                overflow = (
                    len(self.obs_spans) + len(spans) - (self.obs_spans.maxlen or 0)
                )
                if overflow > 0:
                    self.obs_dropped += overflow
                    # the evicted spans are the OLDEST resident entries (or,
                    # past capacity, the head of the incoming batch): count
                    # each against its own role so the victim is named
                    evicted = list(
                        _itertools.islice(self.obs_spans, 0, overflow)
                    )
                    if overflow > len(self.obs_spans):
                        evicted.extend(spans[: overflow - len(evicted)])
                    by_role: Dict[str, int] = {}
                    for record in evicted:
                        victim_role = str(
                            record.get("proc", "proc")
                        ).split(":", 1)[0]
                        by_role[victim_role] = by_role.get(victim_role, 0) + 1
                    for victim_role, count in by_role.items():
                        obs_metrics.counter(
                            f"obs.ingest_evictions.{victim_role}"
                        ).inc(count)
                self.obs_spans.extend(spans)
            if metrics_snapshot:
                metrics_snapshot = dict(metrics_snapshot)
                if proc.get("dropped"):
                    metrics_snapshot["trace.spans_dropped"] = {
                        "type": "counter", "value": proc["dropped"],
                    }
                self.obs_metrics[key] = metrics_snapshot
        # TSDB + flight recorder rides OUTSIDE self.lock: both have their
        # own leaf locks, and neither belongs on the actor-table critical
        # section (a scrape-sized ingest must not stall spawns)
        if metrics_snapshot:
            self.tsdb.ingest(key, role, metrics_snapshot)
        self.flight.note_ingest(key, role, spans or [], metrics_snapshot, logs)
        return True

    def handle_obs_configure(
        self,
        head_ring_spans: Optional[int] = None,
        dossier_dir: Optional[str] = None,
        scrape_port: Optional[int] = None,
    ):
        """Session-boot configuration of the telemetry plane (``obs.*``
        confs, docs/observability.md): resize the head span ring, point the
        dossier dir, and/or start the Prometheus scrape endpoint (idempotent
        — a second session reuses the running server). Returns the live
        settings including the bound scrape address."""
        import collections as _collections

        with self.lock:
            if head_ring_spans is not None and int(head_ring_spans) > 0:
                cap = int(head_ring_spans)
                if cap != (self.obs_spans.maxlen or 0):
                    self.obs_spans = _collections.deque(
                        self.obs_spans, maxlen=cap
                    )
            if dossier_dir:
                self.dossier_dir = str(dossier_dir)
            ring_cap = self.obs_spans.maxlen
            out_dir = self.dossier_dir
        if scrape_port is not None:
            addr = self._ensure_scrape_server(int(scrape_port))
        else:
            addr = self.handle_obs_scrape_addr()
        return {
            "head_ring_spans": ring_cap,
            "dossier_dir": out_dir,
            "scrape_addr": addr,
        }

    def _ensure_scrape_server(self, port: int):
        """Start (or return) the scrape endpoint. Serialized by its own
        LEAF lock (never self.lock — the bind is I/O), so two sessions
        configuring at once cannot race a second live server into
        existence: one server serves, every caller gets its address."""
        with self._scrape_lock:
            server = self.scrape_server
            if server is None:
                from raydp_tpu.obs.timeseries import ScrapeServer

                server = self.scrape_server = ScrapeServer(
                    self.tsdb, port=port
                )
                obs_log.info(
                    "scrape endpoint up", host=server.host, port=server.port
                )
            return (server.host, server.port)

    def handle_obs_scrape_addr(self):
        with self._scrape_lock:
            server = self.scrape_server
            return (server.host, server.port) if server is not None else None

    def close_scrape_server(self) -> None:
        with self._scrape_lock:
            server = self.scrape_server
            self.scrape_server = None
        if server is not None:
            server.close()

    def handle_obs_query_series(
        self,
        name,
        window_s: float = 60.0,
        labels: Optional[dict] = None,
        aggregate: bool = False,
    ):
        """``cluster.query_metrics`` read side: matching series from the
        head TSDB (or the windowed aggregate). ``name`` may be a LIST of
        metric names — one round trip answers a whole signal group
        (``tenancy.fair_share_series`` reads five in one RPC), returned as
        ``{name: result}``."""
        if isinstance(name, (list, tuple)):
            return {
                n: (
                    self.tsdb.windowed(n, window_s, labels) if aggregate
                    else self.tsdb.query(n, window_s, labels)
                )
                for n in name
            }
        if aggregate:
            return self.tsdb.windowed(name, window_s, labels)
        return self.tsdb.query(name, window_s, labels)

    def handle_obs_dossier(
        self, reason: str, victim: Optional[dict] = None,
        needle: Optional[str] = None,
    ):
        """Driver-triggered dossier (unrecovered query, sanitizer finding):
        assemble + write synchronously and return the path."""
        head_state = self._dossier_head_state()
        victim_keys = (
            self.flight.find_victim_keys(needle) if needle
            else self.flight.proc_keys()
        )
        dossier = self.flight.assemble(
            reason, victim_keys=victim_keys, victim=victim,
            head_state=head_state,
        )
        path = self.flight.write(dossier, self.dossier_dir)
        if path:
            obs_metrics.counter("obs.dossiers_written").inc()
        return path

    def _dossier_head_state(self) -> dict:
        """Snapshot of the head's authoritative tables for a dossier —
        cheap dict building only."""
        with self.lock:
            actors = [
                {
                    "actor_id": a.spec.actor_id,
                    "name": a.spec.name,
                    "state": str(a.state),
                    "pid": a.proc.pid if a.proc is not None else None,
                    "node": a.node_id,
                    "incarnation": a.incarnation,
                    "restarts_used": a.restarts_used,
                    "intentional_exit": a.intentional_exit,
                    "error": str(a.error)[:300] if a.error else None,
                }
                for a in self.actors.values()
            ]
            tenants = {
                name: {
                    k: v for k, v in record.items()
                    if isinstance(v, (int, float, str, bool))
                }
                for name, record in self.tenants.items()
            }
            # memory watermark plane (obs/profiler.py): every process's
            # newest mem.* gauges (live value + high watermark) from its
            # shipped registry snapshot — the dossier's memory section
            memory = {}
            for proc_key, snapshot in self.obs_metrics.items():
                mem = {
                    name: {
                        "value": snap.get("value"),
                        "max": snap.get("max"),
                    }
                    for name, snap in snapshot.items()
                    if name.startswith("mem.") and isinstance(snap, dict)
                }
                if mem:
                    memory[proc_key] = mem
            return {
                "actors": actors,
                "tenants": tenants,
                "memory": memory,
                "objects": len(self.objects),
                "block_services": {
                    f"{ns or '-'}::{tenant or '-'}": actor_id
                    for (ns, tenant), actor_id in self.block_services.items()
                },
                "nodes": len(self.nodes),
                "obs_ring": {
                    "spans": len(self.obs_spans),
                    "cap": self.obs_spans.maxlen,
                    "dropped": self.obs_dropped,
                },
            }

    def _write_crash_dossier(self, reason: str, victim: dict,
                             needle: str) -> None:
        """Assemble + write a dossier for one actor death on a DETACHED
        thread: the caller holds self.lock (monitor/death paths) and the
        write is file I/O."""
        head_state = self._dossier_head_state()

        def _write():
            try:
                dossier = self.flight.assemble(
                    reason,
                    victim_keys=self.flight.find_victim_keys(needle),
                    victim=victim, head_state=head_state,
                )
                if self.flight.write(dossier, self.dossier_dir):
                    obs_metrics.counter("obs.dossiers_written").inc()
            except Exception:  # raydp-lint: disable=swallowed-exceptions (dossiers are evidence, never a new failure mode: a full disk must not take the death path down)
                pass

        threading.Thread(target=_write, name="dossier-writer",
                         daemon=True).start()

    def handle_obs_dump(self, clear: bool = False):
        """Everything collected so far (export_trace / dump_metrics read
        side). The head contributes its own local buffer and registry too —
        it never RPCs itself."""
        from raydp_tpu.obs.metrics import metrics as local_metrics
        from raydp_tpu.obs.tracing import drain_local, process_role

        own = drain_local()
        with self.lock:
            if own:
                self.obs_spans.extend(own)
            snapshot = local_metrics.snapshot()
            if snapshot:
                self.obs_metrics[f"{process_role()}:{os.getpid()}"] = snapshot
            out = {
                "spans": list(self.obs_spans),
                "metrics": dict(self.obs_metrics),
                "dropped": self.obs_dropped,
            }
            if clear:
                self.obs_spans.clear()
                self.obs_metrics.clear()
                self.obs_dropped = 0
        return out

    # ---------- lifecycle ----------

    def handle_ping(self):
        return "pong"

    def handle_shutdown(self):
        with self.lock:
            self.shutting_down = True
            for actor in self.actors.values():
                actor.intentional_exit = True
                self._kill_proc(actor)
            metas = list(self.objects.values())
            self.objects.clear()
            agents = [
                n.agent_addr for n in self.nodes.values() if n.agent_addr
            ]
        self._unlink_objects(metas, wait=True)
        for agent_addr in agents:
            try:
                rpc(agent_addr, ("stop", {}), timeout=5)
            except Exception:  # raydp-lint: disable=swallowed-exceptions (advisory stop; the agent's head-liveness watchdog exits it)
                pass  # the agent's own head-liveness watchdog will exit it
        return True

    def monitor_loop(self) -> None:
        last_zygote_check = 0.0
        last_self_ingest = 0.0
        while not self.shutting_down:
            time.sleep(0.05)
            with self.lock:
                for actor in list(self.actors.values()):
                    if actor.state == ActorState.DEAD:
                        continue
                    if actor.pending_respawn:
                        self._try_respawn(actor)
                        continue
                    if actor.proc is not None and actor.proc.poll() is not None:
                        self._on_actor_death(actor)
            # zygote liveness: spawns silently degrade to ~450ms cold starts
            # if the fork template dies — restart it (cheap pid probe, 2s
            # cadence; launch_worker's cold fallback covers the gap)
            now = time.monotonic()
            if now - last_self_ingest > 1.0:
                last_self_ingest = now
                # the head's ~1s telemetry tick: ship its OWN registry (the
                # authoritative per-tenant byte gauges live here) through
                # the direct-ingest hook so the TSDB behind the scrape
                # endpoint always carries fresh head-side series
                try:
                    from raydp_tpu.obs.tracing import flush_throttled

                    flush_throttled(1.0)
                except Exception:  # raydp-lint: disable=swallowed-exceptions (a telemetry tick must never take the monitor loop down)
                    pass
            if now - last_zygote_check > 2.0:
                last_zygote_check = now
                self._ensure_zygote()
                with self.lock:
                    self._gc_proxy_staging(now)
            # driver liveness: tear everything down if the driver is gone
            if self.driver_pid and not _pid_alive(self.driver_pid):
                self.handle_shutdown()
                os._exit(0)

    def _ensure_zygote(self) -> None:
        from raydp_tpu.cluster.common import start_zygote, zygote_alive

        if zygote_alive(self.session_dir):
            return
        try:
            start_zygote(self.session_dir)
        except Exception:
            # spawns keep falling back to cold subprocess starts (~450ms of
            # imports each) — log so slow restarts are attributable
            obs_log.warning("zygote restart failed", exc_info=True)

    def agent_watchdog_loop(self) -> None:
        """Agent liveness: agents watch the head, the head watches agents.
        An unreachable agent (crashed host) gets its node marked dead and
        its actors recycled — otherwise they'd stay ALIVE forever and
        callers would hang retrying a dead tcp:// address. Runs on its OWN
        thread with concurrent probes so blocking 3s pings of several dead
        hosts cannot stall local death detection or driver teardown."""
        agent_last_ok: Dict[str, float] = {}
        while not self.shutting_down:
            time.sleep(2.0)
            with self.lock:
                agent_nodes = [
                    (n.node_id, n.agent_addr)
                    for n in self.nodes.values()
                    if n.alive and n.agent_addr is not None
                ]
            if not agent_nodes:
                continue
            now = time.monotonic()
            results: Dict[str, bool] = {}

            def probe(node_id=None, agent_addr=None):
                try:
                    rpc(agent_addr, ("ping", {}), timeout=3)
                    results[node_id] = True
                except Exception:
                    results[node_id] = False

            threads = [
                threading.Thread(target=probe, kwargs={"node_id": nid, "agent_addr": addr})
                for nid, addr in agent_nodes
            ]
            for t in threads:
                t.start()
            for t in threads:
                # bounded join with slack over the probes' own 3s rpc
                # timeout: a probe stuck past its timeout (half-open TCP,
                # resolver hang) must not park this watchdog forever — the
                # lost-notify/unbounded-join class the raydp-tsan audit
                # covers. Stragglers report into `results` late; the
                # snapshot below keeps their mutation off this iteration
                # and the next sweep picks the node up again.
                t.join(timeout=10.0)
            for node_id, ok in dict(results).items():
                if ok:
                    agent_last_ok[node_id] = now
                    continue
                if now - agent_last_ok.get(node_id, now) > 15.0:
                    try:
                        self.handle_remove_node(node_id)
                    except ClusterError:  # raydp-lint: disable=swallowed-exceptions (node already removed by a concurrent path)
                        pass
                    agent_last_ok.pop(node_id, None)
                else:
                    agent_last_ok.setdefault(node_id, now)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _unknown_method_error(head: "Head", method: str) -> ClusterError:
    """A self-diagnosing unknown-op error: under version skew (old client /
    new head or vice versa) the raw ``unknown head method 'x'`` forced a
    source dive — naming the nearest ``handle_*`` candidates turns a renamed
    op into a one-glance fix. Counted so a fleet speaking a drifted protocol
    shows up in telemetry, not just in one caller's traceback."""
    obs_metrics.counter("head.unknown_method_calls").inc()
    ops = sorted(
        name[len("handle_"):]
        for name in dir(head)
        if name.startswith("handle_") and callable(getattr(head, name))
    )
    near = difflib.get_close_matches(method, ops, n=3, cutoff=0.5)
    hint = f" (nearest handlers: {', '.join(near)})" if near else ""
    return ClusterError(f"unknown head method {method!r}{hint}")


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        head: Head = self.server.head  # type: ignore[attr-defined]
        token = getattr(self.server, "token", None)
        if token is not None:  # TCP: authenticate before any unpickling
            from raydp_tpu.cluster.common import verify_token

            if not verify_token(self.request, token):
                return
        # serve frames until the peer hangs up: one-shot clients close after
        # the first reply (loop exits on EOF), pooled clients keep the
        # connection for their lifetime and skip per-call connect+accept
        while True:
            try:
                frame = recv_frame(self.request)
            except (EOFError, OSError):
                return
            frame, trace_ctx = unwrap_traced(frame)
            method, kwargs = frame
            try:
                fn = getattr(head, f"handle_{method}", None)
                if fn is None:
                    raise _unknown_method_error(head, method)
                if trace_ctx is not None and not method.startswith("obs_"):
                    # adopt the caller's trace: the head's handling of a
                    # traced control-plane call becomes a child span on the
                    # head's own track (obs ship/dump calls stay untraced —
                    # tracing the trace plane would feed back on itself)
                    with obs_use_context(trace_ctx), obs_span(
                        f"head.{method}"
                    ):
                        result = fn(**kwargs)
                else:
                    result = fn(**kwargs)
                reply = ("ok", result)
            except BaseException as exc:  # noqa: BLE001 - propagate to caller
                exc.__cause__ = None
                reply = ("err", exc)
            try:
                send_frame(self.request, reply)
            except OSError:
                return
            except Exception:
                # unpicklable reply: report it without severing the pooled
                # connection (the CALLER still needs a frame)
                try:
                    send_frame(
                        self.request,
                        ("err", ClusterError("head reply could not be serialized")),
                    )
                except OSError:
                    return


class _Server(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class _TcpServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


def _advertised_ip() -> str:
    """The IP other hosts can reach this head on (best effort; loopback when
    the host has no external route — single-machine sessions)."""
    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        probe.connect(("8.8.8.8", 80))
        ip = probe.getsockname()[0]
        probe.close()
        return ip
    except OSError:
        return "127.0.0.1"


def run_head(session_dir: str, driver_pid: int, default_resources: Dict[str, float]) -> None:
    from raydp_tpu.obs.tracing import set_local_ingest, set_process_role

    set_process_role("head")
    sanitize.snapshot_baseline()
    head = Head(session_dir, driver_pid, default_resources)
    # the head's own spans/metrics ingest directly — no RPC loopback
    set_local_ingest(head.handle_obs_ingest)
    server = _Server(head_sock_path(session_dir), _Handler)
    server.head = head  # type: ignore[attr-defined]
    # TCP beside the Unix socket: node agents (and their actors) on other
    # hosts address the head through this; the bound address is published in
    # the session dir for local discovery and passed by env to remote actors
    tcp_server = _TcpServer(("0.0.0.0", 0), _Handler)
    tcp_server.head = head  # type: ignore[attr-defined]
    from raydp_tpu.cluster.common import TOKEN_ENV, load_token

    token = load_token(session_dir)
    tcp_server.token = token  # type: ignore[attr-defined]
    # the head itself dials TCP peers (agents) and its env predates the
    # token file — adopt it so outgoing connects authenticate; worker spawns
    # inherit it too
    os.environ[TOKEN_ENV] = token.hex()
    # pre-warmed fork template: light-actor spawns become ~10ms forks instead
    # of ~450ms interpreter+pyarrow starts. cluster.init usually started one
    # EAGERLY before this head booted (its warm-up is the first session's
    # critical path) — a second one here would rebind the socket over it and
    # double the import work
    from raydp_tpu.cluster.common import start_zygote, zygote_alive

    try:
        if not zygote_alive(session_dir):
            start_zygote(session_dir)
    except Exception:
        obs_log.warning(
            "zygote start failed at head boot; spawns fall back to cold "
            "subprocess starts", exc_info=True,
        )
    head.tcp_addr = f"tcp://{_advertised_ip()}:{tcp_server.server_address[1]}"
    tcp_path = os.path.join(session_dir, HEAD_TCP_FILE)
    with open(tcp_path + ".tmp", "w") as f:
        f.write(head.tcp_addr)
    os.replace(tcp_path + ".tmp", tcp_path)
    threading.Thread(
        target=tcp_server.serve_forever, kwargs={"poll_interval": 0.2}, daemon=True
    ).start()
    monitor = threading.Thread(target=head.monitor_loop, name="monitor", daemon=True)
    monitor.start()
    threading.Thread(
        target=head.agent_watchdog_loop, name="agent-watchdog", daemon=True
    ).start()
    server.timeout = 0.2
    try:
        while not head.shutting_down:
            server.handle_request()
    finally:
        server.server_close()
        tcp_server.shutdown()
        tcp_server.server_close()
        head.close_scrape_server()
        try:
            sanitize.audit_leaks("head")
        except sanitize.LeakError:
            obs_log.error("head leaked resources at shutdown", exc_info=True)
