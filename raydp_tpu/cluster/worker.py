"""Actor process entry point.

Hosts exactly one user actor object, serving method calls over a Unix socket
with a bounded execution pool — the analog of the reference's Ray-actor-hosted
executors (RayDPExecutor.scala:194-253). ``max_concurrency`` mirrors the
reference's ``setMaxConcurrency(2)`` (RayExecutorUtils.java:65): an executor can
serve data-plane reads while its main loop is busy.

Deliberately light on imports so respawn after a crash is fast; user classes
pull in heavy deps (pyarrow, jax) themselves.
"""

from __future__ import annotations

import concurrent.futures
import os
import socket
import socketserver
import sys
import threading
import traceback

import cloudpickle

from raydp_tpu.cluster.common import (
    RawView,
    actor_sock_path,
    recv_frame,
    resolve_head_addr,
    rpc,
    send_frame,
    unwrap_traced,
)
from raydp_tpu.obs import log as obs_log
from raydp_tpu.obs import use_context as obs_use_context


class _WorkerContext:
    """Process-global context for code running inside this actor."""

    def __init__(self, session_dir: str, actor_id: str, incarnation: int):
        self.session_dir = session_dir
        self.actor_id = actor_id
        self.incarnation = incarnation
        self.node_ip = os.environ.get("RAYDP_TPU_NODE_IP", "127.0.0.1")
        self.node_id = os.environ.get("RAYDP_TPU_NODE_ID", "")


_context: _WorkerContext | None = None


def current_context() -> _WorkerContext | None:
    return _context


def exit_actor() -> None:
    """Intentional exit: the head will NOT restart this actor (parity:
    Ray.exitActor semantics relied on at reference ApplicationInfo.scala:119-124)."""
    ctx = _context
    if ctx is None:
        raise RuntimeError("exit_actor() called outside an actor process")
    try:
        rpc(
            resolve_head_addr(ctx.session_dir),
            ("mark_intentional_exit", {"actor_id": ctx.actor_id}),
            timeout=10,
        )
    finally:
        os._exit(0)


class _ActorServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class _ActorTcpServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


def _serve(
    instance,
    sock_path: str,
    max_concurrency: int,
    stop_event: threading.Event,
    bound: "list",
    bound_event: threading.Event,
    use_tcp: bool,
    node_ip: str,
):
    pool = concurrent.futures.ThreadPoolExecutor(max_workers=max(1, max_concurrency))

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            # frames loop until the peer hangs up: one-shot callers
            # (ActorFuture closes after its reply) exit on EOF; pooled
            # clients reuse the connection for sequential calls
            while True:
                try:
                    frame = recv_frame(self.request)
                except (EOFError, OSError):
                    return
                # traced frames wrap the call tuple in an ("__obs__", ctx, …)
                # envelope; the caller's (trace, span) context is adopted for
                # the method body so its spans link into the caller's trace
                frame, trace_ctx = unwrap_traced(frame)
                method, args, kwargs, no_reply = frame
                if method == "__ping__":  # raydp-lint: disable=rpc-closure (transport liveness probe: sent by operators/tools over a raw socket, never via ActorHandle — __getattr__ refuses dunder dispatch)
                    send_frame(self.request, ("ok", "pong"))
                    continue
                if method == "__shutdown__":  # raydp-lint: disable=rpc-closure (graceful-stop escape hatch, same raw-socket-only reachability as __ping__)
                    send_frame(self.request, ("ok", True))
                    stop_event.set()
                    return

                # bind the request into the closure: the frame loop rebinds
                # method/args/kwargs on the NEXT recv, and a pooled client's
                # no_reply call must not race its successor into running
                # with the successor's arguments
                def run(method=method, args=args, kwargs=kwargs, ctx=trace_ctx):
                    try:
                        fn = getattr(instance, method)
                        with obs_use_context(ctx):
                            return ("ok", fn(*args, **kwargs))
                    except BaseException as exc:  # noqa: BLE001
                        tb = traceback.format_exc()
                        try:
                            cloudpickle.dumps(exc)
                        except Exception:
                            exc = RuntimeError(f"{type(exc).__name__}: {exc}")
                        exc.remote_traceback = tb  # type: ignore[attr-defined]
                        return ("err", exc)

                future = pool.submit(run)
                if no_reply:
                    continue
                reply = future.result()
                if reply[0] == "ok" and isinstance(reply[1], RawView):
                    # streaming block reply: a ("raw", size) header frame,
                    # then the mmap'd bytes straight onto the socket — no
                    # pickle, no copy (store/block_service.py client side)
                    raw = reply[1]
                    try:
                        send_frame(self.request, ("raw", raw.size))
                        self.request.sendall(raw.view)
                    except OSError:
                        return
                    finally:
                        raw.close()
                    continue
                try:
                    send_frame(self.request, reply)
                except OSError:
                    return
                except Exception as exc:  # unpicklable result: report, don't sever
                    try:
                        send_frame(
                            self.request,
                            (
                                "err",
                                RuntimeError(
                                    f"result of {method}() could not be serialized: "
                                    f"{type(exc).__name__}: {exc}"
                                ),
                            ),
                        )
                    except OSError:
                        return

    if use_tcp:
        # agent-spawned actors must be reachable across hosts; peers
        # authenticate with the session token before any frame is parsed
        from raydp_tpu.cluster.common import session_token, verify_token

        token = session_token()

        class TcpHandler(Handler):
            def handle(self):
                if not verify_token(self.request, token):
                    return
                super().handle()

        server = _ActorTcpServer(("0.0.0.0", 0), TcpHandler)
        bound.append(f"tcp://{node_ip}:{server.server_address[1]}")
    else:
        server = _ActorServer(sock_path, Handler)
        bound.append(sock_path)
    bound_event.set()
    server.timeout = 0.2
    while not stop_event.is_set():
        server.handle_request()
    server.server_close()


def main() -> None:
    global _context
    session_dir, actor_id, incarnation_str = sys.argv[1], sys.argv[2], sys.argv[3]
    incarnation = int(incarnation_str)
    _context = _WorkerContext(session_dir, actor_id, incarnation)
    from raydp_tpu.obs.tracing import reinit_for_process

    # re-reads RAYDP_TPU_TRACE: a zygote-forked worker inherits the ZYGOTE's
    # tracing state, but this SESSION's env (riding the fork request) decides
    reinit_for_process(f"worker:{actor_id}")
    from raydp_tpu import sanitize

    # the zygote parent's lock-order history and resource floor are
    # meaningless in this fork; start both sanitizers clean
    sanitize.reset_lockdep()
    sanitize.snapshot_baseline()
    head = resolve_head_addr(session_dir)

    spec_path = os.path.join(session_dir, f"a-{actor_id}.spec")
    with open(spec_path, "rb") as f:
        spec = cloudpickle.load(f)

    try:
        cls = cloudpickle.loads(spec.cls_blob)
        args, kwargs = cloudpickle.loads(spec.args_blob)
        instance = cls(*args, **kwargs)
    except BaseException:  # noqa: BLE001 - report init failure then die
        rpc(
            head,
            (
                "actor_init_failed",
                {
                    "actor_id": actor_id,
                    "incarnation": incarnation,
                    "error": traceback.format_exc(),
                },
            ),
            timeout=10,
        )
        raise

    sock_path = actor_sock_path(session_dir, actor_id, incarnation)
    try:
        os.unlink(sock_path)
    except OSError:  # raydp-lint: disable=swallowed-exceptions (stale socket path may not exist)
        pass
    stop_event = threading.Event()
    bound: list = []
    bound_event = threading.Event()
    use_tcp = os.environ.get("RAYDP_TPU_TCP") == "1"
    server_thread = threading.Thread(
        target=_serve,
        args=(
            instance, sock_path, spec.max_concurrency, stop_event,
            bound, bound_event, use_tcp, _context.node_ip,
        ),
        daemon=True,
    )
    server_thread.start()
    if not bound_event.wait(timeout=10):
        raise RuntimeError("actor server failed to bind")
    rpc(
        head,
        (
            "actor_ready",
            {"actor_id": actor_id, "incarnation": incarnation, "sock_path": bound[0]},
        ),
        timeout=30,
    )
    stop_event.wait()
    if hasattr(instance, "on_shutdown"):
        try:
            instance.on_shutdown()
        except Exception:
            obs_log.exception(
                "on_shutdown hook failed", actor_id=actor_id,
                incarnation=incarnation,
            )
    from raydp_tpu.obs import flush as obs_flush

    # graceful teardown audits this worker's inventory back to its baseline
    # (SIGKILLed actors never reach here — their segments are reclaimed by
    # owner-death GC, and the head/agent side unlinks them); the gauges ride
    # the final flush below into cluster.dump_metrics()
    try:
        sanitize.audit_leaks(f"worker:{actor_id}")
    except sanitize.LeakError:
        obs_log.error("worker leaked resources at graceful exit",
                      actor_id=actor_id, exc_info=True)
    obs_flush()  # graceful exits ship their remaining spans/metrics


if __name__ == "__main__":
    # run via the canonical module object so user code reaching
    # raydp_tpu.cluster.worker sees the same process-global _context
    from raydp_tpu.cluster import worker as _canonical

    _canonical.main()
