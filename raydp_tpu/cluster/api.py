"""Client API for the cluster runtime: init/shutdown, actor spawn/call,
placement groups, resource queries.

This is the user-facing surface that replaces Ray core for this framework
(reference substrate, SURVEY.md L1). Handles are plain picklable records, so
they pass freely between actors — exactly how the reference passes executor
actor handles around (ObjectStoreWriter.scala:232-256).
"""

from __future__ import annotations

import atexit
import os
import select
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence

import cloudpickle

from raydp_tpu.cluster.common import (
    DRIVER_OWNER,
    HEAD_ADDR_ENV,
    HEAD_TCP_FILE,
    SESSION_ENV,
    ActorDiedError,
    ActorRecord,
    ActorSpec,
    ActorState,
    ClusterError,
    actor_sock_path,
    connect,
    head_sock_path,
    recv_frame,
    resolve_head_addr,
    rpc,
    rpc_pooled,
    send_frame,
    wait_for_path,
)

from raydp_tpu import sanitize as _sanitize

_lock = _sanitize.named_lock("cluster.api", threading.RLock())
_shutting_down = False  # teardown claimed; guarded-by: _lock
_session_dir: Optional[str] = None
_head_proc: Optional[subprocess.Popen] = None
_is_client = False  # attached to someone else's cluster: detach, never tear down
_is_tcp_client = False  # attached over tcp://: cannot host object-store blocks
_client_env_keys: List[str] = []  # env vars connect_cluster set (cleared on detach)
_client_local_dir: Optional[str] = None  # tcp client's scratch dir (removed on detach)


def is_tcp_client() -> bool:
    return _is_tcp_client


def is_initialized() -> bool:
    return _session_dir is not None


def _join_from_env() -> Optional[str]:
    """Adopt the session an enclosing actor was spawned into, if any."""
    global _session_dir
    with _lock:
        if _session_dir is None:
            env_session = os.environ.get(SESSION_ENV)
            if env_session:
                _session_dir = env_session
        return _session_dir


def session_dir() -> str:
    if _session_dir is None and _join_from_env() is None:
        raise ClusterError("cluster runtime not initialized; call cluster.init()")
    return _session_dir


# head methods that must NOT ride the pooled transport: rpc_pooled retries
# once on a reset connection, and a retry after the head already processed
# the frame would double-execute these (a second create_actor spawns and
# orphans a second OS process; a second add_node registers a ghost node; a
# re-sent obs_ingest would duplicate every span of the flush in the trace)
_NON_IDEMPOTENT_HEAD_METHODS = frozenset(
    {"create_actor", "create_placement_group", "add_node",
     "object_put_proxy_commit", "obs_ingest"}
)


def head_rpc(method: str, timeout: float = 60.0, **kwargs) -> Any:
    # pooled: the object/actor metadata plane is called on every block
    # write/read, and a fresh connect + accept-thread per call costs ~ms —
    # safe because the pool's one reconnect-retry only re-sends requests
    # whose re-execution is harmless (the rest go one-shot)
    addr = resolve_head_addr(session_dir())
    if method in _NON_IDEMPOTENT_HEAD_METHODS:
        return rpc(addr, (method, kwargs), timeout=timeout)
    return rpc_pooled(addr, (method, kwargs), timeout=timeout)


def init(
    num_cpus: Optional[float] = None,
    memory: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    session_root: Optional[str] = None,
) -> str:
    """Start (or join) a session. Inside an actor process this attaches to the
    existing session from the environment — mirroring how Ray workers join the
    cluster they were spawned into."""
    global _session_dir, _head_proc
    with _lock:
        if _session_dir is not None or _join_from_env() is not None:
            return _session_dir
        root = session_root or os.path.join(tempfile.gettempdir(), "raydp_tpu")
        os.makedirs(root, exist_ok=True)
        _session_dir = tempfile.mkdtemp(prefix="session-", dir=root)
        default_resources = dict(resources or {})
        default_resources["CPU"] = float(num_cpus if num_cpus is not None else (os.cpu_count() or 1))
        default_resources["memory"] = float(memory if memory is not None else (4 << 30))
        boot = os.path.join(_session_dir, "head_boot.pkl")
        with open(boot, "wb") as f:
            cloudpickle.dump((os.getpid(), default_resources), f)
        head_env = dict(os.environ)
        # the head (and the actors it spawns) must be able to import raydp_tpu
        # and user modules no matter where the driver was launched from
        head_env["PYTHONPATH"] = os.pathsep.join(p for p in sys.path if p)
        # start the zygote NOW, before the head boots: its import warm-up
        # (~0.45s) is the critical path of the first session's actor spawns,
        # and the head's own _ensure_zygote is idempotent per marker. The
        # zygote's parent-death watch follows this driver — acceptable: the
        # head tears the cluster down when the driver dies anyway, and its
        # monitor restarts a missing zygote.
        try:
            from raydp_tpu.cluster.common import start_zygote

            start_zygote(_session_dir, env=head_env)
        except Exception:  # raydp-lint: disable=swallowed-exceptions (eager warm-up only; the head starts one at boot)
            pass  # the head will start one at boot
        # warm boot: fork the head from the pre-warmed zygote when a READY
        # template exists (second-and-later sessions on a machine — the
        # global template survives across clusters): head boot becomes a
        # ~10ms fork with imports inherited copy-on-write, the dominant
        # term of sub-100ms warm cluster_boot_s. Cold machines fall through
        # to the subprocess start immediately (no warm-up wait).
        _head_proc = None
        try:
            from raydp_tpu.cluster.common import zygote_fork_main

            _head_proc = zygote_fork_main(
                _session_dir,
                "raydp_tpu.cluster.head_main",
                [_session_dir],
                head_env,
                os.path.join(_session_dir, "head"),
            )
        except Exception:  # raydp-lint: disable=swallowed-exceptions (warm boot is opportunistic; the cold start below always works)
            _head_proc = None
        if _head_proc is None:
            # -S: skip site/sitecustomize (this image's sitecustomize
            # imports jax + the TPU plugin — ~2.6s the head never needs);
            # imports resolve via the PYTHONPATH above
            _head_proc = subprocess.Popen(
                [sys.executable, "-S", "-m", "raydp_tpu.cluster.head_main", _session_dir],
                start_new_session=True,
                env=head_env,
            )
        wait_for_path(head_sock_path(_session_dir), 30, "head socket")
        # adopt the cluster token into the environment so this process (and
        # every subprocess it starts — agents, SPMD launchers) can
        # authenticate over the TCP transport
        from raydp_tpu.cluster.common import TOKEN_ENV, load_token

        os.environ[TOKEN_ENV] = load_token(_session_dir).hex()
        atexit.register(shutdown)
        _sanitize.snapshot_baseline()  # leak audit floor for THIS session
        return _session_dir


def connect_cluster(address: str, token: Optional[str] = None) -> str:
    """Attach this process as a DRIVER to an already-running cluster — the
    analog of the reference's ``ray://host:port`` client mode (its test
    matrix runs everything twice, in-process and via the client;
    reference conftest.py:45-52).

    ``address`` is either the cluster's session dir (same host: adopts the
    Unix socket and token file) or the head's ``tcp://host:port`` (any
    machine that can reach it; requires the cluster ``token`` hex string —
    obtain both from the owning driver via ``head_tcp_addr()`` and
    ``cluster_token()``). A TCP client gets its own shm namespace so object
    reads always take the network pull path. Clients never tear the cluster
    down: ``shutdown()`` just detaches."""
    global _session_dir, _is_client, _is_tcp_client
    from raydp_tpu.cluster.common import SHM_NS_ENV, TOKEN_ENV, load_token

    with _lock:
        if _session_dir is not None or _join_from_env() is not None:
            raise ClusterError("cluster runtime already initialized in this process")
        set_env: Dict[str, str] = {}
        if address.startswith("tcp://"):
            if token is None:
                raise ClusterError(
                    "tcp:// attach requires the cluster token "
                    "(cluster_token() on the owning driver)"
                )
            root = os.path.join(tempfile.gettempdir(), "raydp_tpu")
            os.makedirs(root, exist_ok=True)
            local_dir = tempfile.mkdtemp(prefix="client-", dir=root)
            # record the head address in the client dir too: handles pickled
            # by this client embed this dir, and a process resolving them
            # without our env finds the tcp address here (resolve_head_addr)
            from raydp_tpu.cluster.common import HEAD_TCP_FILE

            with open(os.path.join(local_dir, HEAD_TCP_FILE), "w") as f:
                f.write(address)
            set_env[HEAD_ADDR_ENV] = address
            set_env[TOKEN_ENV] = token
            if SHM_NS_ENV not in os.environ:
                # never map foreign shm directly: this process may be on
                # another machine — all reads go through block servers
                set_env[SHM_NS_ENV] = f"client-{uuid.uuid4().hex[:6]}"
        else:
            if not os.path.exists(head_sock_path(address)):
                raise ClusterError(f"no running cluster at {address!r}")
            local_dir = address
            set_env[TOKEN_ENV] = load_token(address).hex()
        os.environ.update(set_env)
        _session_dir = local_dir
        try:
            # raydp-lint: disable=blocking-under-lock (attach validation must
            # be atomic with the attach state it validates: a concurrent
            # init() observing a half-attached session would race the
            # rollback below. The ping is a leaf RPC — its path takes no
            # other lock, so no inversion is possible — and bounded at 10s.)
            head_rpc("ping", timeout=10)  # validate before committing
        except BaseException:
            # roll back: a typo'd address must not poison the process
            _session_dir = None
            for key in set_env:
                os.environ.pop(key, None)
            if address.startswith("tcp://"):
                import shutil

                shutil.rmtree(local_dir, ignore_errors=True)
            raise
        global _client_local_dir
        _client_local_dir = local_dir if address.startswith("tcp://") else None
        _is_client = True
        _is_tcp_client = address.startswith("tcp://")
        _client_env_keys.extend(set_env)
        _sanitize.snapshot_baseline()  # leak audit floor for THIS attach
        return _session_dir


def cluster_token() -> str:
    """This cluster's auth token (hex) — hand it to tcp:// clients."""
    from raydp_tpu.cluster.common import load_token

    return load_token(session_dir()).hex()


def shutdown() -> None:
    global _session_dir, _head_proc, _is_client
    with _lock:
        if _session_dir is None:
            return
        if _is_client:  # clients detach; the cluster belongs to its driver
            global _is_tcp_client, _client_local_dir
            _session_dir = None
            _is_client = False
            _is_tcp_client = False
            for key in _client_env_keys:
                # a later init() in this process must not route to the old
                # cluster through a stale HEAD_ADDR/TOKEN
                os.environ.pop(key, None)
            _client_env_keys.clear()
            if _client_local_dir is not None:
                import shutil

                shutil.rmtree(_client_local_dir, ignore_errors=True)
                _client_local_dir = None
            return
        if os.environ.get(SESSION_ENV):  # actors never tear the session down
            _session_dir = None
            return
        # claim teardown under the lock; RUN it off the lock. The shutdown
        # RPC and process waits block for up to tens of seconds, and holding
        # the api lock through them froze every other thread touching the
        # cluster API — the exact hold-lock-while-blocking shape the
        # blocking-under-lock rule exists for. A concurrent caller returns
        # immediately (_shutting_down claimed) instead of queueing behind
        # the whole teardown; state is cleared only AFTER the teardown
        # completes, so an interrupt (Ctrl-C in a process wait) leaves the
        # session claimable again and the atexit retry can still reap the
        # head/agent processes instead of orphaning them.
        global _shutting_down
        if _shutting_down:
            return  # teardown already in flight on another thread
        _shutting_down = True
        try:
            head_addr = resolve_head_addr(_session_dir)
        except Exception:  # raydp-lint: disable=swallowed-exceptions (session dir already gone: nothing to signal)
            head_addr = None
        head_proc = _head_proc
        agent_procs = list(_agent_procs)
    done = False
    try:
        if head_addr is not None:
            try:
                rpc(head_addr, ("shutdown", {}), timeout=10)
            except Exception:  # raydp-lint: disable=swallowed-exceptions (head may already be gone at shutdown)
                pass
        if head_proc is not None:
            try:
                head_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                head_proc.kill()
        for proc in agent_procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        done = True
    finally:
        with _lock:
            _shutting_down = False
            if done:
                _head_proc = None
                _agent_procs.clear()
                _session_dir = None
    from raydp_tpu.cluster.common import close_pooled_connections

    close_pooled_connections()
    close_actor_connections()  # doorbell sockets join the fd audit too
    try:
        from raydp_tpu.store.block_service import close_service_pool

        close_service_pool()  # pooled block-fetch sockets too
    except Exception:  # raydp-lint: disable=swallowed-exceptions (store layer may not be loaded)
        pass
    _sanitize.audit_leaks("cluster.shutdown")


# ---------- actors ----------

# ---------------------------------------------------------------------------
# doorbell: persistent per-(thread, actor-socket) dispatch connections
#
# Actor method calls used to open a fresh socket per call (ActorFuture closed
# it after the reply) — a connect + accept-thread round per dispatch, ~ms on
# the interactive-query hot path. The doorbell keeps the socket: a completed
# future returns its connection to the calling thread's pool, and the next
# dispatch to that actor reuses it (one outstanding request per pooled
# connection; concurrent sends to one actor from one thread fall back to
# fresh sockets). SAME-HOST (Unix sockets) ONLY: a stale UDS failing at SEND
# was never delivered (peer-closed stream sockets fail the first write), so
# retrying on a fresh socket is safe — the same contract rpc_pooled has; on
# TCP a send into a dead peer succeeds until the RST arrives, so tcp://
# actors keep per-call sockets. Toggles: RAYDP_TPU_NO_DOORBELL=1 (process)
# or the ``cluster.doorbell`` session conf via set_doorbell(). Shutdown
# closes the calling thread's doorbell sockets so the leak sanitizer's fd
# audit stays clean.
# ---------------------------------------------------------------------------

_doorbell_tls = threading.local()
_DOORBELL_MAX = 16  # dead sessions' executor sockets must not pile up
_doorbell_on = True  # process-wide toggle; bool writes are atomic


def _doorbell_enabled() -> bool:
    return _doorbell_on and os.environ.get("RAYDP_TPU_NO_DOORBELL") != "1"


def set_doorbell(enabled: bool) -> None:
    """Process-wide toggle (the ``cluster.doorbell`` session conf): off =
    one fresh socket per actor call, the pre-doorbell behavior."""
    global _doorbell_on
    _doorbell_on = bool(enabled)


def _doorbell_take(sock_path: str):
    conns = getattr(_doorbell_tls, "conns", None)
    if conns is None:
        return None
    return conns.pop(sock_path, None)


def _doorbell_release(sock_path: str, sock) -> None:
    if not _doorbell_enabled():
        try:
            sock.close()
        except OSError:  # raydp-lint: disable=swallowed-exceptions (closing a possibly-dead doorbell socket)
            pass
        return
    conns = getattr(_doorbell_tls, "conns", None)
    if conns is None:
        conns = _doorbell_tls.conns = {}
    old = conns.pop(sock_path, None)
    if old is not None:
        try:
            old.close()
        except OSError:  # raydp-lint: disable=swallowed-exceptions (closing a displaced doorbell socket)
            pass
    while len(conns) >= _DOORBELL_MAX:
        # evict the OLDEST entry (insertion order): dead sessions' sockets
        # age out while the hot actors' connections stay pooled
        oldest = next(iter(conns))
        victim = conns.pop(oldest)
        try:
            victim.close()
        except OSError:  # raydp-lint: disable=swallowed-exceptions (closing an evicted doorbell socket)
            pass
    conns[sock_path] = sock


def close_actor_connections() -> None:
    """Close THIS thread's doorbell sockets (shutdown hygiene, mirroring
    ``common.close_pooled_connections`` for the head pool: the fd audit in
    the leak sanitizer counts lingering sockets against the baseline)."""
    conns = getattr(_doorbell_tls, "conns", None)
    if not conns:
        return
    for sock in list(conns.values()):
        try:
            sock.close()
        except OSError:  # raydp-lint: disable=swallowed-exceptions (closing a possibly-dead doorbell socket)
            pass
    conns.clear()


class RemoteMethod:
    def __init__(self, handle: "ActorHandle", method: str, no_reply: bool = False,
                 timeout: Optional[float] = None, retries: int = 0):
        self._handle = handle
        self._method = method
        self._no_reply = no_reply
        self._timeout = timeout
        self._retries = retries

    def options(self, no_reply: bool = False, timeout: Optional[float] = None,
                retries: int = 0) -> "RemoteMethod":
        return RemoteMethod(self._handle, self._method, no_reply, timeout, retries)

    def remote(self, *args, **kwargs) -> "ActorFuture":
        return self._handle._call(
            self._method, args, kwargs,
            no_reply=self._no_reply, timeout=self._timeout, retries=self._retries,
        )

    def __call__(self, *args, **kwargs):
        """Synchronous sugar: handle.method(args) == handle.method.remote(...).result()."""
        return self.remote(*args, **kwargs).result()


class ActorFuture:
    def __init__(self, sock, timeout: Optional[float], pool_key: Optional[str] = None):
        self._sock = sock
        self._timeout = timeout
        self._pool_key = pool_key  # doorbell: return the conn on completion
        self._done = False
        self._value: Any = None
        self._error: Optional[BaseException] = None

    def result(self, timeout: Optional[float] = None):
        if not self._done:
            wait = timeout if timeout is not None else self._timeout
            if wait is not None:
                # probe without consuming, so a timeout leaves the future usable
                readable, _, _ = select.select([self._sock], [], [], wait)
                if not readable:
                    raise TimeoutError(f"no reply within {wait}s")
            self._sock.settimeout(
                300.0 if self._timeout is None else self._timeout
            )
            try:
                status, value = recv_frame(self._sock)
            except BaseException:
                self._sock.close()
                self._done = True
                raise
            # reply fully consumed: the connection is stream-clean — return
            # it to the doorbell pool so the next dispatch to this actor
            # skips connect/accept/handshake entirely
            if self._pool_key is not None:
                _doorbell_release(self._pool_key, self._sock)
            else:
                self._sock.close()
            self._done = True
            if status == "ok":
                self._value = value
            else:
                self._error = value
        if self._error is not None:
            raise self._error
        return self._value


class _ConnectFailed(OSError):
    """Connection to the actor socket could not be established; the request was
    never delivered, so retrying cannot double-execute a method."""


class _CompletedFuture:
    def __init__(self, value=None):
        self._value = value

    def result(self, timeout=None):
        return self._value


def get(futures, timeout: Optional[float] = None):
    """ray.get-style convenience over one future or a list of futures."""
    if isinstance(futures, (list, tuple)):
        return type(futures)(f.result(timeout) for f in futures)
    return futures.result(timeout)


class ActorHandle:
    """Picklable reference to a named, restartable actor."""

    def __init__(self, session_dir: str, actor_id: str, name: Optional[str] = None):
        self._session_dir = session_dir
        self._actor_id = actor_id
        self._name = name
        self._cached_sock: Optional[str] = None

    @property
    def actor_id(self) -> str:
        return self._actor_id

    @property
    def name(self) -> Optional[str]:
        return self._name

    def __reduce__(self):
        return (ActorHandle, (self._session_dir, self._actor_id, self._name))

    def __getattr__(self, item: str) -> RemoteMethod:
        if item.startswith("_"):
            raise AttributeError(item)
        return RemoteMethod(self, item)

    def _record(self) -> Optional[ActorRecord]:
        return rpc_pooled(
            resolve_head_addr(self._session_dir),
            ("get_actor", {"actor_id": self._actor_id}),
            timeout=30,
        )

    def state(self) -> ActorState:
        record = self._record()
        if record is None:
            raise ClusterError(f"actor {self._actor_id} unknown")
        return record.state

    def wait_ready(self, timeout: float = 120.0) -> "ActorHandle":
        deadline = time.monotonic() + timeout
        use_blocking_wait = True
        while True:
            record = None
            if use_blocking_wait:
                # event-driven: the head parks this call on a condition and
                # replies the moment the actor turns ALIVE/DEAD — no 50ms
                # poll overshoot on the startup critical path
                chunk = min(max(deadline - time.monotonic(), 0.0), 30.0)
                try:
                    record = rpc(
                        resolve_head_addr(self._session_dir),
                        (
                            "wait_actor_ready",
                            {"actor_id": self._actor_id, "timeout": chunk},
                        ),
                        timeout=chunk + 10.0,
                    )
                except ClusterError:
                    use_blocking_wait = False  # older head: fall back to polling
            if not use_blocking_wait:
                record = self._record()
            if record is not None:
                if record.state == ActorState.ALIVE:
                    return self
                if record.state == ActorState.DEAD:
                    raise ActorDiedError(
                        f"actor {self._name or self._actor_id} died during start: {record.error}"
                    )
            if time.monotonic() > deadline:
                raise ClusterError(f"timed out waiting for actor {self._name or self._actor_id}")
            if not use_blocking_wait:
                time.sleep(0.05)

    def _try_send(self, sock_path: str, method: str, args, kwargs, no_reply: bool,
                  timeout: Optional[float]):
        """Connect-phase failures raise _ConnectFailed (request was never
        delivered, always safe to retry); send-phase failures propagate raw
        (the actor may have partially received the request). Dispatches ride
        a pooled doorbell connection when one is free: a stale doorbell that
        fails at SEND was never delivered (peer-closed stream sockets fail
        the first write), so it silently falls through to a fresh connect."""
        from raydp_tpu.cluster.common import traced_request
        from raydp_tpu.obs import metrics as _metrics

        # the caller's trace context rides the frame so executor-side
        # spans (task read/compute/emit) link under the driver's stage
        frame = traced_request((method, args, kwargs, no_reply))
        # off-host actors speak the TCP actor protocol — same doorbell pool,
        # one extra precaution. The stale-at-SEND-was-never-delivered retry
        # premise holds for UDS unconditionally (a peer-closed stream fails
        # the first write) but NOT for TCP, where a send into a dead peer
        # succeeds until the RST arrives — so pooled tcp:// connections are
        # liveness-probed before reuse: the actor never sends unsolicited
        # bytes, hence a READABLE pooled socket can only be EOF/RST and is
        # dropped. Past the probe, a TCP send-phase failure means the RST
        # already arrived (never delivered — safe fresh-connect fallthrough)
        # and a send that lands on a just-died peer surfaces at recv, the
        # exact failure shape a per-call socket has always had.
        is_tcp = sock_path.startswith("tcp://")
        _metrics.counter(
            "rpc.doorbell_tcp" if is_tcp else "rpc.doorbell_uds"
        ).inc()
        use_doorbell = _doorbell_enabled()
        pooled = _doorbell_take(sock_path) if use_doorbell else None
        if pooled is not None and is_tcp:
            try:
                readable, _, _ = select.select([pooled], [], [], 0)
            except (OSError, ValueError):
                readable = [pooled]
            if readable:
                _metrics.counter("rpc.doorbell_tcp_evicted").inc()
                try:
                    pooled.close()
                except OSError:  # raydp-lint: disable=swallowed-exceptions (already dead)
                    pass
                pooled = None
        if pooled is not None:
            try:
                pooled.settimeout(300.0 if timeout is None else timeout)
                send_frame(pooled, frame)
            except OSError:
                try:
                    pooled.close()
                except OSError:  # raydp-lint: disable=swallowed-exceptions (closing the stale doorbell before the fresh connect)
                    pass
            else:
                if no_reply:
                    _doorbell_release(sock_path, pooled)
                    return _CompletedFuture()
                return ActorFuture(pooled, timeout, pool_key=sock_path)
        try:
            sock = connect(
                sock_path, timeout=300.0 if timeout is None else timeout
            )
        except OSError as exc:
            raise _ConnectFailed(str(exc)) from exc
        try:
            send_frame(sock, frame)
        except BaseException:
            sock.close()
            raise
        if no_reply:
            if use_doorbell:
                _doorbell_release(sock_path, sock)
            else:
                sock.close()
            return _CompletedFuture()
        return ActorFuture(
            sock, timeout, pool_key=sock_path if use_doorbell else None
        )

    def _call(self, method: str, args, kwargs, no_reply: bool, timeout: Optional[float],
              retries: int) -> ActorFuture:
        if self._cached_sock is not None:
            try:
                return self._try_send(self._cached_sock, method, args, kwargs, no_reply, timeout)
            except _ConnectFailed:
                self._cached_sock = None  # actor moved/restarted; fall through to head lookup
        sends_failed = 0
        # an explicit timeout=0 must mean "no budget", not the 300s default
        deadline = time.monotonic() + (300.0 if timeout is None else timeout)
        while True:
            record = self._record()
            if record is None:
                raise ClusterError(f"actor {self._actor_id} unknown")
            if record.state == ActorState.DEAD:
                raise ActorDiedError(
                    f"actor {self._name or self._actor_id} is dead: {record.error or 'exited'}"
                )
            if record.state == ActorState.ALIVE and record.sock_path:
                try:
                    future = self._try_send(
                        record.sock_path, method, args, kwargs, no_reply, timeout
                    )
                    self._cached_sock = record.sock_path
                    return future
                except _ConnectFailed:  # raydp-lint: disable=swallowed-exceptions (never delivered; retried until the deadline)
                    pass  # never delivered: retry freely until the deadline
                except OSError:
                    sends_failed += 1
                    if sends_failed > retries:
                        raise
            if time.monotonic() > deadline:
                raise ClusterError(
                    f"timed out calling {method} on {self._name or self._actor_id} "
                    f"(state={record.state})"
                )
            time.sleep(0.05)  # PENDING / RESTARTING: wait for the respawn

    def kill(self, no_restart: bool = True) -> None:
        rpc(
            resolve_head_addr(self._session_dir),
            ("kill_actor", {"actor_id": self._actor_id, "no_restart": no_restart}),
            timeout=30,
        )


def spawn(
    cls,
    *args,
    name: Optional[str] = None,
    resources: Optional[Dict[str, float]] = None,
    num_cpus: float = 0.0,
    memory: float = 0.0,
    max_restarts: int = 0,
    max_concurrency: int = 1,
    placement_group: Optional[str] = None,
    bundle_index: int = -1,
    env: Optional[Dict[str, str]] = None,
    block: bool = True,
    light: bool = False,
    **kwargs,
) -> ActorHandle:
    """Create an actor process running ``cls(*args, **kwargs)``.

    ``light=True`` starts the process with ``python -S`` — no
    site/sitecustomize, which skips environments' expensive startup hooks
    (this image preimports jax + the TPU plugin there, ~2.6s/process).
    The framework's own ETL/storage actors opt in; the PUBLIC default stays
    False because a light actor that later imports jax will silently miss
    any PJRT plugin a sitecustomize would have registered."""
    res = dict(resources or {})
    if num_cpus:
        res["CPU"] = float(num_cpus)
    if memory:
        res["memory"] = float(memory)
    env = dict(env or {})
    # actors must be able to import the modules that defined cls and its args
    env.setdefault("PYTHONPATH", os.pathsep.join(p for p in sys.path if p))
    spec = ActorSpec(
        actor_id=f"actor-{uuid.uuid4().hex[:12]}",
        name=name,
        cls_blob=cloudpickle.dumps(cls),
        args_blob=cloudpickle.dumps((args, kwargs)),
        resources=res,
        max_restarts=max_restarts,
        max_concurrency=max_concurrency,
        placement_group=placement_group,
        bundle_index=bundle_index,
        env=env,
        light=light,
    )
    head_rpc("create_actor", spec=spec)
    handle = ActorHandle(session_dir(), spec.actor_id, name)
    if block:
        handle.wait_ready()
    return handle


def get_actor(name: str) -> ActorHandle:
    record = head_rpc("get_actor", name=name)
    if record is None:
        raise ClusterError(f"no actor named {name!r}")
    return ActorHandle(session_dir(), record.actor_id, name)


def list_actors() -> List[ActorRecord]:
    return head_rpc("list_actors")


def kill_all_matching(prefix: str) -> None:
    for record in list_actors():
        if record.name and record.name.startswith(prefix):
            ActorHandle(session_dir(), record.actor_id, record.name).kill()


# ---------- placement groups ----------


class PlacementGroup:
    def __init__(self, pg_id: str):
        self.id = pg_id

    def __reduce__(self):
        return (PlacementGroup, (self.id,))


def create_placement_group(
    bundles: Sequence[Dict[str, float]], strategy: str = "PACK"
) -> PlacementGroup:
    pg_id = head_rpc("create_placement_group", bundles=list(bundles), strategy=strategy)
    return PlacementGroup(pg_id)


def remove_placement_group(pg: PlacementGroup) -> None:
    head_rpc("remove_placement_group", pg_id=pg.id)


def placement_group_table() -> Dict[str, Any]:
    return head_rpc("placement_group_table")


# ---------- nodes / resources ----------


def head_tcp_addr(timeout: float = 30.0) -> str:
    """The head's TCP address (published in the session dir at startup) —
    what node agents on other hosts connect to."""
    path = os.path.join(session_dir(), HEAD_TCP_FILE)
    wait_for_path(path, timeout, "head TCP address")
    with open(path) as f:
        return f.read().strip()


def start_node_agent(
    resources: Dict[str, float],
    node_ip: Optional[str] = None,
    shm_ns: Optional[str] = None,
    head_addr: Optional[str] = None,
    timeout: float = 60.0,
    host: Optional[str] = None,
) -> Dict[str, str]:
    """Launch a node agent as a detached process and wait for it to register.

    On a real deployment each host runs
    ``python -m raydp_tpu.cluster.agent <head_tcp> <ip> <ns> <dir> <json>``;
    this helper starts one on the local machine — with its own shm NAMESPACE,
    so it behaves exactly like a separate host: none of its blocks can be
    mapped by other nodes, every cross-node read goes over TCP. ``host``
    names the simulated host on the cluster's host axis
    (``RAYDP_TPU_HOST_ID`` in the agent's env, inherited by its actors);
    it defaults to the namespace, which already has host granularity.

    Returns ``{"node_id", "addr", "dir"}``.
    """
    import json

    from raydp_tpu.cluster.common import HOST_ID_ENV

    head = head_addr or head_tcp_addr()
    ns = shm_ns or f"n{uuid.uuid4().hex[:6]}"
    ip = node_ip or "127.0.0.1"
    local_dir = tempfile.mkdtemp(prefix=f"agent-{ns}-", dir=session_dir())
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(p for p in sys.path if p)
    if host is not None:
        env[HOST_ID_ENV] = host
    else:
        # the agent must not inherit THIS process's host identity: its
        # namespace is its (simulated) host
        env.pop(HOST_ID_ENV, None)
    proc = subprocess.Popen(
        [
            sys.executable, "-S", "-m", "raydp_tpu.cluster.agent",
            head, ip, ns, local_dir, json.dumps(resources),
        ],
        start_new_session=True,
        env=env,
    )
    _agent_procs.append(proc)
    ready = os.path.join(local_dir, "agent_ready.json")
    try:
        wait_for_path(ready, timeout, "node agent registration")
    except ClusterError:
        # a half-started agent must not register later as a ghost node
        proc.kill()
        raise
    with open(ready) as f:
        info = json.load(f)
    info["dir"] = local_dir
    info["pid"] = proc.pid
    return info


# agent processes this driver started (reaped at shutdown so exited agents
# don't linger as zombies)
_agent_procs: List[subprocess.Popen] = []


def add_node(resources: Dict[str, float], node_ip: Optional[str] = None) -> str:
    return head_rpc("add_node", resources=resources, node_ip=node_ip)


def remove_node(node_id: str) -> None:
    head_rpc("remove_node", node_id=node_id)


def nodes() -> List[Any]:
    return head_rpc("nodes")


def total_resources() -> Dict[str, Dict[str, float]]:
    return head_rpc("total_resources")


def available_resources() -> Dict[str, Dict[str, float]]:
    return head_rpc("available_resources")


# ---------- observability ----------


def dump_metrics() -> Dict[str, dict]:
    """Cluster-wide metrics: ``{"<role>:<pid>": {metric: snapshot}}`` for
    every process that has flushed telemetry to the head, merged with this
    process's live registry. Works (locally) without a running cluster."""
    from raydp_tpu.obs.export import dump_metrics as _dump

    return _dump()


def export_trace(path: str) -> str:
    """Write the cluster's collected trace as Perfetto-loadable JSON (see
    ``raydp_tpu.obs.export_trace``)."""
    from raydp_tpu.obs.export import export_trace as _export

    return _export(path)


def query_metrics(
    name: str,
    window_s: float = 60.0,
    labels: Optional[Dict[str, str]] = None,
    aggregate: bool = False,
) -> Any:
    """Windowed time-series read from the head's ring TSDB — the in-process
    flavor of a Prometheus scrape (docs/observability.md "Time series").
    Returns matching series (``[{name, labels, type, points, last,
    delta?}]``) or, with ``aggregate=True``, one windowed aggregate
    (``{series, delta, last, max}``). Flushes this process first so its own
    registry is part of the answer; degrades to the process-local mirror
    when no cluster is running."""
    from raydp_tpu.obs import timeseries as _ts
    from raydp_tpu.obs.tracing import flush

    flush()  # best-effort: puts this process's snapshot on the head
    try:
        if is_initialized() or os.environ.get(SESSION_ENV):
            return head_rpc(
                "obs_query_series", name=name, window_s=window_s,
                labels=labels, aggregate=aggregate, timeout=30.0,
            )
    except Exception:  # raydp-lint: disable=swallowed-exceptions (no cluster (or dead head): the local mirror below still answers)
        pass
    if aggregate:
        return _ts.local_store.windowed(name, window_s, labels)
    return _ts.local_store.query(name, window_s, labels)


def scrape_addr() -> Optional[tuple]:
    """(host, port) of the head's Prometheus scrape endpoint, or None when
    no session enabled it (``obs.scrape_port`` conf)."""
    return head_rpc("obs_scrape_addr", timeout=10.0)
