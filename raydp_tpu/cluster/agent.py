"""Node agent: one per (real or simulated) additional host.

The reference's substrate runs actors on many physical machines through Ray's
per-node raylet (SURVEY.md L1); this is that role for the native runtime. An
agent process:

- registers its node (resources, IP, shm namespace) with the head over TCP;
- forks/kills actor worker processes on ITS host when the head schedules
  actors there (the spec ships in the RPC — no shared filesystem assumed);
- serves its node's /dev/shm blocks to remote readers (the data-plane pull
  path: parity with the reference's cross-node plasma reads / the
  RayDatasetRDD owner-IP locality machinery, ObjectStoreReader.scala:34-56);
- watches its children and reports deaths so the head can restart actors
  with the same identity.

On one machine an agent with its own shm NAMESPACE stands in for a separate
host: namespaced objects are never mapped directly by other nodes' processes
— every cross-node read exercises the same network pull path a real
multi-host deployment uses.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import socketserver
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

import cloudpickle

from raydp_tpu.cluster.common import (
    HEAD_ADDR_ENV,
    SESSION_ENV,
    SHM_NS_ENV,
    ActorSpec,
    ClusterError,
    host_id as common_host_id,
    recv_frame,
    rpc,
    send_frame,
    unwrap_traced,
)
from raydp_tpu.obs import log as obs_log
from raydp_tpu.obs import span as obs_span
from raydp_tpu.obs import use_context as obs_use_context


class _ChildProc:
    def __init__(self, proc: subprocess.Popen, incarnation: int):
        self.proc = proc
        self.incarnation = incarnation


class NodeAgent:
    def __init__(
        self,
        head_addr: str,
        node_ip: str,
        resources: Dict[str, float],
        shm_ns: str,
        local_dir: str,
    ):
        self.head_addr = head_addr
        self.node_ip = node_ip
        self.resources = dict(resources)
        self.shm_ns = shm_ns
        self.local_dir = local_dir
        os.makedirs(local_dir, exist_ok=True)
        self.children: Dict[str, _ChildProc] = {}
        # highest incarnation ever spawned here, per actor id. The fence
        # must survive the children-table entry (monitor_loop deletes it
        # after a death report) or a delayed stale spawn arriving AFTER the
        # newer worker died would resurrect a fenced-out incarnation as a
        # leaked live process nothing will ever kill.
        self.incarnation_floor: Dict[str, int] = {}
        # floor entries outlive the children table only for the stale-
        # delivery window; after this grace period with no respawn the entry
        # is pruned (an agent under actor churn must not grow one floor per
        # actor id ever spawned, forever). Scheduled when a death report
        # removes the children entry; cancelled by a fresh spawn.
        self.FLOOR_PRUNE_GRACE_S = 600.0
        self._floor_prune_at: Dict[str, float] = {}
        from raydp_tpu import sanitize

        self.lock = sanitize.named_lock("agent.lock", threading.RLock())
        self.stopping = False
        self.addr: Optional[str] = None
        self.node_id: Optional[str] = None
        self.stats = {"spawned": 0, "blocks_served": 0, "bytes_served": 0}

    # ---------- handlers (same frame protocol as head/actors) ----------

    def handle_ping(self):
        return "pong"

    # raydp-lint: disable=rpc-protocol,rpc-closure (operator introspection
    # surface — poked ad hoc over the agent socket, no in-tree call site)
    def handle_stats(self):
        with self.lock:
            return dict(self.stats)

    def handle_spawn_actor(self, spec: ActorSpec, incarnation: int, head_addr: str):
        """Fork the worker on THIS host. The spec arrives in the RPC and is
        written to the agent's local dir — no shared filesystem with the head
        is assumed (the head-local path writes it to the session dir)."""
        # Fence BEFORE forking: spawn RPCs land on server threads, so a
        # delayed stale delivery (the fenced-out incarnation whose reply the
        # head lost) can arrive AFTER the newer respawn already runs here.
        # Ordering, not inequality, decides who is stale — a stale spawn must
        # never kill or displace the newer healthy worker.
        with self.lock:
            if self.incarnation_floor.get(spec.actor_id, -1) >= incarnation:
                return False  # newer (or duplicate) spawn already owned the id
        spec_path = os.path.join(self.local_dir, f"a-{spec.actor_id}.spec")
        with open(spec_path + ".tmp", "wb") as f:
            cloudpickle.dump(spec, f)
        os.replace(spec_path + ".tmp", spec_path)

        env = dict(os.environ)
        env.update(spec.env)
        env[SESSION_ENV] = self.local_dir
        env[HEAD_ADDR_ENV] = head_addr or self.head_addr
        env[SHM_NS_ENV] = self.shm_ns
        from raydp_tpu.cluster.common import TOKEN_ENV

        if os.environ.get(TOKEN_ENV):  # workers authenticate over TCP too
            env[TOKEN_ENV] = os.environ[TOKEN_ENV]
        env["RAYDP_TPU_ACTOR_ID"] = spec.actor_id
        env["RAYDP_TPU_NODE_ID"] = self.node_id or ""
        env["RAYDP_TPU_NODE_IP"] = self.node_ip
        env["RAYDP_TPU_TCP"] = "1"  # actors must be reachable across hosts
        from raydp_tpu.cluster.common import launch_worker

        proc = launch_worker(spec, incarnation, self.local_dir, env)
        with self.lock:
            old = self.children.get(spec.actor_id)
            if self.incarnation_floor.get(spec.actor_id, -1) >= incarnation:
                # a newer spawn landed while we were forking: OURS is the
                # stale one — reap it and leave the newer worker untouched.
                # The just-forked child may not have setsid'd yet (no
                # process group of its own), so fall back to a direct kill
                # rather than letting it survive untracked.
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    try:
                        os.kill(proc.pid, signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):  # raydp-lint: disable=swallowed-exceptions (kill of an already-dead process is idempotent)
                        pass
                except PermissionError:  # raydp-lint: disable=swallowed-exceptions (killpg fallback; plain kill already sent)
                    pass
                return False
            # an OLDER incarnation still running here is by definition stale
            # once the head spawns a newer one: kill it before its
            # children-table entry — and with it the only pid we hold — is
            # overwritten, or it would leak as a live process for the life
            # of the node
            if old is not None and old.proc.poll() is None:
                try:
                    os.killpg(old.proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):  # raydp-lint: disable=swallowed-exceptions (kill of an already-dead process is idempotent)
                    pass
            self.children[spec.actor_id] = _ChildProc(proc, incarnation)
            self.incarnation_floor[spec.actor_id] = incarnation
            self._floor_prune_at.pop(spec.actor_id, None)  # live again
            self.stats["spawned"] += 1
        return True

    def handle_kill_actor(self, actor_id: str, incarnation: int = -1):
        """Kill a locally-hosted worker. ``incarnation`` >= 0 restricts the
        kill to that exact spawn — the head's fence-out of a possibly-
        delivered stale spawn must not hit a newer healthy replacement that
        was respawned onto this agent in the meantime."""
        with self.lock:
            child = self.children.get(actor_id)
            if (
                child is not None
                and incarnation >= 0
                and child.incarnation != incarnation
            ):
                return False  # a different (newer) spawn owns the id now
        if child is not None and child.proc.poll() is None:
            try:
                os.killpg(child.proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):  # raydp-lint: disable=swallowed-exceptions (kill of an already-dead process is idempotent)
                pass
        return True

    def handle_block_fetch(self, shm_name: str, offset: int = 0, length: int = -1):
        from raydp_tpu.cluster.common import serve_block_bytes

        data = serve_block_bytes(shm_name, offset, length)
        with self.lock:
            self.stats["blocks_served"] += 1
            self.stats["bytes_served"] += len(data)
        return data

    def handle_unlink_shm(self, shm_names: List[str]):
        from raydp_tpu.cluster.common import unlink_block

        for name in shm_names:
            unlink_block(name)
        return True

    def handle_stop(self):
        self.stopping = True
        with self.lock:
            for child in self.children.values():
                if child.proc.poll() is None:
                    try:
                        os.killpg(child.proc.pid, signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):  # raydp-lint: disable=swallowed-exceptions (kill of an already-dead process is idempotent)
                        pass
        return True

    # ---------- lifecycle ----------

    def monitor_loop(self):
        """Report child deaths so the head can run its restart bookkeeping
        (identical semantics to the head's local proc.poll monitoring), and
        watch head liveness: an agent must not outlive its cluster."""
        last_head_ok = time.monotonic()
        last_ping = 0.0
        last_zygote_check = 0.0
        while not self.stopping:
            time.sleep(0.05)
            # zygote liveness (same role as the head's _ensure_zygote, for
            # THIS node): a dead fork template silently degrades every light
            # spawn/restart here to ~450ms cold starts
            now = time.monotonic()
            if now - last_zygote_check > 2.0:
                last_zygote_check = now
                from raydp_tpu.cluster.common import start_zygote, zygote_alive

                if not zygote_alive(self.local_dir):
                    try:
                        start_zygote(self.local_dir)
                    except Exception:
                        # cold-start fallback keeps working, but every spawn
                        # on this node now pays ~450ms of imports — say so
                        obs_log.warning(
                            "zygote restart failed; spawns fall back to "
                            "cold subprocess starts", exc_info=True,
                        )
            dead = []
            with self.lock:
                for actor_id, child in list(self.children.items()):
                    if child.proc.poll() is not None:
                        dead.append((actor_id, child.incarnation))
            for actor_id, incarnation in dead:
                obs_log.warning(
                    "hosted actor exited", actor_id=actor_id,
                    incarnation=incarnation,
                )
                try:
                    rpc(
                        self.head_addr,
                        (
                            "actor_exited",
                            {"actor_id": actor_id, "incarnation": incarnation},
                        ),
                        timeout=10,
                    )
                    last_head_ok = time.monotonic()
                except Exception:  # raydp-lint: disable=swallowed-exceptions (death report kept and retried next loop)
                    continue  # keep the entry: retried next loop — a death
                    # report must not be lost to a transient head blip
                with self.lock:
                    # the head may have ALREADY respawned this actor while we
                    # were reporting (its spawn RPC lands on the server
                    # thread): only remove the entry we actually reported
                    current = self.children.get(actor_id)
                    if current is not None and current.incarnation == incarnation:
                        del self.children[actor_id]
                        # keep the incarnation fence for the stale-delivery
                        # window only; schedule its pruning
                        self._floor_prune_at[actor_id] = (
                            time.monotonic() + self.FLOOR_PRUNE_GRACE_S
                        )
            now = time.monotonic()
            if now - last_ping >= 2.0:
                last_ping = now
                from raydp_tpu.obs import flush_throttled as obs_flush_throttled

                # piggyback the telemetry flush on the ping cadence so agent
                # spans/metrics reach the head without a dedicated flusher
                # thread (metrics push with tracing off too)
                obs_flush_throttled(2.0)
                with self.lock:
                    for actor_id in [
                        a
                        for a, t in self._floor_prune_at.items()
                        if now >= t and a not in self.children
                    ]:
                        self._floor_prune_at.pop(actor_id, None)
                        self.incarnation_floor.pop(actor_id, None)
                try:
                    rpc(self.head_addr, ("ping", {}), timeout=5)
                    last_head_ok = now
                except Exception:
                    # expected while the head is briefly unreachable; the
                    # 15s watchdog below decides — the counter makes flaky
                    # links visible without log spam
                    from raydp_tpu.obs import metrics

                    metrics.counter("agent.head_ping_failures").inc()
            if now - last_head_ok > 15.0:
                # head gone: tear down children and exit (parity: Ray nodes
                # die with their GCS; prevents orphaned agent processes)
                self.handle_stop()
                return

    def serve(self):
        agent = self

        from raydp_tpu.cluster.common import session_token, verify_token

        token = session_token()

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                if not verify_token(self.request, token):
                    return
                try:
                    frame = recv_frame(self.request)
                except (ConnectionError, EOFError):
                    return
                frame, trace_ctx = unwrap_traced(frame)
                method, kwargs = frame
                try:
                    fn = getattr(agent, f"handle_{method}", None)
                    if fn is None:
                        raise ClusterError(f"unknown agent method {method!r}")
                    if trace_ctx is not None:
                        with obs_use_context(trace_ctx), obs_span(
                            f"agent.{method}"
                        ):
                            reply = ("ok", fn(**kwargs))
                    else:
                        reply = ("ok", fn(**kwargs))
                except BaseException as exc:  # noqa: BLE001
                    reply = ("err", exc)
                try:
                    send_frame(self.request, reply)
                except ConnectionError:  # raydp-lint: disable=swallowed-exceptions (peer hung up; no one left to reply to)
                    pass

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        server = Server(("0.0.0.0", 0), Handler)
        self.addr = f"tcp://{self.node_ip}:{server.server_address[1]}"
        self.node_id = rpc(
            self.head_addr,
            (
                "register_agent",
                {
                    "resources": self.resources,
                    "node_ip": self.node_ip,
                    "agent_addr": self.addr,
                    "shm_ns": self.shm_ns,
                    # host axis: RAYDP_TPU_HOST_ID when set (real multi-host
                    # or the simulated harness), else the shm namespace —
                    # which already has host granularity
                    "host": common_host_id(),
                },
            ),
            timeout=30,
        )
        # pre-warmed fork template for THIS node's light actors (same role as
        # the head's zygote; launch_worker routes through it)
        from raydp_tpu.cluster.common import start_zygote

        try:
            start_zygote(self.local_dir)
        except Exception:
            obs_log.warning(
                "zygote start failed at agent boot; spawns fall back to "
                "cold subprocess starts", exc_info=True,
            )
        # publish readiness for whoever launched us
        ready = os.path.join(self.local_dir, "agent_ready.json")
        with open(ready + ".tmp", "w") as f:
            json.dump({"addr": self.addr, "node_id": self.node_id}, f)
        os.replace(ready + ".tmp", ready)
        threading.Thread(target=self.monitor_loop, daemon=True).start()
        server.timeout = 0.2
        try:
            while not self.stopping:
                server.handle_request()
        finally:
            server.server_close()
            from raydp_tpu import sanitize

            try:
                sanitize.audit_leaks(f"agent:{self.node_ip}")
            except sanitize.LeakError:
                obs_log.error(
                    "agent leaked resources at shutdown", exc_info=True
                )


def main() -> None:
    head_addr, node_ip, shm_ns, local_dir, resources_json = sys.argv[1:6]
    from raydp_tpu.obs import set_process_role

    # node-qualified role: two agents on different hosts can share an OS
    # pid, and the (role, pid) pair keys metric snapshots and trace tracks
    set_process_role(f"agent:{node_ip}")
    from raydp_tpu import sanitize

    sanitize.snapshot_baseline()  # leak-audit floor for this agent process
    # anchor the serving root: the spill-path sanitizer pins file:// block
    # reads/unlinks to THIS node's spill dir
    os.environ[SESSION_ENV] = local_dir
    agent = NodeAgent(
        head_addr, node_ip, json.loads(resources_json), shm_ns, local_dir
    )
    agent.serve()


if __name__ == "__main__":
    main()
