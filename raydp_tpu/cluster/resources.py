"""Cluster resource inspection with a short cache.

Parity: reference ``ClusterResources`` (ray_cluster_resources.py:25-79) —
polls the node table at most every ``REFRESH_INTERVAL`` seconds and matches
resource requests to nodes via their ``node:<ip>`` labels.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from raydp_tpu.cluster import api as cluster


class ClusterResources:
    REFRESH_INTERVAL = 0.1

    _last_refresh = 0.0
    _cached: List = []

    @classmethod
    def _nodes(cls) -> List:
        now = time.monotonic()
        if now - cls._last_refresh > cls.REFRESH_INTERVAL:
            cls._cached = cluster.nodes()
            cls._last_refresh = now
        return cls._cached

    @classmethod
    def total_alive_nodes(cls) -> int:
        return sum(1 for n in cls._nodes() if getattr(n, "alive", True))

    @classmethod
    def satisfy(cls, request: Dict[str, float]) -> List[str]:
        """Node labels (node:<ip>) whose resources satisfy ``request``."""
        out = []
        for node in cls._nodes():
            resources = getattr(node, "resources", {})
            if all(resources.get(k, 0.0) >= v for k, v in request.items()):
                label = next(
                    (k for k in resources if k.startswith("node:")), None
                )
                out.append(label or getattr(node, "node_id", ""))
        return out

    @classmethod
    def total_resources(cls) -> Dict[str, float]:
        totals: Dict[str, float] = {}
        for node_resources in cluster.total_resources().values():
            for k, v in node_resources.items():
                totals[k] = totals.get(k, 0.0) + v
        return totals
