"""Worker zygote: a pre-warmed fork template for actor processes.

Cold actor spawn costs ~0.45s of pure Python imports (worker runtime +
pyarrow Arrow stack), paid per actor per (re)start — it dominated session
startup and made elastic restarts slow. The zygote pays those imports ONCE:
the head (and each node agent) forks a single template process at boot that
imports the common dependency set and then serves fork requests on a Unix
socket in the session dir. Each actor spawn becomes one fork(2) — the child
inherits the warmed modules copy-on-write and calls ``worker.main()``
directly, no exec, no re-import. Measured: ~10-20ms per spawn vs ~450ms.

This plays the role Ray's prestarted worker pool plays in the reference's
substrate (SURVEY.md L1): actor creation latency decoupled from interpreter
warm-up. Restart-after-crash (max_restarts) rides the same path, so elastic
recovery is fast too.

Protocol: one frame per connection — {run_dir, actor_id, incarnation, env,
log_base} → ("ok", child_pid). The requester (head or agent) monitors the
child with a pid-probe Popen shim (children are reaped HERE, by their true
parent). The zygote exits when its parent does (getppid watch), so cluster
shutdown needs no extra plumbing. Only ``light`` actors route here; actors
that need sitecustomize (jax/TPU plugin registration) still get a full
interpreter start.
"""

from __future__ import annotations

import os
import socket
import sys

ZYGOTE_SOCK_FILE = "zygote.sock"
ZYGOTE_MARKER_FILE = "zygote.pid"
ZYGOTE_ADOPTION_STAMP_FILE = "adopted.stamp"
# serving fork template: warm the jax/flax/orbax import set too (set before
# the zygote starts — i.e. before the first cluster.init on the machine)
WARM_JAX_ENV = "RAYDP_TPU_ZYGOTE_WARM_JAX"

_listener: socket.socket | None = None


def zygote_sock_path(run_dir: str) -> str:
    return os.path.join(run_dir, ZYGOTE_SOCK_FILE)


def zygote_marker_path(run_dir: str) -> str:
    return os.path.join(run_dir, ZYGOTE_MARKER_FILE)


def adoption_stamp_path(run_dir: str) -> str:
    return os.path.join(run_dir, ZYGOTE_ADOPTION_STAMP_FILE)


def touch_adoption_stamp(run_dir: str) -> None:
    """Record 'a session adopted this template NOW'. Written by
    ``common._adopt_global_zygote`` while it HOLDS the adoption flock, so
    retirement (which also takes the flock) observes every adoption that
    completed before it could acquire the lock — the lock-protected
    last-adopted stamp ADVICE r5 asked for."""
    stamp = adoption_stamp_path(run_dir)
    with open(stamp, "w") as f:
        f.write(str(os.getpid()))
    # the mtime IS the datum; writing the pid is purely diagnostic


def adoption_recent(run_dir: str, ttl_s: float) -> bool:
    """Did a session adopt this template within ``ttl_s``? Read under the
    adoption flock by the retirement path: a fresh stamp vetoes retirement
    (the stamp is re-checked AFTER taking the lock, closing the window where
    an adoption landed between the idle-TTL check and the lock acquire)."""
    import time

    try:
        age = time.time() - os.stat(adoption_stamp_path(run_dir)).st_mtime
    except OSError:
        return False
    # a negative age (clock step) counts as recent: err towards staying up
    return age <= ttl_s


def _warm_imports() -> None:
    """Import what (nearly) every light actor needs BEFORE binding the fork
    socket. pandas belongs here even though the worker ready path never
    touches it: pyarrow's first pa.array/pa.scalar resolves its lazy
    pandas-compat shim by importing pandas (~0.35s), so any child forked
    without it pays that on its FIRST TASK — once per child instead of once
    per zygote. Failures are tolerated: a zygote without pyarrow still
    serves forks, children just import lazily."""
    import cloudpickle  # noqa: F401
    import raydp_tpu.cluster.worker  # noqa: F401

    try:
        import numpy  # noqa: F401
        import pandas  # noqa: F401  (pyarrow's pa.array imports it anyway)
        import pyarrow  # noqa: F401
        import pyarrow.compute  # noqa: F401

        import pyarrow as _pa

        # resolve the pandas-compat shim NOW: pa.array/pa.scalar do this
        # lazily on first use, and children should inherit it resolved
        _pa.array([0])

        import raydp_tpu.etl.executor  # noqa: F401
        import raydp_tpu.etl.tasks  # noqa: F401
        import raydp_tpu.store.object_store  # noqa: F401
    except Exception:  # pragma: no cover - partial environments; raydp-lint: disable=swallowed-exceptions (partial environments: children import lazily)
        pass
    if os.environ.get(WARM_JAX_ENV) == "1":
        # serving fork template (docs/serving.md): model REPLICAS are light
        # actors that need the jax/flax/orbax import set (~1-2s cold), which
        # dominates replica spin-up once the fork itself is ~10ms. Opt-in by
        # env because (a) a template this heavy is wasted on ETL-only
        # clusters and (b) children inherit the IMPORTED modules only — no
        # backend may initialize here (a forked PJRT client is undefined
        # behavior), so nothing below touches devices.
        try:
            import jax  # noqa: F401
            import flax.linen  # noqa: F401
            import orbax.checkpoint  # noqa: F401
        except Exception:  # pragma: no cover - partial environments; raydp-lint: disable=swallowed-exceptions (partial environments: replicas import lazily)
            pass


def _become_worker(req: dict, conn: socket.socket) -> None:
    """Runs in the forked CHILD: detach, redirect logs, adopt the requested
    environment, and hand control to the worker entry point."""
    global _listener
    try:
        os.setsid()  # own process group: killpg(pid) from head/agent works
        conn.close()
        if _listener is not None:
            _listener.close()
        out = os.open(
            req["log_base"] + ".out", os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
        )
        err = os.open(
            req["log_base"] + ".err", os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
        )
        os.dup2(out, 1)
        os.dup2(err, 2)
        os.close(out)
        os.close(err)
        env = req["env"]
        os.environ.clear()
        os.environ.update(env)
        # adopt the SPAWNER's cwd (what a cold subprocess start would
        # inherit): a machine-global zygote's own cwd is whichever driver
        # started it first — possibly deleted, and never session B's
        try:
            os.chdir(req.get("cwd") or req["run_dir"])
        except OSError:
            os.chdir("/")
        # PYTHONPATH is normally consumed at interpreter start — this child
        # skipped that, so graft any missing entries onto sys.path (user
        # actor classes may live outside the zygote's own path)
        for entry in reversed(env.get("PYTHONPATH", "").split(os.pathsep)):
            if entry and entry not in sys.path:
                sys.path.insert(0, entry)
        if req.get("kind") == "main":
            # pre-forked MODULE MAIN (head / agent entry): the child
            # inherits the warmed import set and jumps straight into the
            # module's main() — a head boot becomes a ~10ms fork instead of
            # a cold `python -S` interpreter + import start
            import importlib

            sys.argv = [req["module"]] + [str(a) for a in req.get("argv", [])]
            importlib.import_module(req["module"]).main()
        else:
            sys.argv = [
                "raydp_tpu-worker",
                req["run_dir"],
                req["actor_id"],
                str(req["incarnation"]),
            ]
            from raydp_tpu.cluster import worker

            worker.main()
    except SystemExit:  # raydp-lint: disable=swallowed-exceptions (worker.main exits via SystemExit on clean shutdown)
        pass
    except BaseException:  # noqa: BLE001 - last-resort report to the log
        from raydp_tpu.obs import get_logger

        get_logger("zygote-child").exception(
            "forked worker died before handing off to worker.main",
            actor_id=req.get("actor_id"), run_dir=req.get("run_dir"),
        )
        os._exit(1)
    finally:
        os._exit(0)


def _serve_one(children: dict) -> bool:
    """Accept and serve one fork request; False on accept timeout. An
    empty connection (liveness probes) counts as activity but forks
    nothing. (Adoptions no longer poke the socket — they write the
    lock-protected adoption stamp instead, which retirement re-checks.)"""
    from raydp_tpu.cluster.common import recv_frame, send_frame

    try:
        conn, _ = _listener.accept()
    except socket.timeout:
        return False
    except OSError:
        os._exit(0)
    try:
        try:
            req = recv_frame(conn)
        except (ConnectionError, EOFError):
            return True  # poke/probe: no request followed the connect
        pid = os.fork()
        if pid == 0:
            _become_worker(req, conn)  # never returns
        children[pid] = req["log_base"]
        send_frame(conn, ("ok", pid))
    except Exception:  # noqa: BLE001 - a bad request must not kill the zygote
        from raydp_tpu.obs import get_logger

        get_logger("zygote").exception("fork request failed")
    finally:
        try:
            conn.close()
        except OSError:  # raydp-lint: disable=swallowed-exceptions (closing a possibly-closed connection)
            pass
    return True


GLOBAL_MODE_ENV = "RAYDP_TPU_ZYGOTE_GLOBAL"
# a machine-global zygote with no fork requests for this long exits (it has
# no owning cluster to die with; sessions re-adopt or restart one on demand)
GLOBAL_IDLE_TTL_S = 1800.0


def main() -> None:
    global _listener
    run_dir = sys.argv[1]
    from raydp_tpu.obs import set_process_role

    set_process_role("zygote")
    # global mode (common.start_zygote): this zygote serves EVERY cluster of
    # this user+source-tree on the machine — fork requests carry the target
    # session's run_dir/env, so nothing here is session-specific. It ignores
    # parent death (its starter is just whichever driver came first) and
    # retires itself after an idle TTL instead.
    global_mode = os.environ.get(GLOBAL_MODE_ENV) == "1"
    _warm_imports()

    path = zygote_sock_path(run_dir)
    try:
        os.unlink(path)
    except OSError:  # raydp-lint: disable=swallowed-exceptions (stale socket may not exist)
        pass
    _listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    _listener.bind(path)
    _listener.listen(64)
    parent = os.getppid()
    children: dict = {}  # pid -> log_base, for exit markers at reap time
    import time as _time

    last_fork = _time.monotonic()

    # 50ms accept timeout bounds child-reap latency (the .exit markers are
    # one of the signals ZygoteProc.poll reads; zombie detection via /proc
    # covers the window before the marker lands)
    _listener.settimeout(0.05)
    while True:
        # reap exited children; record each child's true exit status in an
        # ``<log_base>.exit`` marker. Monitors hold only a pid (the child is
        # reaped HERE, by its true parent), and a raw pid probe lies twice:
        # it reports "alive" after pid reuse, and it can never recover the
        # exit code. The marker is the ground truth ZygoteProc.poll reads.
        while True:
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:  # raydp-lint: disable=swallowed-exceptions (no children left to reap)
                break
            if pid == 0:
                break
            log_base = children.pop(pid, None)
            if log_base is not None:
                try:
                    code = os.waitstatus_to_exitcode(status)
                    with open(log_base + ".exit.tmp", "w") as f:
                        f.write(str(code))
                    os.replace(log_base + ".exit.tmp", log_base + ".exit")
                except OSError:  # raydp-lint: disable=swallowed-exceptions (marker write best-effort; zombie probe covers the gap)
                    pass
        if global_mode:
            # linger only while useful: exit when idle past the TTL and no
            # children remain to reap (their exit markers must not be lost).
            # The adoption lock serializes retirement against adoption, and
            # the lock-protected adoption stamp closes the residual race
            # (ADVICE r5): adoption's idle-clock poke used to land AFTER the
            # flock was released, so a template exactly at its TTL could
            # take the lock and retire right after a session adopted it —
            # the stamp is written UNDER the adoption lock and re-checked
            # here UNDER the same lock, so a just-adopted template always
            # observes the adoption and stays alive.
            if (
                not children
                and _time.monotonic() - last_fork > GLOBAL_IDLE_TTL_S
            ):
                import fcntl

                try:
                    lock_file = open(os.path.join(run_dir, ".lock"), "w")
                except OSError:  # raydp-lint: disable=swallowed-exceptions (cannot open the lock: retry next round)
                    continue
                try:
                    fcntl.flock(lock_file, fcntl.LOCK_EX | fcntl.LOCK_NB)
                except OSError:
                    lock_file.close()
                    continue  # adoption in progress: stay alive this round
                if adoption_recent(run_dir, GLOBAL_IDLE_TTL_S):
                    # adopted since our last fork: treat as activity and
                    # serve a full TTL for the adopting session
                    fcntl.flock(lock_file, fcntl.LOCK_UN)
                    lock_file.close()
                    last_fork = _time.monotonic()
                    continue
                marker = zygote_marker_path(run_dir)
                for stale in (
                    path, marker, marker + ".start", adoption_stamp_path(run_dir)
                ):
                    try:  # a marker left behind + pid reuse would make a
                        os.unlink(stale)  # later adoption latch onto an
                    except OSError:  # unrelated process; raydp-lint: disable=swallowed-exceptions (retirement cleanup of files that may not exist)
                        pass
                os._exit(0)  # lock released by process exit
        elif os.getppid() != parent:
            os._exit(0)  # the head/agent died; the cluster is gone
        if _serve_one(children):
            last_fork = _time.monotonic()


if __name__ == "__main__":
    main()
