"""``python -m raydp_tpu.cluster.head_main <session_dir>`` — head process entry."""

import os
import secrets
import sys

import cloudpickle

from raydp_tpu.cluster.common import TOKEN_FILE, TOKEN_LEN
from raydp_tpu.cluster.head import run_head


def main() -> None:
    session_dir = sys.argv[1]
    # anchor the serving root: the spill-path sanitizer pins file:// block
    # reads/unlinks to THIS session's spill dir
    from raydp_tpu.cluster.common import SESSION_ENV

    os.environ[SESSION_ENV] = session_dir
    # a zygote-forked head inherits the TEMPLATE's tracing state and lock-
    # order history; this session's env (delivered with the fork request)
    # decides — same re-init dance the worker entry does
    from raydp_tpu.obs.tracing import reinit_for_process

    reinit_for_process("head")
    from raydp_tpu import sanitize

    sanitize.reset_lockdep()
    with open(os.path.join(session_dir, "head_boot.pkl"), "rb") as f:
        driver_pid, default_resources = cloudpickle.load(f)
    # the cluster's shared secret, written before any socket exists; the
    # session dir is mkdtemp(0700) so only the session's user can read it
    token_path = os.path.join(session_dir, TOKEN_FILE)
    if not os.path.exists(token_path):
        with open(token_path + ".tmp", "wb") as f:
            f.write(secrets.token_bytes(TOKEN_LEN))
        os.replace(token_path + ".tmp", token_path)
    run_head(session_dir, driver_pid, default_resources)


if __name__ == "__main__":
    main()
