"""``python -m raydp_tpu.cluster.head_main <session_dir>`` — head process entry."""

import os
import sys

import cloudpickle

from raydp_tpu.cluster.head import run_head


def main() -> None:
    session_dir = sys.argv[1]
    with open(os.path.join(session_dir, "head_boot.pkl"), "rb") as f:
        driver_pid, default_resources = cloudpickle.load(f)
    run_head(session_dir, driver_pid, default_resources)


if __name__ == "__main__":
    main()
