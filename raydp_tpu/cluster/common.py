"""Wire protocol + shared records for the cluster runtime.

The runtime replaces the reference's substrate (Ray actors + GCS; SURVEY.md L1)
with a small native stack: one *head* process holding cluster state (actors,
virtual nodes, placement groups, object metadata) and one OS process per actor,
all talking length-prefixed cloudpickle frames over Unix-domain sockets. On a
TPU pod this head runs on the coordinator host and the socket layer swaps to
TCP; the control plane is deliberately tiny because the data plane (gradient
and activation traffic) is XLA collectives compiled into step functions, never
these sockets.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import socket
import struct
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

_LEN = struct.Struct(">I")
MAX_FRAME = 1 << 30

HEAD_SOCK_NAME = "head.sock"
HEAD_TCP_FILE = "head_tcp.addr"
TOKEN_FILE = "cluster.token"
SESSION_ENV = "RAYDP_TPU_SESSION"
HEAD_ADDR_ENV = "RAYDP_TPU_HEAD_ADDR"
SHM_NS_ENV = "RAYDP_TPU_SHM_NS"
HOST_ID_ENV = "RAYDP_TPU_HOST_ID"
TOKEN_ENV = "RAYDP_TPU_TOKEN"
DRIVER_OWNER = "__driver__"
TOKEN_LEN = 32


class ClusterError(RuntimeError):
    pass


class ActorDiedError(ClusterError):
    """The callee actor is dead (crashed past max_restarts or intentionally exited)."""


class OwnerDiedError(ClusterError):
    """An object's owner died and the object was not transferred (parity:
    ray.exceptions.OwnerDiedError asserted in reference
    test_data_owner_transfer.py:33-77)."""


class ProgramCacheMiss(ClusterError):
    """Raised by an executor asked to run a program id it has never seen
    (cache evicted / actor restarted): the driver re-dispatches with the
    program body attached. Picklable with its single string arg; defined
    here (not in etl/program.py) because it crosses the executor RPC
    boundary and the catching process must be able to unpickle it without
    the etl import set."""


class TenantQuotaError(ClusterError):
    """A tenant exceeded one of its quotas (max block bytes at the head,
    max in-flight / queued tasks at the fair-share scheduler). Typed so
    callers can tell an over-quota rejection from an infrastructure failure
    — the multi-tenant contract is reject-fast, never wedge the queue
    (docs/multitenancy.md). Carries ``tenant`` when known; defined here so
    it pickles across the head RPC boundary like every cluster error."""

    tenant: str = ""


def tenant_of_object(object_id: str) -> str:
    """The tenant namespace encoded in a block's object id (empty for
    unprefixed ids — single-session / tenancy-off blocks). Tenant-scoped
    writers mint ids as ``<tenant>.<hex16>`` (store.new_object_id); the hex
    tail never contains a dot, so the LAST dot splits unambiguously."""
    head, sep, _tail = object_id.rpartition(".")
    return head if sep else ""


class ActorState(str, enum.Enum):
    PENDING = "PENDING"
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"


def send_frame(sock: socket.socket, obj: Any) -> None:
    payload = cloudpickle.dumps(obj)
    if len(payload) > MAX_FRAME:
        raise ClusterError(f"frame too large: {len(payload)} bytes")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return cloudpickle.loads(_recv_exact(sock, length))


def session_token() -> bytes:
    """The cluster's shared secret. TCP peers must present it before any
    frame is parsed — without it, a reachable port would mean arbitrary
    unpickling (RCE) for anyone on the network. Resolution: env (remote
    processes) → the session dir's token file (head-local processes)."""
    env_token = os.environ.get(TOKEN_ENV)
    if env_token:
        return bytes.fromhex(env_token)
    session = os.environ.get(SESSION_ENV)
    if session:
        path = os.path.join(session, TOKEN_FILE)
        if os.path.exists(path):
            with open(path, "rb") as f:
                return f.read()
    return b"\0" * TOKEN_LEN  # no session context: deliberately non-matching


def load_token(session_dir: str) -> bytes:
    with open(os.path.join(session_dir, TOKEN_FILE), "rb") as f:
        return f.read()


def verify_token(sock: socket.socket, expected: bytes) -> bool:
    """Server side of the TCP handshake: challenge-response, verified before
    any frame touches cloudpickle (a reachable port must not mean arbitrary
    unpickling). The server sends a fresh nonce and the client proves
    possession with HMAC-SHA256(token, nonce) — the secret itself never
    crosses the wire, so a passive observer cannot capture-and-replay it.
    (An attacker who can fully MITM an established connection can still relay
    frames; untrusted networks need TLS on top.)"""
    import hashlib
    import hmac

    try:
        nonce = os.urandom(TOKEN_LEN)
        sock.sendall(nonce)
        presented = _recv_exact(sock, hashlib.sha256().digest_size)
    except OSError:
        return False
    digest = hmac.new(expected, nonce, hashlib.sha256).digest()
    return hmac.compare_digest(presented, digest)


def connect(addr: str, timeout: Optional[float] = None) -> socket.socket:
    """Connect to either transport: ``tcp://host:port`` or a Unix socket
    path. The TCP side is what makes the substrate multi-host — agents and
    their actors on other machines are addressed exactly like local ones.
    TCP connections start with the session-token handshake; Unix sockets are
    guarded by the session dir's filesystem permissions instead."""
    if addr.startswith("tcp://"):
        host, _, port = addr[6:].rpartition(":")
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect((host, int(port)))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # client side of the challenge-response handshake (see verify_token)
        import hashlib
        import hmac

        nonce = _recv_exact(sock, TOKEN_LEN)
        sock.sendall(hmac.new(session_token(), nonce, hashlib.sha256).digest())
        return sock
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout)
    sock.connect(addr)
    return sock


def safe_shm_name(shm_name: str) -> str:
    """Reject anything but a flat segment name (a client-supplied name is
    joined under /dev/shm — path traversal must be impossible)."""
    name = shm_name.lstrip("/")
    if not name or "/" in name or ".." in name or not name.startswith("rtpu-"):
        raise ClusterError(f"invalid shm segment name {shm_name!r}")
    return name


def resolve_head_addr(session_dir: str) -> str:
    """The head's address for THIS process: remote processes (spawned via a
    node agent) carry it in the environment; head-local ones use the Unix
    socket in the session dir. A dir WITHOUT a head socket is a tcp://
    client's local dir — its ``head_tcp.addr`` file (written at attach)
    carries the address, so handles pickled BY the client resolve anywhere
    in the cluster (an actor holding such a handle has neither the client's
    env nor its head socket)."""
    env_addr = os.environ.get(HEAD_ADDR_ENV)
    if env_addr:
        return env_addr
    sock = head_sock_path(session_dir)
    if not os.path.exists(sock):
        tcp_file = os.path.join(session_dir, HEAD_TCP_FILE)
        try:
            with open(tcp_file) as f:
                addr = f.read().strip()
            if addr:
                return addr
        except OSError:  # raydp-lint: disable=swallowed-exceptions (no tcp addr file: fall through to the unix socket)
            pass
    return sock


def shm_namespace() -> str:
    """This process's shared-memory namespace (one per node). Objects are
    only mapped directly when their namespace matches; everything else goes
    through the owning node's block server."""
    return os.environ.get(SHM_NS_ENV, "")


def host_id() -> str:
    """This process's host identity on the cluster's host axis. Real
    multi-host deployments set ``RAYDP_TPU_HOST_ID`` per box; the simulated
    multi-host harness (two agents on one machine with distinct shm
    namespaces) falls back to the shm namespace, which already has exactly
    host granularity — same namespace ⇒ blocks map locally, different
    namespace ⇒ bytes cross the (possibly loopback) wire. Empty string is
    the head's own host."""
    return os.environ.get(HOST_ID_ENV) or shm_namespace()


def host_label(host: str) -> str:
    """Metric-safe token for a host id (flat dotted metric names — empty
    host is the head's, dots would split the name)."""
    return (host or "head").replace(".", "_")


# ---------------------------------------------------------------------------
# trace-context propagation (obs layer)
#
# When tracing is on and the calling thread carries a span context, outgoing
# requests are wrapped in an ``("__obs__", (trace_id, span_id), request)``
# envelope; servers unwrap with ``unwrap_traced`` and adopt the context around
# the handled call, so one query's spans link across driver, head, agents and
# executors. Untraced frames are byte-identical to before.
# ---------------------------------------------------------------------------

OBS_FRAME_MARK = "__obs__"


def traced_request(request: Tuple) -> Tuple:
    from raydp_tpu.obs.tracing import current_context, enabled

    if enabled():
        ctx = current_context()
        if ctx is not None:
            return (OBS_FRAME_MARK, ctx, request)
    return request


def unwrap_traced(request: Any) -> Tuple[Any, Optional[Tuple[str, str]]]:
    """(inner_request, trace_ctx_or_None) — the server half."""
    if (
        isinstance(request, tuple)
        and len(request) == 3
        and request[0] == OBS_FRAME_MARK
    ):
        return request[2], request[1]
    return request, None


def _observe_rpc(request: Tuple, seconds: float) -> None:
    from raydp_tpu.obs.metrics import metrics

    metrics.counter("rpc.client.calls").inc()
    metrics.histogram("rpc.client.seconds").observe(seconds)
    if isinstance(request, tuple) and request and isinstance(request[0], str):
        metrics.counter(f"rpc.client.calls.{request[0]}").inc()


def rpc(sock_path: str, request: Tuple, timeout: Optional[float] = 60.0) -> Any:
    """One-shot request/response. Raises the remote exception if status != ok."""
    t0 = time.perf_counter()
    with connect(sock_path, timeout) as sock:
        send_frame(sock, traced_request(request))
        status, value = recv_frame(sock)
    _observe_rpc(request, time.perf_counter() - t0)
    if status == "ok":
        return value
    raise value


# ---------------------------------------------------------------------------
# pooled RPC: persistent per-(thread, address) connections
#
# Control-plane servers serve multiple sequential frames per connection, so
# hot callers (object register/lookup on every block write/read, task
# dispatch bookkeeping) skip the ~ms connect + accept-thread cost per call.
# Strictly sequential request/response per connection — concurrency comes
# from each thread owning its own socket.
# ---------------------------------------------------------------------------

import threading as _threading

_rpc_pool_tls = _threading.local()
_POOL_MAX_ADDRS = 8  # old sessions' sockets must not accumulate per thread


def _pool_drop(addr: str) -> None:
    conns = getattr(_rpc_pool_tls, "conns", None)
    if conns:
        sock = conns.pop(addr, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:  # raydp-lint: disable=swallowed-exceptions (closing a possibly-dead pooled socket)
                pass


def close_pooled_connections() -> None:
    """Close THIS thread's pooled RPC sockets (shutdown hygiene: the pool
    keeps one live socket per address for the thread's lifetime, which the
    leak sanitizer's fd audit would otherwise count against the baseline
    forever)."""
    conns = getattr(_rpc_pool_tls, "conns", None)
    if not conns:
        return
    for addr in list(conns):
        _pool_drop(addr)


def rpc_pooled(sock_path: str, request: Tuple, timeout: Optional[float] = 60.0) -> Any:
    """Request/response over a cached per-thread connection. A stale cached
    connection (server restarted / closed idle) is dropped and the request
    retried ONCE on a fresh connection — the same failure surface a fresh-
    connection caller has. Callers routing non-idempotent requests should
    use ``rpc`` instead."""
    conns = getattr(_rpc_pool_tls, "conns", None)
    if conns is None:
        conns = _rpc_pool_tls.conns = {}
    t0 = time.monotonic()
    wire_request = traced_request(request)
    for attempt in (0, 1):
        sock = conns.get(sock_path)
        fresh = sock is None
        try:
            if sock is None:
                if len(conns) >= _POOL_MAX_ADDRS:
                    for stale in list(conns):
                        _pool_drop(stale)
                sock = connect(sock_path, timeout)
                conns[sock_path] = sock
            sock.settimeout(timeout)
            send_frame(sock, wire_request)
            status, value = recv_frame(sock)
            break
        except socket.timeout:
            # the server HAS the request and may still be processing it —
            # retrying would double-execute (create_actor would leak a
            # second process). Propagate like plain rpc(); the connection
            # is poisoned (a late reply would desync the stream), so drop it.
            _pool_drop(sock_path)
            raise
        except (EOFError, OSError):
            _pool_drop(sock_path)
            if attempt or fresh:
                raise
    _observe_rpc(request, time.monotonic() - t0)
    if status == "ok":
        return value
    raise value


def wait_for_path(path: str, timeout: float, what: str) -> None:
    deadline = time.monotonic() + timeout
    while not os.path.exists(path):
        if time.monotonic() > deadline:
            raise ClusterError(f"timed out waiting for {what} at {path}")
        # 5ms: this poll sits on the warm-boot critical path (head socket
        # after a ~10ms zygote fork) — a 20ms granularity dominated it
        time.sleep(0.005)


@dataclasses.dataclass
class ActorSpec:
    """Everything needed to (re)start an actor process; persisted to the session
    dir so the head can respawn a crashed actor with the same identity
    (restart-aware identity, parity: RayDPExecutor restart dance,
    reference RayDPExecutor.scala:84-96 / RayExecutorUtils.java:63-65)."""

    actor_id: str
    name: Optional[str]
    cls_blob: bytes  # cloudpickled class
    args_blob: bytes  # cloudpickled (args, kwargs)
    resources: Dict[str, float]
    max_restarts: int = 0
    max_concurrency: int = 1
    placement_group: Optional[str] = None
    bundle_index: int = -1
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    # light=True starts the actor with `python -S`: site/sitecustomize are
    # skipped (this image's sitecustomize imports jax + the TPU plugin,
    # ~2.6s per process) and imports resolve via the PYTHONPATH the spawner
    # provides. ETL/storage actors never touch jax; SPMD ranks that need the
    # TPU plugin registered must set light=False.
    light: bool = True


@dataclasses.dataclass
class ActorRecord:
    """Head-side view of one actor, as reported to clients."""

    actor_id: str
    name: Optional[str]
    state: ActorState
    incarnation: int
    sock_path: Optional[str]
    node_id: Optional[str]
    node_ip: Optional[str]
    restarts_used: int = 0
    error: Optional[str] = None
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class NodeRecord:
    node_id: str
    node_ip: str
    resources: Dict[str, float]
    alive: bool = True
    # agent-backed nodes (real multi-host): the head spawns/kills actors and
    # fetches blocks through the agent's TCP address; shm_ns is the node's
    # shared-memory namespace (objects from other namespaces must be pulled
    # over the network, never mapped)
    agent_addr: Optional[str] = None
    shm_ns: str = ""
    # host axis (ISSUE 18): which physical (or simulated) host this node
    # lives on. Placement scoring and transport selection key on it; ""
    # means the head's own host. Defaults keep old pickles/ctors valid.
    host: str = ""


def actor_sock_path(session_dir: str, actor_id: str, incarnation: int) -> str:
    return os.path.join(session_dir, f"a-{actor_id}-{incarnation}.sock")


def head_sock_path(session_dir: str) -> str:
    return os.path.join(session_dir, HEAD_SOCK_NAME)


def safe_spill_path(name: str) -> str:
    """Validate a ``file://`` block location before serving/unlinking it: the
    resolved path must be a framework spill file (rtpu- prefixed) DIRECTLY
    inside this process's own spill root (``$RAYDP_TPU_SESSION/spill`` —
    head_main/agent anchor it at boot) — a client-supplied path must not be
    able to read or remove arbitrary files, nor another session's spill."""
    path = os.path.realpath(name[len("file://"):])
    base = os.path.basename(path)
    if not base.startswith("rtpu-"):
        raise ClusterError(f"invalid spill block path {name!r}")
    session = os.environ.get(SESSION_ENV)
    if not session:
        raise ClusterError(
            f"cannot serve spill path {name!r}: no session root anchored"
        )
    root = os.path.realpath(os.path.join(session, "spill"))
    if os.path.dirname(path) != root:
        raise ClusterError(f"spill path {name!r} outside this node's spill dir")
    return path


def object_meta_entry(
    object_id: str, owner: str, shm_name: str, size: int,
    node_id: str, shm_ns: str = "",
) -> Dict[str, Any]:
    """The canonical metadata-registration record for one object-store
    block — the single schema shared by the per-block ``object_put`` RPC and
    the vectorized ``object_put_batch`` frame (store client side and head
    handler side both build/consume exactly this shape)."""
    return {
        "object_id": object_id,
        "owner": owner,
        "shm_name": shm_name,
        "size": size,
        "node_id": node_id,
        "shm_ns": shm_ns,
    }


def serve_block_bytes(shm_name: str, offset: int = 0, length: int = -1) -> bytes:
    """Read a local block for a remote reader (the block-server primitive
    shared by the head and node agents — one copy of the sanitize/seek/length
    logic). Serves both tiers: /dev/shm segments and ``file://`` spill files."""
    if shm_name.startswith("file://"):
        path = safe_spill_path(shm_name)
    else:
        path = os.path.join("/dev/shm", safe_shm_name(shm_name))
    with open(path, "rb") as f:
        f.seek(offset)
        return f.read() if length < 0 else f.read(length)


class RawView:
    """A zero-copy reply payload: a read-only view over an mmap of the
    block's backing file. When an actor method returns one, the worker's
    serve loop streams the bytes straight from the page cache onto the
    socket — ``("raw", size)`` header frame, then ``size`` raw bytes — with
    no pickling and no intermediate copy. The handler, not the method, owns
    closing it (the view must stay mapped until sendall returns)."""

    __slots__ = ("view", "size", "_mm")

    def __init__(self, mm, view: memoryview):
        self._mm = mm
        self.view = view
        self.size = len(view)

    def close(self) -> None:
        try:
            self.view.release()
            if hasattr(self._mm, "close"):
                self._mm.close()
        except (BufferError, ValueError):  # raydp-lint: disable=swallowed-exceptions (a partially sent view may still be exported; the mmap closes with the process)
            pass


def serve_block_view(shm_name: str, offset: int = 0, length: int = -1) -> RawView:
    """Zero-copy variant of ``serve_block_bytes``: mmap the block (either
    tier) and return a :class:`RawView` over the requested range instead of
    a copied ``bytes``. The streaming block server sends it with
    ``sendall(view)`` — kernel reads pages straight from the segment."""
    import mmap

    if shm_name.startswith("file://"):
        path = safe_spill_path(shm_name)
    else:
        path = os.path.join("/dev/shm", safe_shm_name(shm_name))
    with open(path, "rb") as f:
        total = os.fstat(f.fileno()).st_size
        if total == 0:
            # cannot mmap an empty file; an empty view needs no backing
            return RawView(memoryview(b""), memoryview(b""))
        mm = mmap.mmap(f.fileno(), 0, prot=mmap.PROT_READ)
    end = total if length < 0 else min(total, offset + length)
    start = min(offset, total)
    return RawView(mm, memoryview(mm)[start:end])


def unlink_block(shm_name: str) -> None:
    """Remove a block in either tier (shared by head and agents)."""
    from raydp_tpu import sanitize

    sanitize.untrack_block(shm_name)
    try:
        if shm_name.startswith("file://"):
            os.unlink(safe_spill_path(shm_name))
        else:
            os.unlink(os.path.join("/dev/shm", safe_shm_name(shm_name)))
    except (OSError, ClusterError):  # raydp-lint: disable=swallowed-exceptions (best-effort removal; block may already be gone)
        pass


class ZygoteProc:
    """Popen-shaped handle for a zygote-forked worker. The child's true
    parent (the zygote) reaps it and records the exit status in an
    ``<log_base>.exit`` marker; monitors here read the marker first, then
    fall back to a pid probe — a raw probe alone would report "alive"
    forever after pid reuse and could never recover the exit code."""

    def __init__(self, pid: int, log_base: str = ""):
        self.pid = pid
        self._log_base = log_base
        self._rc: Optional[int] = None

    def wait(self, timeout: Optional[float] = None) -> int:
        """Popen.wait parity over the poll shim (callers that treat head/
        agent processes uniformly — api.shutdown — need it). Raises
        subprocess.TimeoutExpired like the real thing."""
        import subprocess

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            rc = self.poll()
            if rc is not None:
                return rc
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("zygote-forked-process", timeout)
            time.sleep(0.01)

    def kill(self) -> None:
        """SIGKILL the forked child's process group (it setsid() at birth,
        so the group is exactly its own tree)."""
        import signal as _signal

        try:
            os.killpg(self.pid, _signal.SIGKILL)
        except OSError:
            try:
                os.kill(self.pid, _signal.SIGKILL)
            except OSError:  # raydp-lint: disable=swallowed-exceptions (kill of an already-dead process is idempotent)
                pass

    def poll(self) -> Optional[int]:
        if self._rc is not None:
            return self._rc
        if self._log_base:
            try:
                with open(self._log_base + ".exit") as f:
                    self._rc = int(f.read().strip() or 0)
                return self._rc
            except (OSError, ValueError):  # raydp-lint: disable=swallowed-exceptions (no exit marker yet: pid probe follows)
                pass  # no marker yet: the child may still be running
        # _probe_pid treats zombies as dead: the child may be dead but not
        # yet reaped by the zygote (its loop cadence stretches under CPU
        # contention — measured ~0.4s on a busy 1-core box); death detection
        # must not wait on the reaper. The exit marker, when it lands,
        # carries the real code for post-mortems.
        state = _probe_pid(self.pid)
        if state == "gone":
            self._rc = 0  # vanished before the marker landed; code unknown
            return self._rc
        if state == "dead":
            self._rc = 1
            return self._rc
        return None


# the zygote processes THIS process started, keyed by run_dir — kept so
# liveness checks can poll() (and thereby reap) a dead child: a bare pid
# probe sees the unreaped zombie as alive forever
_zygote_procs: Dict[str, Any] = {}


def _zygote_source_key() -> str:
    """Staleness key for the machine-global zygote: interpreter, the
    raydp_tpu source tree's (path, mtime, size) set, AND the versions of
    the warmed dependencies (an in-place `pip install -U pyarrow` must not
    leave a template serving the old in-memory copy). Any change keys new
    sessions into a fresh global dir; stale templates idle out."""
    import hashlib
    import sys

    import raydp_tpu

    pkg_root = os.path.dirname(os.path.abspath(raydp_tpu.__file__))
    h = hashlib.sha1()
    h.update(sys.executable.encode())
    h.update(pkg_root.encode())
    from importlib import metadata

    for dist in ("pyarrow", "pandas", "numpy", "cloudpickle"):
        try:  # dist-info read, no import (pandas costs 0.3s to import)
            h.update(f"{dist}={metadata.version(dist)};".encode())
        except Exception:
            h.update(f"{dist}=?;".encode())
    for dirpath, dirnames, filenames in sorted(os.walk(pkg_root)):
        dirnames.sort()
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            try:
                st = os.stat(path)
            except OSError:  # raydp-lint: disable=swallowed-exceptions (file vanished mid-walk: excluded from the key)
                continue
            h.update(
                f"{os.path.relpath(path, pkg_root)}:{st.st_mtime_ns}:{st.st_size};".encode()
            )
    return h.hexdigest()[:16]


def _probe_pid(pid: int) -> str:
    """'alive' | 'gone' (no such pid) | 'dead' (zombie, or pid owned by
    another uid — our child can't be). The one pid-probe implementation
    shared by ZygoteProc.poll and the zygote liveness checks."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return "gone"
    except PermissionError:  # pragma: no cover - pid reused by another uid
        return "dead"
    try:
        with open(f"/proc/{pid}/stat") as f:
            if f.read().rsplit(") ", 1)[1][:1] == "Z":
                return "dead"
    except (OSError, IndexError):  # raydp-lint: disable=swallowed-exceptions (proc entry vanished: next probe decides)
        pass
    return "alive"


def _pid_alive_not_zombie(pid: int) -> bool:
    return _probe_pid(pid) == "alive"


def _proc_starttime(pid: int) -> Optional[int]:
    """Kernel start time (clock ticks since boot, /proc stat field 22) —
    the (pid, starttime) pair uniquely identifies a process incarnation,
    immune to pid reuse AND to fork-without-exec cmdline inheritance."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return int(f.read().rsplit(") ", 1)[1].split()[19])
    except (OSError, IndexError, ValueError):
        return None


def _write_zygote_marker(marker: str, pid: int) -> None:
    """pid in the marker + its starttime in a sidecar (separate file: the
    marker's bare-int format is read by tests and older probes)."""
    with open(marker + ".tmp", "w") as f:
        f.write(str(pid))
    os.replace(marker + ".tmp", marker)
    st = _proc_starttime(pid)
    try:
        if st is not None:
            with open(marker + ".start.tmp", "w") as f:
                f.write(str(st))
            os.replace(marker + ".start.tmp", marker + ".start")
        else:
            os.unlink(marker + ".start")
    except OSError:  # raydp-lint: disable=swallowed-exceptions (starttime sidecar is best-effort)
        pass


def _marker_pid_alive(marker: str) -> Optional[int]:
    """The marker's pid if that exact process incarnation is still alive
    (starttime sidecar checked when present — a REUSED pid reads as dead,
    even one whose inherited cmdline still looks like a zygote)."""
    try:
        with open(marker) as f:
            pid = int(f.read().strip())
    except (OSError, ValueError):
        return None
    if not _pid_alive_not_zombie(pid):
        return None
    try:
        with open(marker + ".start") as f:
            recorded = int(f.read().strip())
        live = _proc_starttime(pid)
        if live is not None and live != recorded:
            return None  # same pid, different process: reuse
    except (OSError, ValueError):  # raydp-lint: disable=swallowed-exceptions (no sidecar (older writer): liveness is the best we have)
        pass  # no sidecar (older writer): plain liveness is the best we have
    return pid


def _adopt_global_zygote(run_dir: str, env: Dict[str, str]) -> bool:
    """Adopt (or start) the machine-global pre-warmed zygote and point this
    session's zygote.sock/zygote.pid at it. The global template is shared by
    every cluster of this user running the SAME source tree (fork requests
    carry the target session's run_dir and env, so the zygote itself is
    session-agnostic): after the first cluster on a machine pays the import
    warm-up once, later first-sessions fork in ~10ms instead of ~0.9s.
    Returns False on any problem — the caller falls back to a session-local
    zygote."""
    import fcntl
    import subprocess
    import sys

    from raydp_tpu.cluster.zygote import (
        GLOBAL_MODE_ENV,
        touch_adoption_stamp,
        zygote_marker_path,
        zygote_sock_path,
    )

    # per-uid root (like tempfile/X11 sockets): a shared machine's first
    # user must not own the path and silently lock everyone else out
    root = os.path.join(
        tempfile.gettempdir(), f"raydp_tpu-zygote-{os.getuid()}"
    )
    os.makedirs(root, mode=0o700, exist_ok=True)
    os.chmod(root, 0o700)
    if os.stat(root).st_uid != os.getuid():  # pragma: no cover - hostile /tmp
        return False
    gdir = os.path.join(root, _zygote_source_key())
    os.makedirs(gdir, mode=0o700, exist_ok=True)
    with open(os.path.join(gdir, ".lock"), "w") as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        gmarker = zygote_marker_path(gdir)
        pid = _marker_pid_alive(gmarker)
        if pid is None:
            genv = dict(env)
            genv[GLOBAL_MODE_ENV] = "1"
            if not genv.get("PYTHONPATH"):
                # the zygote runs python -S: without an explicit PYTHONPATH
                # it cannot resolve site-packages and dies at import
                genv["PYTHONPATH"] = os.pathsep.join(p for p in sys.path if p)
            log = os.path.join(gdir, "zygote.log")
            with open(log, "ab") as out:
                proc = subprocess.Popen(
                    [
                        sys.executable, "-S", "-m",
                        "raydp_tpu.cluster.zygote", gdir,
                    ],
                    stdout=out,
                    stderr=out,
                    env=genv,
                    start_new_session=True,
                )
            pid = proc.pid
            _write_zygote_marker(gmarker, pid)
        # session-side adoption UNDER THE LOCK (the zygote's idle-TTL exit
        # takes this lock too, so a just-adopted template can't vanish
        # between the liveness check and the marker write)
        sock = zygote_sock_path(run_dir)
        try:
            os.unlink(sock)
        except OSError:  # raydp-lint: disable=swallowed-exceptions (stale symlink may not exist)
            pass
        # symlink may dangle until the global zygote binds — the spawn
        # path's connect-retry loop covers the warm-up window
        os.symlink(zygote_sock_path(gdir), sock)
        _write_zygote_marker(zygote_marker_path(run_dir), pid)
        # idle-clock bump UNDER THE LOCK (ADVICE r5): retirement re-checks
        # this stamp after taking the same flock, so a template exactly at
        # its idle TTL can no longer retire right after we adopted it — the
        # old post-unlock socket poke left exactly that window, stranding
        # the session's marker/symlink on a dead template
        touch_adoption_stamp(gdir)
    # a dead session-local Popen recorded earlier must not shadow the
    # healthy adopted template in zygote_alive()
    _zygote_procs.pop(run_dir, None)
    return True


def start_zygote(run_dir: str, env: Optional[Dict[str, str]] = None) -> None:
    """Provide a pre-warmed fork template for this node (idempotent per
    marker file): adopt the machine-global zygote when possible (one import
    warm-up per machine per source tree), else start a session-local one.
    Called at head/agent boot — and eagerly by cluster.init — so any
    warm-up overlaps other startup work; spawns wait on the socket."""
    import subprocess
    import sys

    from raydp_tpu.cluster.zygote import zygote_marker_path

    env_dict = dict(env if env is not None else os.environ)
    if os.environ.get("RAYDP_TPU_NO_GLOBAL_ZYGOTE") != "1":
        try:
            if _adopt_global_zygote(run_dir, env_dict):
                return
        except Exception:  # raydp-lint: disable=swallowed-exceptions (session-local fallback follows)
            pass  # fall back to the session-local template

    marker = zygote_marker_path(run_dir)
    log = os.path.join(run_dir, "zygote.log")
    with open(log, "ab") as out:
        proc = subprocess.Popen(
            [sys.executable, "-S", "-m", "raydp_tpu.cluster.zygote", run_dir],
            stdout=out,
            stderr=out,
            env=env_dict,
            start_new_session=True,
        )
    _zygote_procs[run_dir] = proc
    _write_zygote_marker(marker, proc.pid)


def zygote_alive(run_dir: str) -> bool:
    """Is this node's zygote running? Polls (reaps) our own child; falls
    back to a pid probe for a zygote another process started (incl. an
    adopted machine-global one). A ZOMBIE counts as dead (an unreaped
    corpse would otherwise look alive forever), and a REUSED pid counts as
    dead (the probe verifies the cmdline is actually a zygote)."""
    proc = _zygote_procs.get(run_dir)
    if proc is not None:
        return proc.poll() is None
    from raydp_tpu.cluster.zygote import zygote_marker_path

    return _marker_pid_alive(zygote_marker_path(run_dir)) is not None


def _safe_getcwd(fallback: str) -> str:
    """getcwd that tolerates a DELETED working directory (raises
    FileNotFoundError otherwise) — spawns must degrade, not crash."""
    try:
        return os.getcwd()
    except OSError:
        return fallback


def _zygote_request(run_dir: str, req: Dict[str, Any], wait_s: float = 15.0):
    """Send one fork request to the node's zygote; the child pid, or None =
    unavailable (no marker, dead zygote, protocol failure) — callers fall
    back to a cold subprocess start. ``wait_s`` bounds how long to wait for
    a zygote still warming its imports."""
    from raydp_tpu.cluster.zygote import zygote_marker_path, zygote_sock_path

    marker = zygote_marker_path(run_dir)
    if not os.path.exists(marker) or not zygote_alive(run_dir):
        return None
    sock_path = zygote_sock_path(run_dir)
    # the zygote may still be warming its imports; wait for the socket (its
    # warm-up started at node boot, so this is usually instant)
    deadline = time.monotonic() + wait_s
    while True:
        try:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(10.0)
            sock.connect(sock_path)
            break
        except OSError:
            sock.close()
            if time.monotonic() > deadline:
                return None
            if not zygote_alive(run_dir):
                return None  # died while warming
            time.sleep(0.02)
    try:
        send_frame(sock, req)
        status, pid = recv_frame(sock)
    except OSError:
        return None
    finally:
        sock.close()
    if status != "ok":
        return None
    return pid


def _zygote_spawn(spec, incarnation: int, run_dir: str, env: Dict[str, str], log_base: str):
    """Request an actor-worker fork from the node's zygote; None = fall back
    to a cold subprocess start."""
    pid = _zygote_request(
        run_dir,
        {
            "run_dir": run_dir,
            "actor_id": spec.actor_id,
            "incarnation": incarnation,
            "env": env,
            "log_base": log_base,
            # what a cold subprocess start would inherit — the global
            # zygote's own cwd belongs to whichever driver started it
            "cwd": _safe_getcwd(run_dir),
        },
    )
    if pid is None:
        return None
    return ZygoteProc(pid, log_base)


def zygote_fork_main(
    run_dir: str,
    module: str,
    argv: List[str],
    env: Dict[str, str],
    log_base: str,
    wait_s: float = 2.0,
):
    """Fork a MODULE MAIN (head / agent entry point) from the pre-warmed
    zygote: the warm-boot path that takes ``cluster_boot_s`` under 100ms on
    a machine whose global template is already up — the head becomes a
    ~10ms fork with its import set inherited copy-on-write, instead of a
    cold ``python -S`` start. Returns a ZygoteProc, or None when no READY
    template exists (absent or still warming — boot must fall back to the
    cold start immediately rather than wait out the warm-up)."""
    from raydp_tpu.cluster.zygote import zygote_sock_path

    if not os.path.exists(zygote_sock_path(run_dir)):
        # exists() follows the adoption symlink: a dangling link means the
        # global template is still importing — cold start wins that race
        return None
    pid = _zygote_request(
        run_dir,
        {
            "kind": "main",
            "module": module,
            "argv": list(argv),
            "run_dir": run_dir,
            "env": dict(env),
            "log_base": log_base,
            "cwd": _safe_getcwd(run_dir),
        },
        wait_s=wait_s,
    )
    if pid is None:
        return None
    return ZygoteProc(pid, log_base)


def launch_worker(spec, incarnation: int, run_dir: str, env: Dict[str, str]):
    """Fork one actor worker process — the single spawn recipe used by both
    the head (local nodes) and node agents (remote nodes): log redirection,
    optional ``-S`` light start, detached session. Light actors fork from
    the node's pre-warmed zygote when one is up (~10-20ms instead of ~450ms
    of imports); everything else — and any zygote failure — takes the cold
    subprocess path."""
    import subprocess
    import sys

    log_base = os.path.join(run_dir, f"a-{spec.actor_id}-{incarnation}")
    try:  # a stale marker from a same-(id, incarnation) relaunch would make
        os.unlink(log_base + ".exit")  # the new child look dead at birth
    except OSError:  # raydp-lint: disable=swallowed-exceptions (stale exit marker may not exist)
        pass
    if getattr(spec, "light", True):
        proc = _zygote_spawn(spec, incarnation, run_dir, env, log_base)
        if proc is not None:
            return proc
    with open(log_base + ".out", "ab") as out, open(log_base + ".err", "ab") as err:
        return subprocess.Popen(
            [sys.executable]
            + (["-S"] if getattr(spec, "light", True) else [])
            + [
                "-m",
                "raydp_tpu.cluster.worker",
                run_dir,
                spec.actor_id,
                str(incarnation),
            ],
            stdout=out,
            stderr=err,
            env=env,
            start_new_session=True,
        )
