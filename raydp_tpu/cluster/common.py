"""Wire protocol + shared records for the cluster runtime.

The runtime replaces the reference's substrate (Ray actors + GCS; SURVEY.md L1)
with a small native stack: one *head* process holding cluster state (actors,
virtual nodes, placement groups, object metadata) and one OS process per actor,
all talking length-prefixed cloudpickle frames over Unix-domain sockets. On a
TPU pod this head runs on the coordinator host and the socket layer swaps to
TCP; the control plane is deliberately tiny because the data plane (gradient
and activation traffic) is XLA collectives compiled into step functions, never
these sockets.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import socket
import struct
import time
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

_LEN = struct.Struct(">I")
MAX_FRAME = 1 << 30

HEAD_SOCK_NAME = "head.sock"
HEAD_TCP_FILE = "head_tcp.addr"
TOKEN_FILE = "cluster.token"
SESSION_ENV = "RAYDP_TPU_SESSION"
HEAD_ADDR_ENV = "RAYDP_TPU_HEAD_ADDR"
SHM_NS_ENV = "RAYDP_TPU_SHM_NS"
TOKEN_ENV = "RAYDP_TPU_TOKEN"
DRIVER_OWNER = "__driver__"
TOKEN_LEN = 32


class ClusterError(RuntimeError):
    pass


class ActorDiedError(ClusterError):
    """The callee actor is dead (crashed past max_restarts or intentionally exited)."""


class OwnerDiedError(ClusterError):
    """An object's owner died and the object was not transferred (parity:
    ray.exceptions.OwnerDiedError asserted in reference
    test_data_owner_transfer.py:33-77)."""


class ActorState(str, enum.Enum):
    PENDING = "PENDING"
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"


def send_frame(sock: socket.socket, obj: Any) -> None:
    payload = cloudpickle.dumps(obj)
    if len(payload) > MAX_FRAME:
        raise ClusterError(f"frame too large: {len(payload)} bytes")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return cloudpickle.loads(_recv_exact(sock, length))


def session_token() -> bytes:
    """The cluster's shared secret. TCP peers must present it before any
    frame is parsed — without it, a reachable port would mean arbitrary
    unpickling (RCE) for anyone on the network. Resolution: env (remote
    processes) → the session dir's token file (head-local processes)."""
    env_token = os.environ.get(TOKEN_ENV)
    if env_token:
        return bytes.fromhex(env_token)
    session = os.environ.get(SESSION_ENV)
    if session:
        path = os.path.join(session, TOKEN_FILE)
        if os.path.exists(path):
            with open(path, "rb") as f:
                return f.read()
    return b"\0" * TOKEN_LEN  # no session context: deliberately non-matching


def load_token(session_dir: str) -> bytes:
    with open(os.path.join(session_dir, TOKEN_FILE), "rb") as f:
        return f.read()


def verify_token(sock: socket.socket, expected: bytes) -> bool:
    """Server side of the TCP handshake: challenge-response, verified before
    any frame touches cloudpickle (a reachable port must not mean arbitrary
    unpickling). The server sends a fresh nonce and the client proves
    possession with HMAC-SHA256(token, nonce) — the secret itself never
    crosses the wire, so a passive observer cannot capture-and-replay it.
    (An attacker who can fully MITM an established connection can still relay
    frames; untrusted networks need TLS on top.)"""
    import hashlib
    import hmac

    try:
        nonce = os.urandom(TOKEN_LEN)
        sock.sendall(nonce)
        presented = _recv_exact(sock, hashlib.sha256().digest_size)
    except (ConnectionError, OSError):
        return False
    digest = hmac.new(expected, nonce, hashlib.sha256).digest()
    return hmac.compare_digest(presented, digest)


def connect(addr: str, timeout: Optional[float] = None) -> socket.socket:
    """Connect to either transport: ``tcp://host:port`` or a Unix socket
    path. The TCP side is what makes the substrate multi-host — agents and
    their actors on other machines are addressed exactly like local ones.
    TCP connections start with the session-token handshake; Unix sockets are
    guarded by the session dir's filesystem permissions instead."""
    if addr.startswith("tcp://"):
        host, _, port = addr[6:].rpartition(":")
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect((host, int(port)))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # client side of the challenge-response handshake (see verify_token)
        import hashlib
        import hmac

        nonce = _recv_exact(sock, TOKEN_LEN)
        sock.sendall(hmac.new(session_token(), nonce, hashlib.sha256).digest())
        return sock
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout)
    sock.connect(addr)
    return sock


def safe_shm_name(shm_name: str) -> str:
    """Reject anything but a flat segment name (a client-supplied name is
    joined under /dev/shm — path traversal must be impossible)."""
    name = shm_name.lstrip("/")
    if not name or "/" in name or ".." in name or not name.startswith("rtpu-"):
        raise ClusterError(f"invalid shm segment name {shm_name!r}")
    return name


def resolve_head_addr(session_dir: str) -> str:
    """The head's address for THIS process: remote processes (spawned via a
    node agent) carry it in the environment; head-local ones use the Unix
    socket in the session dir. A dir WITHOUT a head socket is a tcp://
    client's local dir — its ``head_tcp.addr`` file (written at attach)
    carries the address, so handles pickled BY the client resolve anywhere
    in the cluster (an actor holding such a handle has neither the client's
    env nor its head socket)."""
    env_addr = os.environ.get(HEAD_ADDR_ENV)
    if env_addr:
        return env_addr
    sock = head_sock_path(session_dir)
    if not os.path.exists(sock):
        tcp_file = os.path.join(session_dir, HEAD_TCP_FILE)
        try:
            with open(tcp_file) as f:
                addr = f.read().strip()
            if addr:
                return addr
        except OSError:
            pass
    return sock


def shm_namespace() -> str:
    """This process's shared-memory namespace (one per node). Objects are
    only mapped directly when their namespace matches; everything else goes
    through the owning node's block server."""
    return os.environ.get(SHM_NS_ENV, "")


def rpc(sock_path: str, request: Tuple, timeout: Optional[float] = 60.0) -> Any:
    """One-shot request/response. Raises the remote exception if status != ok."""
    with connect(sock_path, timeout) as sock:
        send_frame(sock, request)
        status, value = recv_frame(sock)
    if status == "ok":
        return value
    raise value


def wait_for_path(path: str, timeout: float, what: str) -> None:
    deadline = time.monotonic() + timeout
    while not os.path.exists(path):
        if time.monotonic() > deadline:
            raise ClusterError(f"timed out waiting for {what} at {path}")
        time.sleep(0.02)


@dataclasses.dataclass
class ActorSpec:
    """Everything needed to (re)start an actor process; persisted to the session
    dir so the head can respawn a crashed actor with the same identity
    (restart-aware identity, parity: RayDPExecutor restart dance,
    reference RayDPExecutor.scala:84-96 / RayExecutorUtils.java:63-65)."""

    actor_id: str
    name: Optional[str]
    cls_blob: bytes  # cloudpickled class
    args_blob: bytes  # cloudpickled (args, kwargs)
    resources: Dict[str, float]
    max_restarts: int = 0
    max_concurrency: int = 1
    placement_group: Optional[str] = None
    bundle_index: int = -1
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    # light=True starts the actor with `python -S`: site/sitecustomize are
    # skipped (this image's sitecustomize imports jax + the TPU plugin,
    # ~2.6s per process) and imports resolve via the PYTHONPATH the spawner
    # provides. ETL/storage actors never touch jax; SPMD ranks that need the
    # TPU plugin registered must set light=False.
    light: bool = True


@dataclasses.dataclass
class ActorRecord:
    """Head-side view of one actor, as reported to clients."""

    actor_id: str
    name: Optional[str]
    state: ActorState
    incarnation: int
    sock_path: Optional[str]
    node_id: Optional[str]
    node_ip: Optional[str]
    restarts_used: int = 0
    error: Optional[str] = None
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class NodeRecord:
    node_id: str
    node_ip: str
    resources: Dict[str, float]
    alive: bool = True
    # agent-backed nodes (real multi-host): the head spawns/kills actors and
    # fetches blocks through the agent's TCP address; shm_ns is the node's
    # shared-memory namespace (objects from other namespaces must be pulled
    # over the network, never mapped)
    agent_addr: Optional[str] = None
    shm_ns: str = ""


def actor_sock_path(session_dir: str, actor_id: str, incarnation: int) -> str:
    return os.path.join(session_dir, f"a-{actor_id}-{incarnation}.sock")


def head_sock_path(session_dir: str) -> str:
    return os.path.join(session_dir, HEAD_SOCK_NAME)


def safe_spill_path(name: str) -> str:
    """Validate a ``file://`` block location before serving/unlinking it: the
    resolved path must be a framework spill file (rtpu- prefixed) DIRECTLY
    inside this process's own spill root (``$RAYDP_TPU_SESSION/spill`` —
    head_main/agent anchor it at boot) — a client-supplied path must not be
    able to read or remove arbitrary files, nor another session's spill."""
    path = os.path.realpath(name[len("file://"):])
    base = os.path.basename(path)
    if not base.startswith("rtpu-"):
        raise ClusterError(f"invalid spill block path {name!r}")
    session = os.environ.get(SESSION_ENV)
    if not session:
        raise ClusterError(
            f"cannot serve spill path {name!r}: no session root anchored"
        )
    root = os.path.realpath(os.path.join(session, "spill"))
    if os.path.dirname(path) != root:
        raise ClusterError(f"spill path {name!r} outside this node's spill dir")
    return path


def serve_block_bytes(shm_name: str, offset: int = 0, length: int = -1) -> bytes:
    """Read a local block for a remote reader (the block-server primitive
    shared by the head and node agents — one copy of the sanitize/seek/length
    logic). Serves both tiers: /dev/shm segments and ``file://`` spill files."""
    if shm_name.startswith("file://"):
        path = safe_spill_path(shm_name)
    else:
        path = os.path.join("/dev/shm", safe_shm_name(shm_name))
    with open(path, "rb") as f:
        f.seek(offset)
        return f.read() if length < 0 else f.read(length)


def unlink_block(shm_name: str) -> None:
    """Remove a block in either tier (shared by head and agents)."""
    try:
        if shm_name.startswith("file://"):
            os.unlink(safe_spill_path(shm_name))
        else:
            os.unlink(os.path.join("/dev/shm", safe_shm_name(shm_name)))
    except (OSError, ClusterError):
        pass


class ZygoteProc:
    """Popen-shaped handle for a zygote-forked worker. The child's true
    parent (the zygote) reaps it and records the exit status in an
    ``<log_base>.exit`` marker; monitors here read the marker first, then
    fall back to a pid probe — a raw probe alone would report "alive"
    forever after pid reuse and could never recover the exit code."""

    def __init__(self, pid: int, log_base: str = ""):
        self.pid = pid
        self._log_base = log_base
        self._rc: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self._rc is not None:
            return self._rc
        if self._log_base:
            try:
                with open(self._log_base + ".exit") as f:
                    self._rc = int(f.read().strip() or 0)
                return self._rc
            except (OSError, ValueError):
                pass  # no marker yet: the child may still be running
        try:
            os.kill(self.pid, 0)
        except ProcessLookupError:
            self._rc = 0  # gone before the marker landed; code unknown
            return self._rc
        except PermissionError:  # pragma: no cover - pid reused by other uid
            # the pid now belongs to someone else's process, so OUR child
            # has exited (the marker write may still be in flight)
            self._rc = 1
            return self._rc
        # kill(pid, 0) succeeds on ZOMBIES too: the child is dead but the
        # zygote hasn't reaped it yet (its loop cadence stretches under CPU
        # contention — measured ~0.4s on a busy 1-core box). Read the state
        # from /proc so death detection never waits on the reaper; the exit
        # marker, when it lands, carries the real code for post-mortems.
        try:
            with open(f"/proc/{self.pid}/stat") as f:
                if f.read().rsplit(") ", 1)[1][:1] == "Z":
                    self._rc = 1
                    return self._rc
        except (OSError, IndexError):
            pass  # no /proc (non-Linux): fall back to marker/pid semantics
        return None


# the zygote processes THIS process started, keyed by run_dir — kept so
# liveness checks can poll() (and thereby reap) a dead child: a bare pid
# probe sees the unreaped zombie as alive forever
_zygote_procs: Dict[str, Any] = {}


def start_zygote(run_dir: str, env: Optional[Dict[str, str]] = None) -> None:
    """Start the pre-warmed fork template for this node (idempotent per
    marker file). Called at head/agent boot — and eagerly by cluster.init —
    so the warm-up overlaps other startup work; spawns wait on the socket,
    not the warm-up."""
    import subprocess
    import sys

    from raydp_tpu.cluster.zygote import zygote_marker_path

    marker = zygote_marker_path(run_dir)
    log = os.path.join(run_dir, "zygote.log")
    with open(log, "ab") as out:
        proc = subprocess.Popen(
            [sys.executable, "-S", "-m", "raydp_tpu.cluster.zygote", run_dir],
            stdout=out,
            stderr=out,
            env=dict(env if env is not None else os.environ),
            start_new_session=True,
        )
    _zygote_procs[run_dir] = proc
    with open(marker + ".tmp", "w") as f:
        f.write(str(proc.pid))
    os.replace(marker + ".tmp", marker)


def zygote_alive(run_dir: str) -> bool:
    """Is this node's zygote running? Polls (reaps) our own child; falls
    back to a pid probe for a zygote another process started. A ZOMBIE
    counts as dead: the eager cluster.init zygote is the DRIVER's child, so
    after it dies the head's pid probe would otherwise see the unreaped
    zombie as alive forever and never restart it."""
    proc = _zygote_procs.get(run_dir)
    if proc is not None:
        return proc.poll() is None
    from raydp_tpu.cluster.zygote import zygote_marker_path

    try:
        with open(zygote_marker_path(run_dir)) as f:
            pid = int(f.read().strip())
        os.kill(pid, 0)
    except (OSError, ValueError):
        return False
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().rsplit(") ", 1)[1][:1] != "Z"
    except (OSError, IndexError):
        return True  # no /proc: keep the plain pid-probe answer


def _zygote_spawn(spec, incarnation: int, run_dir: str, env: Dict[str, str], log_base: str):
    """Request a fork from the node's zygote; None = unavailable (no marker,
    dead zygote, or protocol failure) — the caller falls back to a cold
    subprocess start."""
    from raydp_tpu.cluster.zygote import zygote_marker_path, zygote_sock_path

    marker = zygote_marker_path(run_dir)
    if not os.path.exists(marker) or not zygote_alive(run_dir):
        return None
    sock_path = zygote_sock_path(run_dir)
    # the zygote may still be warming its imports; wait for the socket (its
    # warm-up started at node boot, so this is usually instant)
    deadline = time.monotonic() + 15.0
    while True:
        try:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(10.0)
            sock.connect(sock_path)
            break
        except OSError:
            sock.close()
            if time.monotonic() > deadline:
                return None
            if not zygote_alive(run_dir):
                return None  # died while warming
            time.sleep(0.02)
    try:
        send_frame(
            sock,
            {
                "run_dir": run_dir,
                "actor_id": spec.actor_id,
                "incarnation": incarnation,
                "env": env,
                "log_base": log_base,
            },
        )
        status, pid = recv_frame(sock)
    except (ConnectionError, OSError):
        return None
    finally:
        sock.close()
    if status != "ok":
        return None
    return ZygoteProc(pid, log_base)


def launch_worker(spec, incarnation: int, run_dir: str, env: Dict[str, str]):
    """Fork one actor worker process — the single spawn recipe used by both
    the head (local nodes) and node agents (remote nodes): log redirection,
    optional ``-S`` light start, detached session. Light actors fork from
    the node's pre-warmed zygote when one is up (~10-20ms instead of ~450ms
    of imports); everything else — and any zygote failure — takes the cold
    subprocess path."""
    import subprocess
    import sys

    log_base = os.path.join(run_dir, f"a-{spec.actor_id}-{incarnation}")
    try:  # a stale marker from a same-(id, incarnation) relaunch would make
        os.unlink(log_base + ".exit")  # the new child look dead at birth
    except OSError:
        pass
    if getattr(spec, "light", True):
        proc = _zygote_spawn(spec, incarnation, run_dir, env, log_base)
        if proc is not None:
            return proc
    with open(log_base + ".out", "ab") as out, open(log_base + ".err", "ab") as err:
        return subprocess.Popen(
            [sys.executable]
            + (["-S"] if getattr(spec, "light", True) else [])
            + [
                "-m",
                "raydp_tpu.cluster.worker",
                run_dir,
                spec.actor_id,
                str(incarnation),
            ],
            stdout=out,
            stderr=err,
            env=env,
            start_new_session=True,
        )
