"""raydp-tpu: a TPU-native single-cluster ETL -> training framework.

Capabilities modeled on RayDP (reference: hezhaozhao-git/raydp): one Python program
does ETL on a distributed Arrow DataFrame engine and trains JAX models on the same
cluster with in-memory Arrow data exchange and ownership-transfer semantics — but
re-architected for TPU: gradient/activation communication is XLA collectives
(`jax.lax.psum` & friends) compiled into the step function over an ICI/DCN device
mesh, never a runtime service (NCCL/Gloo/Horovod/MPI) as in the reference.

Public surface parity (reference python/raydp/__init__.py:18-22):
  raydp.init_spark / stop_spark      -> raydp_tpu.init_etl / stop_etl
  raydp.spark.spark_dataframe_to_ray_dataset -> raydp_tpu.dataframe_to_dataset
  raydp.torch.TorchEstimator         -> raydp_tpu.estimator.JaxEstimator (flagship)
                                        raydp_tpu.estimator.TorchEstimator (parity)
  raydp.mpi.create_mpi_job           -> raydp_tpu.spmd.create_spmd_job
"""

__version__ = "0.1.0"

_LAZY = {
    "init_etl": ("raydp_tpu.etl.session", "init_etl"),
    "stop_etl": ("raydp_tpu.etl.session", "stop_etl"),
    # Familiar aliases for users migrating from the reference API.
    "init_spark": ("raydp_tpu.etl.session", "init_etl"),
    "stop_spark": ("raydp_tpu.etl.session", "stop_etl"),
    "dataframe_to_dataset": ("raydp_tpu.exchange.dataset", "dataframe_to_dataset"),
    "dataset_to_dataframe": ("raydp_tpu.exchange.dataset", "dataset_to_dataframe"),
    "from_etl_recoverable": ("raydp_tpu.exchange.dataset", "from_etl_recoverable"),
    "Dataset": ("raydp_tpu.exchange.dataset", "Dataset"),
    "create_spmd_job": ("raydp_tpu.spmd.job", "create_spmd_job"),
    "elastic_fit": ("raydp_tpu.spmd.elastic", "elastic_fit"),
    "MLDataset": ("raydp_tpu.exchange.ml_dataset", "MLDataset"),
    "JaxEstimator": ("raydp_tpu.estimator.jax_estimator", "JaxEstimator"),
    # client mode: attach a second driver to a running cluster (the
    # reference's ray://host:port analog)
    "connect_cluster": ("raydp_tpu.cluster.api", "connect_cluster"),
    # observability plane (raydp_tpu.obs): Perfetto trace export + merged
    # cluster metrics + windowed time-series + critical-path attribution
    "export_trace": ("raydp_tpu.obs", "export_trace"),
    "dump_metrics": ("raydp_tpu.cluster.api", "dump_metrics"),
    "query_metrics": ("raydp_tpu.cluster.api", "query_metrics"),
    "explain_last_query": ("raydp_tpu.obs", "explain_last_query"),
    # online serving plane (docs/serving.md): attribute access resolves the
    # subpackage so `raydp_tpu.serve.deploy(...)` works without an explicit
    # `import raydp_tpu.serve`
    "serve": ("raydp_tpu.serve", None),
    # multi-tenant control plane (docs/multitenancy.md): session registry,
    # fair-share scheduler, per-tenant quotas/accounting
    "tenancy": ("raydp_tpu.tenancy", None),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        loaded = importlib.import_module(module)
        value = loaded if attr is None else getattr(loaded, attr)
        globals()[name] = value
        return value
    raise AttributeError(f"module 'raydp_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
