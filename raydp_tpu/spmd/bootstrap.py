"""Multi-host mesh bootstrap.

The L1' mesh runtime of SURVEY.md §7: on a TPU pod each host runs one process;
``initialize_from_env`` wires ``jax.distributed`` from the env the SPMD job
launcher (job.py) or an external scheduler provides, after which
``jax.devices()`` spans the pod and ``parallel.make_mesh`` lays ICI/DCN axes.

The reference's analog is the MPI rank discovering itself from OMPI/PMI env
vars and joining Ray (mpi_worker.py:33-42,158-166).
"""

from __future__ import annotations

import os
from typing import Optional

COORD_ENV = "RAYDP_TPU_COORDINATOR"
RANK_ENV = "RAYDP_TPU_SPMD_RANK"
WORLD_ENV = "RAYDP_TPU_SPMD_WORLD_SIZE"


def initialize_from_env(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Idempotent jax.distributed bootstrap from args or env (no-op when
    single-process)."""
    import jax

    coordinator = coordinator_address or os.environ.get(COORD_ENV)
    world = num_processes if num_processes is not None else int(
        os.environ.get(WORLD_ENV, "1")
    )
    rank = process_id if process_id is not None else int(
        os.environ.get(RANK_ENV, "0")
    )
    if world <= 1 or coordinator is None:
        return
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=world,
        process_id=rank,
    )


def process_rank() -> int:
    return int(os.environ.get(RANK_ENV, "0"))


def world_size() -> int:
    return int(os.environ.get(WORLD_ENV, "1"))
