"""SPMD job launcher — generic multi-process SPMD on the cluster runtime.

Re-architecture of the reference's MPI-on-Ray (SURVEY.md P14-P16, §3.5):
where the reference reserves hosts with a STRICT_SPREAD placement group,
launches real ``mpirun``, and wires a gRPC control plane for function
shipping (mpi_job.py:165-278), here the ranks ARE actors on the cluster
runtime — the control plane is the actor RPC itself, and the *data plane for
gradients doesn't exist at this layer at all*: ranks bootstrap
``jax.distributed`` and collectives compile into their jitted step functions
over ICI/DCN. Kept semantics: one rank per placement bundle (spread), strict
function-id ordering per rank (mpi_worker.py TaskRunner :75-96), fan-out
run + gather results in rank order (mpi_job.py:325-339), restartable
start/stop/reset (:345-396).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

from raydp_tpu.cluster import api as cluster


class WorkerContext:
    """Passed to every shipped function (parity: mpi WorkerContext)."""

    def __init__(self, job_name: str, rank: int, world_size: int):
        self.job_name = job_name
        self.rank = rank
        self.world_size = world_size

    def __repr__(self):
        return f"WorkerContext({self.job_name}, rank={self.rank}/{self.world_size})"


class SpmdWorker:
    """One rank: executes shipped functions in submission order. The job env
    (incl. rank/world vars) arrives via the actor's process environment —
    set at spawn so interpreter-startup consumers (JAX platform selection)
    see it; nothing is re-applied here."""

    def __init__(self, job_name: str, rank: int, world_size: int):
        from raydp_tpu.sanitize import named_lock

        self.ctx = WorkerContext(job_name, rank, world_size)
        self._next_func_id = 0
        self._lock = named_lock("spmd.worker")

    def ping(self) -> int:
        return self.ctx.rank

    def pick_free_port(self) -> int:
        """A free TCP port on THIS rank's host (the jax.distributed
        coordinator must bind where rank 0 actually runs)."""
        import socket

        with socket.socket() as s:
            s.bind(("0.0.0.0", 0))
            return s.getsockname()[1]

    def bootstrap_jax_distributed(
        self, coordinator_address: str, num_processes: int, process_id: int
    ) -> int:
        """Join the jax.distributed mesh (the reference's analog: each mpi
        rank joins Ray via ray.init(address), mpi_worker.py:158-166)."""
        import os

        import jax

        # honor a CPU request even if the image pre-imports jax with a TPU
        # plugin registered (config must be set before backend init)
        if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                from raydp_tpu.obs import log as obs_log

                obs_log.warning(
                    "could not force jax_platforms=cpu; the rank may "
                    "initialize against the image's default backend",
                    exc_info=True,
                )
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return len(jax.devices())

    def run_function(self, func_id: int, blob: bytes) -> Any:
        """Execute a shipped function. Strict ordering: func_id must be the
        next expected (parity: mpi_worker TaskRunner check, :85-90)."""
        with self._lock:
            if func_id != self._next_func_id:
                raise RuntimeError(
                    f"out-of-order function: got {func_id}, expected {self._next_func_id}"
                )
            self._next_func_id += 1
        fn = cloudpickle.loads(blob)
        return fn(self.ctx)


class SpmdJob:
    def __init__(
        self,
        job_name: str,
        world_size: int,
        num_cpus_per_worker: float = 1.0,
        placement_group: Optional[cluster.PlacementGroup] = None,
        placement_group_bundle_indexes: Optional[List[int]] = None,
        placement_strategy: str = "SPREAD",
        env: Optional[Dict[str, str]] = None,
        timeout: float = 120.0,
    ):
        self.job_name = job_name
        self.world_size = world_size
        self.num_cpus_per_worker = num_cpus_per_worker
        self.placement_strategy = placement_strategy
        self.env = dict(env or {})
        self.timeout = timeout
        self._pg = placement_group
        self._bundle_indexes = placement_group_bundle_indexes
        self._owns_pg = False
        self._workers: List[cluster.ActorHandle] = []
        self._func_id = 0
        self._started = False
        from raydp_tpu.sanitize import named_lock

        self._lock = named_lock("spmd.job", threading.RLock())

    # ------------------------------------------------------------------

    def start(self) -> "SpmdJob":
        """Reserve one bundle per rank (spread across nodes like the mpi
        launcher's STRICT_SPREAD peers, mpi_job.py:192-222) and spawn ranks."""
        with self._lock:
            if self._started:
                raise RuntimeError(f"job {self.job_name} already started")
            if not cluster.is_initialized():
                cluster.init()
            if self._pg is None:
                bundles = [
                    {"CPU": float(self.num_cpus_per_worker)}
                    for _ in range(self.world_size)
                ]
                try:
                    self._pg = cluster.create_placement_group(
                        bundles, strategy=self.placement_strategy
                    )
                except Exception:
                    # resources are logical: grow the cluster with an extra
                    # node rather than failing (an ETL session may be holding
                    # the original CPUs — the reference runs Ray Train worker
                    # groups beside Spark executors the same way)
                    cluster.add_node(
                        {
                            "CPU": float(self.num_cpus_per_worker)
                            * self.world_size,
                            "memory": float(1 << 30),
                        }
                    )
                    self._pg = cluster.create_placement_group(
                        bundles, strategy=self.placement_strategy
                    )
                self._owns_pg = True
            indexes = self._bundle_indexes or list(range(self.world_size))
            self._workers = []
            try:
                for rank in range(self.world_size):
                    handle = cluster.spawn(
                        SpmdWorker,
                        self.job_name,
                        rank,
                        self.world_size,
                        name=f"{self.job_name}-rank-{rank}",
                        num_cpus=self.num_cpus_per_worker,
                        placement_group=self._pg.id,
                        bundle_index=indexes[rank % len(indexes)],
                        max_restarts=0,
                        max_concurrency=2,
                        # env must be in place at process start: platform
                        # selection (JAX_PLATFORMS/XLA_FLAGS) is read during
                        # interpreter startup, before __init__ runs
                        env={
                            **self.env,
                            "RAYDP_TPU_SPMD_RANK": str(rank),
                            "RAYDP_TPU_SPMD_WORLD_SIZE": str(self.world_size),
                        },
                        block=False,
                    )
                    self._workers.append(handle)
                for handle in self._workers:
                    handle.wait_ready(timeout=self.timeout)
            except BaseException:
                # don't leak actors/PG when a rank fails to come up: the
                # caller never gets a handle to stop()
                self._started = True  # let stop() run its full path
                self.stop()
                raise
            self._started = True
            return self

    def _worker_host(self, rank: int) -> str:
        """The given rank's node address from its actor record — never the
        driver's loopback: ranks placed on other machines must reach it."""
        try:
            record = self._workers[rank]._record()
            return record.node_ip if record and record.node_ip else "127.0.0.1"
        except Exception:
            return "127.0.0.1"

    def _worker_host_port(self, rank: int, port: int = 0) -> str:
        """``host:port`` on the given rank's node; the port is picked ON the
        rank's host (the driver cannot probe another machine's port space)."""
        if port == 0:
            port = self._workers[rank].pick_free_port.options(
                timeout=self.timeout
            ).remote().result()
        return f"{self._worker_host(rank)}:{port}"

    def rendezvous_address(self, port: int = 0) -> str:
        """``host:port`` on RANK 0's node, for any single-coordinator
        worker-group rendezvous (jax.distributed coordinator, torch gloo
        store, ...). Ray Train plays this role for the reference's
        estimators (torch/estimator.py:311-327)."""
        return self._worker_host_port(0, port)

    def worker_addresses(self) -> List[str]:
        """One reachable ``host:port`` per rank (each port picked on that
        rank's own host) — the cluster spec an all-workers rendezvous like
        TF's ``TF_CONFIG`` needs. Port picks fan out concurrently: serial
        round trips would cost 2·world_size control-plane RTTs per fit."""
        futures = [
            w.pick_free_port.options(timeout=self.timeout).remote()
            for w in self._workers
        ]
        return [
            f"{self._worker_host(rank)}:{f.result()}"
            for rank, f in enumerate(futures)
        ]

    def bootstrap_jax(self, coordinator_port: int = 0) -> List[int]:
        """Bring up jax.distributed across all ranks; returns per-rank global
        device counts. The coordinator binds on RANK 0's node — its address
        is resolved from rank 0's actor record, not the driver's loopback,
        so multi-host jobs rendezvous correctly (round-1 ADVICE: the old
        127.0.0.1 address silently broke off the driver's host)."""
        address = self.rendezvous_address(coordinator_port)
        futures = [
            w.bootstrap_jax_distributed.options(timeout=self.timeout).remote(
                address, self.world_size, rank
            )
            for rank, w in enumerate(self._workers)
        ]
        return [f.result() for f in futures]

    def run(self, fn: Callable[[WorkerContext], Any], timeout: Optional[float] = None) -> List[Any]:
        """Ship ``fn`` to every rank concurrently; gather in rank order
        (parity: mpi_job.run, :325-339).

        The gather FAILS FAST: a dead rank surfaces immediately instead of
        waiting out rank 0 first — with collectives in flight, surviving
        ranks hang on the dead one, so rank-order result() would stall the
        whole deadline before reporting the failure. The elastic watchdog
        depends on this to restart gangs promptly."""
        import time

        with self._lock:
            if not self._started:
                raise RuntimeError("job not started")
            func_id = self._func_id
            self._func_id += 1
        blob = cloudpickle.dumps(fn)
        wait = self.timeout if timeout is None else timeout
        futures = [
            w.run_function.options(timeout=wait).remote(func_id, blob)
            for w in self._workers
        ]
        import selectors

        results: List[Any] = [None] * len(futures)
        done = [False] * len(futures)
        for i, future in enumerate(futures):
            if getattr(future, "_sock", None) is None:  # already-completed
                results[i] = future.result()
                done[i] = True
        deadline = time.monotonic() + wait
        # Readable sockets are drained on worker THREADS: result() reads a
        # whole frame under the actor timeout, so one rank streaming a large
        # or partial frame must not stall detection of other ranks' failures
        # (the sweep's constant-latency guarantee — the elastic watchdog
        # depends on it).
        import queue

        drain_q: "queue.Queue" = queue.Queue()
        draining: set = set()

        def _drain(idx, fut):
            try:
                drain_q.put((idx, fut.result(), None))
            except BaseException as e:  # noqa: BLE001 — relayed to the sweep
                drain_q.put((idx, None, e))

        while not all(done):
            # ONE poll over every pending rank's socket: sweep latency is
            # constant, not world_size × probe (a dead rank must surface
            # immediately — the elastic watchdog depends on it). selectors
            # (epoll) rather than select(): a long-lived driver can hold
            # fds >= FD_SETSIZE, which select() rejects outright.
            pending = [
                (i, f) for i, f in enumerate(futures)
                if not done[i] and i not in draining
                and getattr(f, "_sock", None) is not None
            ]
            if pending:
                with selectors.DefaultSelector() as sel:
                    for i, f in pending:
                        sel.register(f._sock, selectors.EVENT_READ, i)
                    ready = {key.data for key, _ in sel.select(timeout=0.2)}
                for i, future in pending:
                    if i not in ready:
                        continue
                    draining.add(i)
                    threading.Thread(
                        target=_drain, args=(i, future), daemon=True
                    ).start()
            # harvest finished drains (block briefly only when every pending
            # rank is already mid-drain, so the loop still ticks the deadline)
            block = not pending
            while True:
                try:
                    i, value, err = drain_q.get(
                        timeout=0.2 if block else 0.0
                    )
                except queue.Empty:  # raydp-lint: disable=swallowed-exceptions (queue drain)
                    break
                block = False
                draining.discard(i)
                if err is not None:
                    # rank failure (remote raise / ConnectionError /
                    # ActorDiedError): fail fast
                    raise err
                results[i] = value
                done[i] = True
            if not all(done) and time.monotonic() > deadline:
                raise TimeoutError(
                    f"spmd job {self.job_name}: "
                    f"{done.count(False)} rank(s) did not finish within {wait}s"
                )
        return results

    def stop(self) -> None:
        import time

        from raydp_tpu.cluster.common import ActorState

        # The whole teardown runs UNDER the job lock ON PURPOSE: stop() is
        # only "done" once the ranks are DEAD and the PG's bundles are back,
        # and a start() admitted mid-drain would see self._pg already None,
        # fail to create a new PG against the still-reserved bundles, and
        # fall into its add_node() fallback — permanently growing the
        # cluster. The lock is the job's lifecycle serializer, its hold is
        # bounded by the 15s drain deadline, and nothing under it takes any
        # other instrumented lock, so no inversion is possible.
        with self._lock:
            killed = list(self._workers)
            for w in killed:
                try:
                    w.kill(no_restart=True)
                except Exception:
                    # already dead is the common case; count the rest so a
                    # systematically failing teardown is visible in metrics
                    from raydp_tpu.obs import metrics

                    metrics.counter("spmd.teardown_kill_failures").inc()
            self._workers = []
            # drain: bundles must be free before the PG is removed, and the
            # next job's PG must see the resources back
            deadline = time.monotonic() + 15.0
            for w in killed:
                while time.monotonic() < deadline:
                    try:
                        if w.state() == ActorState.DEAD:
                            break
                    except Exception:  # raydp-lint: disable=swallowed-exceptions (polling a dying actor)
                        break
                    # raydp-lint: disable=blocking-under-lock (deliberate, deadline-bounded hold — see the lifecycle-serializer comment above)
                    time.sleep(0.05)
            if self._owns_pg and self._pg is not None:
                try:
                    cluster.remove_placement_group(self._pg)
                except Exception:
                    from raydp_tpu.obs import log as obs_log

                    obs_log.warning(
                        "failed to remove SPMD placement group; bundles may "
                        "stay reserved until session shutdown",
                        pg=self._pg.id, exc_info=True,
                    )
                self._pg = None
                self._owns_pg = False
            self._started = False
            self._func_id = 0

    # restart parity (reference _reset + start again, :345-396)
    def restart(self) -> "SpmdJob":
        self.stop()
        return self.start()

    def __enter__(self) -> "SpmdJob":
        return self.start() if not self._started else self

    def __exit__(self, *exc) -> None:
        self.stop()


def create_spmd_job(
    job_name: Optional[str] = None,
    world_size: int = 1,
    num_cpus_per_worker: float = 1.0,
    placement_group: Optional[cluster.PlacementGroup] = None,
    placement_group_bundle_indexes: Optional[List[int]] = None,
    placement_strategy: str = "SPREAD",
    env: Optional[Dict[str, str]] = None,
    timeout: float = 120.0,
) -> SpmdJob:
    """Parity: raydp.mpi.create_mpi_job (reference mpi/__init__.py:36-91)."""
    return SpmdJob(
        job_name or f"spmd-{uuid.uuid4().hex[:8]}",
        world_size,
        num_cpus_per_worker=num_cpus_per_worker,
        placement_group=placement_group,
        placement_group_bundle_indexes=placement_group_bundle_indexes,
        placement_strategy=placement_strategy,
        env=env,
        timeout=timeout,
    )
