"""Elastic multi-host training: the rebuild-mesh-from-checkpoint watchdog.

TPU pods fail as slices — a dead host cannot hot-swap into a running
jax.distributed mesh, so the recovery model is: detect the dead rank, tear
the gang down, start a fresh gang, and resume from the last committed
checkpoint (SURVEY.md §7 hard part 3). Round 1 shipped every piece
(restartable actors, orbax epoch checkpoints, ``resume_from_epoch``) but
not the loop that connects them; this module is that loop.

Strictly stronger than the reference's recovery story: its only elasticity
test re-materializes converted *data* after a node kill
(test_reconstruction, reference test_spark_cluster.py:166-196) while
training-level failures just re-run whole trainers via Ray Train's
FailureConfig. Here a mid-fit rank death costs only the epochs since the
last checkpoint.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

from raydp_tpu.cluster.common import ClusterError


def _invoke(fit_fn, resume_from_epoch, ctx):
    return fit_fn(ctx, resume_from_epoch)


def elastic_fit(
    fit_fn: Callable[[Any, Optional[int]], Any],
    world_size: int,
    checkpoint_dir: str,
    max_failures: int = 2,
    job_name: str = "elastic",
    env: Optional[Dict[str, str]] = None,
    num_cpus_per_worker: float = 1.0,
    timeout: float = 300.0,
    bootstrap: bool = True,
) -> List[Any]:
    """Run ``fit_fn(ctx, resume_from_epoch)`` on every rank of an SPMD gang,
    restarting the WHOLE gang from the latest committed checkpoint when any
    rank dies mid-fit.

    ``fit_fn`` must write checkpoints under ``checkpoint_dir``
    (JaxEstimator(checkpoint_dir=...) does) and honor the resume value it is
    passed (None = fresh start; an int epoch, or an ``(epoch, step)`` tuple
    when the newest committed checkpoint is a save_every_steps mid-epoch one
    — JaxEstimator's ``resume_from_epoch`` accepts both, so a mid-epoch
    death replays only the tail steps). Returns the per-rank results of the
    first fully-successful attempt.
    """
    from raydp_tpu.estimator.jax_estimator import latest_checkpoint
    from raydp_tpu.spmd.job import create_spmd_job

    failures = 0
    while True:
        latest = latest_checkpoint(checkpoint_dir)
        if latest is None:
            resume = None
        elif latest[1] is None:
            resume = latest[0]  # epoch complete
        else:
            resume = latest  # (epoch, step): resume mid-epoch
        job = create_spmd_job(
            f"{job_name}-a{failures}",
            world_size=world_size,
            env=env,
            num_cpus_per_worker=num_cpus_per_worker,
            timeout=timeout,
        )
        try:
            job.start()
            if bootstrap:
                job.bootstrap_jax()
            return job.run(
                functools.partial(_invoke, fit_fn, resume), timeout=timeout
            )
        except (
            ClusterError,
            ConnectionError,
            EOFError,
            TimeoutError,
        ):
            failures += 1
            if failures > max_failures:
                raise
            # loop: the next attempt resumes at the newest checkpoint that
            # landed before the failure
        finally:
            job.stop()
