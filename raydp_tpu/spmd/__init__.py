"""SPMD runtime: job launcher (MPI-on-Ray parity) + jax.distributed bootstrap."""

from raydp_tpu.spmd.bootstrap import initialize_from_env, process_rank, world_size
from raydp_tpu.spmd.elastic import elastic_fit
from raydp_tpu.spmd.job import SpmdJob, SpmdWorker, WorkerContext, create_spmd_job

__all__ = [
    "SpmdJob",
    "SpmdWorker",
    "WorkerContext",
    "create_spmd_job",
    "elastic_fit",
    "initialize_from_env",
    "process_rank",
    "world_size",
]
