"""Parameter-sharding rules: regex-on-path → PartitionSpec.

The one genuinely model-parallel artifact the reference's workloads need is
DLRM's sharded embedding tables (BASELINE.md); here that is a rule like
``(r"embedding", P("model", None))``. Everything else defaults to replicated.
"""

from __future__ import annotations

import re
from typing import Any, Callable, List, Sequence, Tuple


def shard_params_by_rules(
    mesh,
    params,
    rules: Sequence[Tuple[str, Any]],
    default=None,
):
    """pytree of NamedShardings: first rule whose regex matches the param's
    '/'-joined path wins; unmatched params use ``default`` (replicated).

    Shapes that don't divide the mesh axis fall back to replication rather
    than failing inside jit with an opaque sharding error.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    replicated = NamedSharding(mesh, default or PartitionSpec())
    compiled = [(re.compile(pattern), spec) for pattern, spec in rules]

    def resolve(path, leaf):
        path_str = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        for regex, spec in compiled:
            if regex.search(path_str):
                if _divisible(leaf.shape, spec, mesh):
                    return NamedSharding(mesh, spec)
                return replicated
        return replicated

    return jax.tree_util.tree_map_with_path(resolve, params)


def _divisible(shape, spec, mesh) -> bool:
    for dim, axis in zip(shape, tuple(spec)):
        if axis is None:
            continue
        axes = axis if isinstance(axis, tuple) else (axis,)
        size = 1
        for a in axes:
            size *= int(mesh.shape.get(a, 1))
        if dim % size:
            return False
    return True


def sharding_rules_fn(rules: Sequence[Tuple[str, Any]]) -> Callable:
    """Adapter for JaxEstimator(param_sharding_rules=...)."""

    def fn(mesh, params):
        return shard_params_by_rules(mesh, params, rules)

    return fn


def shard_map_compat(f, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across jax versions. Modern jax exposes it at the
    top level with a ``check_vma`` kwarg; the legacy experimental entry point
    spells the same switch ``check_rep`` — translating here keeps every
    caller on one signature (passing check_vma to the legacy one is a
    TypeError)."""
    kwargs = {}
    try:
        from jax import shard_map

        if check_vma is not None:
            kwargs["check_vma"] = check_vma
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

        if check_vma is not None:
            kwargs["check_rep"] = check_vma
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
