"""Ring attention: exact attention over sequences sharded across devices.

Long-context is first-class in this framework (the reference has no sequence
axis at all — SURVEY.md §5 "long-context: absent"). The sequence is sharded
over a mesh axis; each device holds a Q/K/V block. K/V blocks rotate around
the ring via ``lax.ppermute`` while every device accumulates its Q block's
attention with the numerically-stable online-softmax update (flash-attention
statistics: running max m, denominator l, unnormalized output o). After
``axis_size`` steps every Q block has attended to the full sequence — exact
attention, O(T/N) memory per device, and the permute overlaps with compute
under XLA's latency-hiding scheduler on ICI.

Causal masking uses global block offsets derived from ``lax.axis_index``:
a rotated K/V block j contributes fully when j < i, triangularly when j == i,
and not at all when j > i (those steps still run — uniform control flow — but
are masked to -inf so the softmax ignores them).
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from raydp_tpu.parallel.mesh import axis_env_size

NEG_INF = -1e30


def _block_attn(q, k, v, mask):
    """Scores for one (Q-block, K-block) pair + masked online-softmax stats.

    q: [B, H, Tq, D], k/v: [B, H, Tk, D], mask: [Tq, Tk] bool (True = keep).
    Returns (o_un, m, l): unnormalized output, row max, row denom.
    """
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    scores = jnp.where(mask[None, None], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # [B,H,Tq]
    # guard all-masked rows: exp(NEG_INF - NEG_INF) would be 1, so zero them
    row_valid = jnp.any(mask, axis=-1)[None, None]  # [1,1,Tq broadcast]
    p = jnp.exp(scores - m[..., None])
    p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)  # noqa: E741 - flash-attention notation
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    m = jnp.where(row_valid, m, NEG_INF)
    return o, m, l


def _merge(o1, m1, l1, o2, m2, l2):
    """Combine two online-softmax partials (standard flash merge)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    o = o1 * a1[..., None] + o2 * a2[..., None]
    l = l1 * a1 + l2 * a2  # noqa: E741
    return o, m, l


def _ring_forward_stats(q, k, v, axis_name, causal, use_flash):
    """Ring forward returning (o_unnormalized, m, l)."""
    n = axis_env_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, t, d = q.shape
    tk = k.shape[2]

    q_pos = jnp.arange(t)
    k_pos = jnp.arange(tk)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def block_product(k_cur, v_cur, s):
        """(o_un, m, l) of q against the K/V block that originated on device
        (my_idx - s) mod n."""
        src = (my_idx - s) % n
        if use_flash:
            from raydp_tpu.ops.flash_attention import flash_attention_stats

            return flash_attention_stats(
                q, k_cur, v_cur, my_idx * t, src * tk, causal
            )
        if causal:
            gq = my_idx * t + q_pos
            gk = src * tk + k_pos
            mask = gq[:, None] >= gk[None, :]
        else:
            mask = jnp.ones((t, tk), bool)
        return _block_attn(q, k_cur, v_cur, mask)

    # step 0: the local block, no communication
    o, m, l = block_product(k, v, 0)  # noqa: E741

    def step(carry, s):
        o, m, l, k_cur, v_cur = carry  # noqa: E741
        # permute FIRST, then attend — no dead rotation after the last use
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        o2, m2, l2 = block_product(k_cur, v_cur, s)
        o, m, l = _merge(o, m, l, o2, m2, l2)  # noqa: E741
        return (o, m, l, k_cur, v_cur), None

    if n > 1:
        (o, m, l, _, _), _ = lax.scan(  # noqa: E741
            step, (o, m, l, k, v), jnp.arange(1, n)
        )
    return o, m, l


def _block_grads(q, k, v, lse, dsum, g, q_off, k_off, causal, use_flash):
    """(dq, dk, dv) partials of the local Q block against ONE K/V block,
    from the GLOBAL logsumexp/dsum — the backward counterpart of the
    forward's block products."""
    if use_flash:
        from raydp_tpu.ops.flash_attention import flash_backward_blocks

        return flash_backward_blocks(
            q, k, v, lse, dsum, g, q_off, k_off, causal
        )
    scale = q.shape[-1] ** -0.5
    t, tk = q.shape[2], k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        gq = q_off + jnp.arange(t)
        gk = k_off + jnp.arange(tk)
        s = jnp.where(gq[:, None] >= gk[None, :], s, NEG_INF)
    p = jnp.exp(s - lse[..., None])  # masked rows underflow to exactly 0
    gf = g.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
    dp = jnp.einsum("bhqd,bhkd->bhqk", gf, v.astype(jnp.float32))
    ds = p * (dp - dsum[..., None]) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool = False,
    use_flash: bool = False,
) -> jnp.ndarray:
    """Exact attention with sequence sharded over ``axis_name``.

    Call inside ``shard_map`` (or any SPMD context where ``axis_name`` is
    bound). Shapes are per-device: q, k, v: [B, H, T_local, D]; the global
    sequence is ``T_local * axis_size`` in ring order.

    ``use_flash=True`` computes each (Q-block, K/V-block) product with the
    fused pallas flash kernel (O(T_local) VMEM, MXU scores) instead of the
    einsum path; the cross-device merge is identical.

    TRAINING is O(T_local) memory either way: the custom VJP runs a second
    ring pass — dk/dv accumulators rotate WITH their K/V blocks and arrive
    home after a full cycle — rebuilding each block's probabilities from the
    saved global logsumexp instead of saving any [T, T] intermediate.
    """
    o, m, l = _ring_forward_stats(q, k, v, axis_name, causal, use_flash)  # noqa: E741
    return (o / jnp.maximum(l[..., None], 1e-30)).astype(q.dtype)


def _ring_fwd(q, k, v, axis_name, causal, use_flash):
    o, m, l = _ring_forward_stats(q, k, v, axis_name, causal, use_flash)  # noqa: E741
    out = (o / jnp.maximum(l[..., None], 1e-30)).astype(q.dtype)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))  # [B,H,T] global logsumexp
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, use_flash, residuals, g):
    q, k, v, out, lse = residuals
    n = axis_env_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    t, tk = q.shape[2], k.shape[2]
    perm = [(i, (i + 1) % n) for i in range(n)]
    dsum = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [B,H,T]

    def step(carry, s):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (my_idx - s) % n  # origin of the block currently held
        dq_p, dk_p, dv_p = _block_grads(
            q, k_cur, v_cur, lse, dsum, g,
            my_idx * t, src * tk, causal, use_flash,
        )
        dq = dq + dq_p
        dk_cur = dk_cur + dk_p
        dv_cur = dv_cur + dv_p
        # rotate the block AND its gradient accumulators together: after a
        # full cycle every (k, v, dk, dv) quadruple is back on its home
        # device with contributions from every Q shard accumulated
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        dk_cur = lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = lax.ppermute(dv_cur, axis_name, perm)
        return (dq, k_cur, v_cur, dk_cur, dv_cur), None

    # derive zeros from the inputs so they carry the same varying-axes type
    # under shard_map (a fresh constant is unvaried; the loop body's outputs
    # are varying, and scan requires carry types to match exactly)
    zeros_q = (q * 0).astype(jnp.float32)
    zeros_k = (k * 0).astype(jnp.float32)
    zeros_v = (v * 0).astype(jnp.float32)
    init = (zeros_q, k, v, zeros_k, zeros_v)
    (dq, _, _, dk, dv), _ = lax.scan(step, init, jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention.defvjp(_ring_fwd, _ring_bwd)


def ring_attention_sharded(
    q, k, v, mesh, axis: str = "sp", causal: bool = False, use_flash: bool = False
):
    """Convenience wrapper: q/k/v are global arrays sharded over ``axis`` on
    the sequence dim; runs ring_attention under shard_map."""
    from jax.sharding import PartitionSpec as P

    from raydp_tpu.parallel.sharding import shard_map_compat

    spec = P(None, None, axis, None)

    # use_flash: the pallas interpreter can't reconcile invariant grid slices
    # with varying operands; JAX's documented workaround is check_vma=False
    # (numerics are validated against full attention in tests)
    fn = shard_map_compat(
        partial(
            ring_attention, axis_name=axis, causal=causal, use_flash=use_flash
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False if use_flash else None,
    )
    return fn(q, k, v)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool = False,
    use_flash: bool = False,
) -> jnp.ndarray:
    """DeepSpeed-Ulysses-style sequence parallelism: all-to-all swaps the
    sharded dim from sequence to heads, attention runs locally on full
    sequences for H/N heads, then all-to-all swaps back. Cheaper than a ring
    when H divides the axis and the full sequence fits one device's memory
    budget; call inside shard_map. Per-device shapes: [B, H, T_local, D].
    ``use_flash``: compute the local attention with the fused pallas flash
    kernel (O(T) memory for the gathered sequence) instead of the einsum."""
    n = axis_env_size(axis_name)
    b, h, t, d = q.shape
    if h % n:
        raise ValueError(f"heads {h} not divisible by sequence axis {n}")

    def seq_to_heads(x):
        # [B, H, T_local, D] -> [B, H/N, T_global, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def heads_to_seq(x):
        # [B, H/N, T_global, D] -> [B, H, T_local, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if use_flash:
        from raydp_tpu.ops.flash_attention import flash_attention

        # default blocks = pick_blocks: the measured-fastest large tiles
        og = flash_attention(qg, kg, vg, causal)
        return heads_to_seq(og)
    tg = qg.shape[2]
    scale = d**-0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", qg, kg) * scale
    if causal:
        pos = jnp.arange(tg)
        scores = jnp.where(pos[:, None] >= pos[None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    og = jnp.einsum("bhqk,bhkd->bhqd", probs, vg)
    return heads_to_seq(og)


def full_attention(q, k, v, causal: bool = False) -> jnp.ndarray:
    """Single-device reference implementation (for tests and small models)."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        tq, tk = scores.shape[-2:]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        scores = jnp.where(mask, scores, NEG_INF)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, -1), v)
