"""Expert parallelism: top-1 mixture-of-experts with all-to-all dispatch.

Each device on the ``ep`` axis hosts ONE expert. Tokens are data-sharded over
the same axis; a replicated router assigns each token an expert; dispatch
builds per-expert capacity buffers, an all-to-all ships every device's buffer
for expert e to device e, the expert runs on its combined buffer, and the
inverse all-to-all + weighted combine returns outputs to the tokens' home
devices. Tokens beyond an expert's capacity are dropped (output 0) — the
standard capacity-factor trade.

All dispatch/combine math is one-hot einsums: MXU-friendly, fully
differentiable (gradients flow through the gate weights), no gathers.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def moe_apply(
    expert_fn: Callable,
    expert_params,
    router_weights: jnp.ndarray,  # [D, N] replicated
    x: jnp.ndarray,  # [B_local, D] this device's token shard
    axis_name: str = "ep",
    capacity_factor: float = 1.25,
) -> jnp.ndarray:
    """Call inside shard_map. ``expert_params`` is THIS device's expert."""
    import math

    n = lax.axis_size(axis_name)
    b, d = x.shape
    # ceil keeps the requested headroom even at small per-device batches
    capacity = max(1, math.ceil(b * capacity_factor / n))  # per (device, expert)

    logits = x @ router_weights  # [B, N]
    gates = jax.nn.softmax(logits, axis=-1)
    assign = jnp.argmax(gates, axis=-1)  # [B]
    gate = jnp.take_along_axis(gates, assign[:, None], axis=1)[:, 0]  # [B]

    # slot bookkeeping in f32 regardless of x.dtype: a bf16 cumsum saturates
    # at 256 and silently collides capacity slots
    one_hot_f32 = jax.nn.one_hot(assign, n, dtype=jnp.float32)  # [B, N]
    pos = (jnp.cumsum(one_hot_f32, axis=0) - 1.0) * one_hot_f32  # [B, N]
    in_capacity = pos < capacity
    dispatch_mask = one_hot_f32 * in_capacity  # [B, N]
    slot_one_hot = jax.nn.one_hot(
        pos.astype(jnp.int32), capacity, dtype=jnp.float32
    )  # [B, N, C]
    dispatch = (slot_one_hot * dispatch_mask[:, :, None]).astype(x.dtype)

    # local per-expert buffers [N, C, D] → ship buffer e to device e; the
    # tiled all_to_all splits the expert dim across devices and concatenates
    # the received chunks along the slot dim: result [1, C*n, D] — all
    # devices' capacity buffers for MY expert
    buffers = jnp.einsum("bnc,bd->ncd", dispatch, x)
    received = lax.all_to_all(
        buffers, axis_name, split_axis=0, concat_axis=1, tiled=True
    )
    received = received.reshape(n * capacity, d)

    expert_out = expert_fn(expert_params, received)  # [n*C, D_out]
    d_out = expert_out.shape[-1]
    expert_out = expert_out.reshape(1, n * capacity, d_out)

    # inverse: send each source device its slice back
    returned = lax.all_to_all(
        expert_out, axis_name, split_axis=1, concat_axis=0, tiled=True
    )  # [n, C, D_out] — my tokens' outputs, per assigned expert
    combined = jnp.einsum("bnc,ncd->bd", dispatch, returned)
    return combined * gate[:, None]  # dropped tokens yield 0


def moe_sharded(
    expert_fn: Callable,
    stacked_expert_params,
    router_weights: jnp.ndarray,
    x: jnp.ndarray,  # [B, D] global
    mesh,
    axis: str = "ep",
    capacity_factor: float = 1.25,
) -> jnp.ndarray:
    """Global wrapper: expert params stacked on a leading dim sharded over
    ``axis``; tokens sharded over the same axis (dp=ep co-located)."""
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    def body(params_local, router, x_local):
        params = jax.tree.map(lambda p: p[0], params_local)
        return moe_apply(
            expert_fn, params, router, x_local,
            axis_name=axis, capacity_factor=capacity_factor,
        )

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis), stacked_expert_params), P(), P(axis)),
        out_specs=P(axis),
    )(stacked_expert_params, router_weights, x)
