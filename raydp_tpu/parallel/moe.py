"""Expert parallelism: top-k mixture-of-experts with all-to-all dispatch.

Each device on the ``ep`` axis hosts ONE expert. Tokens are data-sharded over
the same axis; a replicated router assigns each token its top-k experts
(renormalized gates); dispatch builds per-expert capacity buffers, an
all-to-all ships every device's buffer for expert e to device e, the expert
runs on its combined buffer, and the inverse all-to-all + weighted combine
returns outputs to the tokens' home devices. Capacity is allocated
first-choice-first (GShard priority): second choices are the first dropped
when an expert overflows, and dropped (token, choice) pairs contribute 0.

Router health is a first-class output (``return_aux=True``):
- ``load_balance_loss`` — the Switch-Transformer auxiliary loss
  N * Σ_n f_n · P_n (f_n = routed fraction to expert n PRE-capacity, P_n =
  mean router probability); 1.0 at perfect balance, grows as the router
  collapses. Add
  ``aux_weight * load_balance_loss`` to the task loss to train against
  collapse.
- ``drop_fraction`` — fraction of (token, choice) pairs dropped by capacity;
  silent in round 1, now observable.

All dispatch/combine math is one-hot einsums: MXU-friendly, fully
differentiable (gradients flow through the gate weights), no gathers.
The reference has no MoE at all (SURVEY.md §2.4: TP/PP/SP/EP absent).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from raydp_tpu.parallel.mesh import axis_env_size


def moe_apply(
    expert_fn: Callable,
    expert_params,
    router_weights: jnp.ndarray,  # [D, N] replicated
    x: jnp.ndarray,  # [B_local, D] this device's token shard
    axis_name: str = "ep",
    capacity_factor: float = 1.25,
    top_k: int = 1,
    return_aux: bool = False,
):
    """Call inside shard_map. ``expert_params`` is THIS device's expert.

    Returns the combined output [B_local, D_out]; with ``return_aux=True``
    returns ``(out, {"load_balance_loss", "drop_fraction"})`` where the aux
    scalars are pmean'd over ``axis_name`` (identical on every device).
    """
    import math

    n = axis_env_size(axis_name)
    b, d = x.shape
    k = min(top_k, n)
    # ceil keeps the requested headroom even at small per-device batches;
    # scales with k because every token now occupies up to k slots
    capacity = max(1, math.ceil(b * k * capacity_factor / n))

    logits = x @ router_weights  # [B, N]
    gates = jax.nn.softmax(logits, axis=-1)
    top_vals, top_idx = lax.top_k(gates, k)  # [B, K]
    if k > 1:
        # renormalize the chosen gates (GShard): combine weights sum to 1
        weights = top_vals / jnp.maximum(
            jnp.sum(top_vals, axis=-1, keepdims=True), 1e-30
        )
    else:
        weights = top_vals

    # slot bookkeeping in f32 regardless of x.dtype: a bf16 cumsum saturates
    # at 256 and silently collides capacity slots. Choice-major flattening
    # gives first choices strictly higher capacity priority than second.
    oh = jax.nn.one_hot(top_idx.T, n, dtype=jnp.float32)  # [K, B, N]
    pos = (jnp.cumsum(oh.reshape(k * b, n), axis=0) - 1.0).reshape(k, b, n) * oh
    in_capacity = pos < capacity
    dispatch_mask = oh * in_capacity  # [K, B, N]
    slot_one_hot = jax.nn.one_hot(
        pos.astype(jnp.int32), capacity, dtype=jnp.float32
    )  # [K, B, N, C]
    dispatch_k = slot_one_hot * dispatch_mask[..., None]  # [K, B, N, C]
    # send each token once per chosen expert; fold the gate weight into the
    # combine side only
    dispatch_send = jnp.sum(dispatch_k, axis=0).astype(x.dtype)  # [B, N, C]
    combine_w = jnp.einsum(
        "kbnc,bk->bnc", dispatch_k, weights.astype(jnp.float32)
    ).astype(x.dtype)

    # local per-expert buffers [N, C, D] → ship buffer e to device e; the
    # tiled all_to_all splits the expert dim across devices and concatenates
    # the received chunks along the slot dim: result [1, C*n, D] — all
    # devices' capacity buffers for MY expert
    buffers = jnp.einsum("bnc,bd->ncd", dispatch_send, x)
    received = lax.all_to_all(
        buffers, axis_name, split_axis=0, concat_axis=1, tiled=True
    )
    received = received.reshape(n * capacity, d)

    expert_out = expert_fn(expert_params, received)  # [n*C, D_out]
    d_out = expert_out.shape[-1]
    expert_out = expert_out.reshape(1, n * capacity, d_out)

    # inverse: send each source device its slice back
    returned = lax.all_to_all(
        expert_out, axis_name, split_axis=1, concat_axis=0, tiled=True
    )  # [n, C, D_out] — my tokens' outputs, per assigned expert
    out = jnp.einsum("bnc,ncd->bd", combine_w, returned)
    if not return_aux:
        return out

    # Switch-Transformer load-balancing loss: N * Σ_n f_n · P_n. f_n is the
    # ROUTED fraction (pre-capacity, standard Switch formulation — it can
    # exceed what was actually dispatched when drops occur) and is constant
    # wrt the router — gradients flow through P_n, pushing probability mass
    # toward under-used experts.
    f = jnp.mean(oh, axis=(0, 1))  # [N] fraction of choices per expert
    p = jnp.mean(gates, axis=0)  # [N] mean router probability
    aux = {
        "load_balance_loss": lax.pmean(
            n * jnp.sum(lax.stop_gradient(f) * p), axis_name
        ),
        "drop_fraction": lax.pmean(
            1.0 - jnp.sum(dispatch_mask) / (b * k), axis_name
        ),
    }
    return out, aux


def moe_sharded(
    expert_fn: Callable,
    stacked_expert_params,
    router_weights: jnp.ndarray,
    x: jnp.ndarray,  # [B, D] global
    mesh,
    axis: str = "ep",
    capacity_factor: float = 1.25,
    top_k: int = 1,
    return_aux: bool = False,
):
    """Global wrapper: expert params stacked on a leading dim sharded over
    ``axis``; tokens sharded over the same axis (dp=ep co-located)."""
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    def body(params_local, router, x_local):
        params = jax.tree.map(lambda p: p[0], params_local)
        return moe_apply(
            expert_fn, params, router, x_local,
            axis_name=axis, capacity_factor=capacity_factor,
            top_k=top_k, return_aux=return_aux,
        )

    out_specs = P(axis)
    if return_aux:
        out_specs = (
            P(axis),
            {"load_balance_loss": P(), "drop_fraction": P()},
        )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis), stacked_expert_params), P(), P(axis)),
        out_specs=out_specs,
    )(stacked_expert_params, router_weights, x)
