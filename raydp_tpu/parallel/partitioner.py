"""Partitioner: the one place batch/state placement rules live.

The RecML-shaped abstraction (SNIPPETS.md [1]/[3]): ``shard_inputs`` puts a
host batch pytree onto the mesh, ``partition_step`` wraps the step function
under the same placement rules. Every feed path in the
repo (exchange ``device_put_batch``/``device_put_stacked``, the estimator's
scan/stream runners) routes through ONE ``DataParallelPartitioner`` so the
placement rules — and their sharp edges, catalogued below — cannot fork per
call site:

- **shard-direct** (default): inputs go through
  ``jax.make_array_from_process_local_data`` — each PROCESS contributes only
  its local rows and the runtime assembles the global array, so a multi-host
  feed never stages the global batch on one driver. Single-process this is
  semantically identical to a sharded ``device_put``; the toggle
  (``shard_direct=False``) keeps the legacy driver-staged ``device_put`` as
  the A/B arm (parity tests assert byte-identical results).
- **single-device meshes stay uncommitted**: a committed array (even
  SingleDeviceSharding) forces the SPMD-executor path on some PJRT plugins —
  ~10ms per call, measured 14× step slowdown — so the default device takes a
  plain ``jnp.asarray`` and only an explicit non-default device pins.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import numpy as np


def _mesh_device_count(mesh) -> int:
    try:
        return int(np.prod(list(mesh.shape.values())))
    except Exception:
        return 2  # unknown mesh type: assume multi-device


def _mesh_single_device(mesh):
    return np.asarray(mesh.devices).reshape(-1)[0]


class Partitioner:
    """Abstract partitioning logic for data and computation (RecML shape)."""

    def shard_inputs(self, inputs: Any) -> Any:
        """Shard a host batch pytree (leading dim = batch) onto devices."""
        raise NotImplementedError

    def shard_stacked(self, inputs: Any) -> Any:
        """Shard a STACKED [S, B, ...] segment pytree (scan dim leading,
        batch dim second) onto devices."""
        raise NotImplementedError

    def partition_step(self, fn: Callable, *, donate_argnums=()) -> Callable:
        """Jit a train/eval step under this partitioner's placement rules."""
        raise NotImplementedError


class NullPartitioner(Partitioner):
    """No-op placement: inputs pass through, steps get a plain jit."""

    def shard_inputs(self, inputs: Any) -> Any:
        return inputs

    def shard_stacked(self, inputs: Any) -> Any:
        return inputs

    def partition_step(self, fn: Callable, *, donate_argnums=()) -> Callable:
        from raydp_tpu.sanitize import checked_jit

        return checked_jit(fn, donate_argnums=donate_argnums)


class DataParallelPartitioner(Partitioner):
    """Batch dim sharded over ``axis``, params replicated (or ruled).

    ``shard_direct=True`` (default) feeds through
    ``make_array_from_process_local_data`` — the per-process upload path;
    ``False`` is the legacy driver-staged sharded ``device_put``. Both land
    byte-identical arrays; multi-host, only shard-direct avoids materializing
    the global batch per process.
    """

    def __init__(self, mesh, axis: str = "data", shard_direct: bool = True):
        self.mesh = mesh
        self.axis = axis
        self.shard_direct = bool(shard_direct)
        # resolved once — shard_inputs sits on the per-segment hot path
        self._single_device = None
        from raydp_tpu.obs import metrics

        self._direct_puts = metrics.counter("partitioner.shard_direct_puts")
        self._staged_puts = metrics.counter("partitioner.driver_staged_puts")

    # -- placement ------------------------------------------------------

    def _is_single_device(self) -> bool:
        if self._single_device is None:
            import jax

            self._single_device = (
                _mesh_device_count(self.mesh) <= 1 and jax.process_count() == 1
            )
        return self._single_device

    def _sharding(self, ndim: int, stacked: bool):
        from jax.sharding import NamedSharding, PartitionSpec

        if stacked:
            spec = PartitionSpec(None, self.axis, *([None] * (ndim - 2)))
        else:
            spec = PartitionSpec(self.axis, *([None] * (ndim - 1)))
        return NamedSharding(self.mesh, spec)

    def _put_leaf(self, x, stacked: bool):
        import jax

        if x is None:
            return None
        x = np.asarray(x)
        if self._is_single_device():
            import jax.numpy as jnp

            device = _mesh_single_device(self.mesh)
            if device == jax.devices()[0]:
                # default device: stay UNCOMMITTED — a committed array (even
                # SingleDeviceSharding) forces a ~10ms/call executor path on
                # some PJRT plugins (14× step slowdown measured)
                return jnp.asarray(x)
            return jax.device_put(x, device)  # explicit non-default pin
        sharding = self._sharding(max(1, x.ndim), stacked)
        if self.shard_direct or jax.process_count() > 1:
            # shard-direct: this process hands over only ITS rows; the
            # runtime assembles the global array (multi-process has no
            # driver-staged alternative — the global batch never exists in
            # any one process)
            self._direct_puts.inc()
            return jax.make_array_from_process_local_data(sharding, x)
        self._staged_puts.inc()
        return jax.device_put(x, sharding)

    def shard_inputs(self, inputs: Any) -> Any:
        import jax

        return jax.tree_util.tree_map(
            lambda x: self._put_leaf(x, stacked=False), inputs
        )

    def shard_stacked(self, inputs: Any) -> Any:
        import jax

        return jax.tree_util.tree_map(
            lambda x: self._put_leaf(x, stacked=True), inputs
        )

    # -- computation ----------------------------------------------------

    def partition_step(self, fn: Callable, *, donate_argnums=()) -> Callable:
        """Step jit under this partitioner's placement rules: donation-checked
        (``RAYDP_TPU_SANITIZE=donation`` verifies donated args against
        externally-owned host spans at dispatch) and mesh-scoped by the
        caller's ``with mesh`` context — the same ``checked_jit`` chain the
        estimator's ``partial_jit`` builds. The streaming runner jits its
        segment scan through here; the remaining estimator jit sites still
        call ``partial_jit`` directly (identical semantics)."""
        from raydp_tpu.sanitize import checked_jit

        return checked_jit(fn, donate_argnums=donate_argnums)
