"""Parallelism: mesh construction, sharding rules, sequence parallelism."""

from raydp_tpu.parallel.mesh import (
    axis_env_size,
    data_parallel_mesh,
    make_mesh,
    mesh_axis_size,
    multihost_mesh,
)
from raydp_tpu.parallel.partitioner import (
    DataParallelPartitioner,
    NullPartitioner,
    Partitioner,
)
from raydp_tpu.parallel.ring_attention import (
    full_attention,
    ring_attention,
    ring_attention_sharded,
    ulysses_attention,
)
from raydp_tpu.parallel.moe import moe_apply, moe_sharded
from raydp_tpu.parallel.pipeline import pipeline_apply, pipeline_sharded
from raydp_tpu.parallel.sharding import shard_params_by_rules, sharding_rules_fn

__all__ = [
    "DataParallelPartitioner",
    "NullPartitioner",
    "Partitioner",
    "axis_env_size",
    "moe_apply",
    "moe_sharded",
    "pipeline_apply",
    "pipeline_sharded",
    "data_parallel_mesh",
    "full_attention",
    "make_mesh",
    "mesh_axis_size",
    "multihost_mesh",
    "ring_attention",
    "ring_attention_sharded",
    "shard_params_by_rules",
    "sharding_rules_fn",
    "ulysses_attention",
]
