"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pp`` axis.

Each device on the axis holds ONE stage's parameters (stacked and sharded on
the leading dim). Activations flow rightward via ``lax.ppermute`` inside a
``lax.scan`` over M + N - 1 ticks: device d computes stage d at tick t for
microbatch t - d; the first N-1 and last N-1 ticks are the pipeline bubble.
All devices execute the same program every tick (SPMD — control flow is
uniform, data is masked), so XLA compiles one step and the permutes ride ICI.

The reference has no model parallelism of any kind (SURVEY.md §2.4); this is
part of making the mesh axes (dp/tp/sp/pp/ep) first-class.
"""

from __future__ import annotations


from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from raydp_tpu.parallel.mesh import axis_env_size


def pipeline_apply(
    stage_fn: Callable,
    stage_params: Any,
    microbatches: jnp.ndarray,
    axis_name: str = "pp",
) -> jnp.ndarray:
    """Run a pipeline of ``N = axis_size`` stages over M microbatches.

    Call inside shard_map. Per-device arguments:
      - ``stage_params``: THIS device's stage parameters (pytree).
      - ``microbatches``: [M, B, F] — the full microbatch stream (replicated;
        only device 0 consumes it as input).
    Returns [M, B, F_out] (meaningful on the last device; replicate or
    psum-select outside as needed — see ``pipeline_sharded`` below).
    """
    n = axis_env_size(axis_name)
    my = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    ticks = m + n - 1

    right = [(i, (i + 1) % n) for i in range(n)]
    sample_out = jax.eval_shape(stage_fn, stage_params, microbatches[0])
    out_buffer = jnp.zeros((m,) + tuple(sample_out.shape), sample_out.dtype)

    def tick(carry, t):
        incoming, outputs = carry
        # stage 0 reads microbatch t (clamped; masked when t >= m)
        feed_idx = jnp.clip(t, 0, m - 1)
        first_in = lax.dynamic_index_in_dim(microbatches, feed_idx, 0, False)
        x = jnp.where(my == 0, first_in, incoming)
        y = stage_fn(stage_params, x)
        # last device banks microbatch (t - (n-1)) at ticks >= n-1
        out_idx = jnp.clip(t - (n - 1), 0, m - 1)
        should_store = (my == n - 1) & (t >= n - 1)
        updated = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(should_store, y, lax.dynamic_index_in_dim(outputs, out_idx, 0, False)),
            out_idx,
            0,
        )
        # activations move one stage rightward
        nxt = lax.ppermute(y, axis_name, right)
        return (nxt, updated), None

    # stage inputs/outputs must be shape-uniform across stages for the permute
    if tuple(sample_out.shape) != tuple(microbatches.shape[1:]):
        raise ValueError(
            "pipeline stages must preserve activation shape "
            f"(got {microbatches.shape[1:]} -> {sample_out.shape})"
        )
    zero_in = jnp.zeros(sample_out.shape, sample_out.dtype)
    # fresh zeros are device-invariant; the carry becomes varying over the
    # pipeline axis (axis_index-dependent), so mark the initial values too
    zero_in, out_buffer = (_pvary(v, axis_name) for v in (zero_in, out_buffer))
    (_, outputs), _ = lax.scan(tick, (zero_in, out_buffer), jnp.arange(ticks))
    return outputs


def _pvary(x, axis_name):
    try:
        return lax.pcast(x, (axis_name,), to="varying")
    except AttributeError:
        try:
            return lax.pvary(x, (axis_name,))
        except AttributeError:
            return x


def pipeline_sharded(
    stage_fn: Callable,
    stacked_params: Any,
    x: jnp.ndarray,
    mesh,
    num_microbatches: int,
    axis: str = "pp",
) -> jnp.ndarray:
    """Global-array wrapper: ``stacked_params`` leaves have a leading stage
    dim sharded over ``axis``; ``x`` [B, F] is split into microbatches; output
    is the pipelined result [B, F]."""
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible by {num_microbatches} microbatches")
    micro = x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])

    def body(params_local, micro_all):
        # params_local leaves: [1, ...] — this device's stage; drop stage dim
        params = jax.tree.map(lambda p: p[0], params_local)
        outs = pipeline_apply(stage_fn, params, micro_all, axis_name=axis)
        # broadcast the last stage's banked outputs to every device so the
        # out_spec can be replicated
        n = axis_env_size(axis)
        mask = (lax.axis_index(axis) == n - 1).astype(outs.dtype)
        return lax.psum(outs * mask, axis)

    param_specs = jax.tree.map(lambda _: P(axis), stacked_params)
    out = shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
    )(stacked_params, micro)
    return out.reshape(b, *out.shape[2:])
