"""Device-mesh construction over ICI×DCN axes.

The reference has no model parallelism (SURVEY.md §2.4: TP/PP/SP/EP absent);
its distributed story is DDP over Gloo/NCCL plus mpirun. Here the mesh is the
*single* abstraction all parallelism hangs off: data, fsdp, tensor, sequence
and expert axes are named mesh dimensions, and every collective is compiled
into the step function by XLA — the "pick a mesh, annotate shardings, let XLA
insert collectives" recipe.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np


def make_mesh(
    axes: Dict[str, int],
    devices: Optional[Sequence] = None,
    allow_split_physical: bool = True,
):
    """Build a Mesh with named ``axes`` (e.g. {"data": 4, "model": 2}).

    A -1 axis size absorbs the remaining devices (like a reshape). Axis order
    matters on real hardware: earlier axes are outer (DCN-ish), later axes are
    inner (ICI-adjacent) — put tensor/sequence axes last so their collectives
    ride the fastest links.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    names = list(axes.keys())
    sizes = list(axes.values())
    unknown = [i for i, s in enumerate(sizes) if s == -1]
    if len(unknown) > 1:
        raise ValueError("at most one axis may be -1")
    known = int(np.prod([s for s in sizes if s != -1])) or 1
    if unknown:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[unknown[0]] = n // known
    total = int(np.prod(sizes))
    if total > n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, have {n}")
    mesh_devices = np.array(devices[:total]).reshape(sizes)
    return Mesh(mesh_devices, tuple(names))


def data_parallel_mesh(devices: Optional[Sequence] = None):
    return make_mesh({"data": -1}, devices)


def mesh_axis_size(mesh, axis: str) -> int:
    return int(mesh.shape.get(axis, 1))


def axis_env_size(axis_name) -> int:
    """Size of a named MAPPED axis from inside shard_map/pmap — the axis-env
    compat shim. Modern jax spells this ``lax.axis_size``; older releases
    (0.4.x) don't have it, but ``psum`` of a Python-int literal constant-folds
    to a static int at trace time (verified on 0.4.37), so both branches
    return a value usable for shapes and loop bounds."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def multihost_mesh(axes: Dict[str, int], process_axis: str = "data"):
    """Multi-host mesh: each process contributes its local devices; the
    ``process_axis`` spans hosts (DCN), remaining axes stay intra-host (ICI).
    Call after ``jax.distributed.initialize`` (see raydp_tpu.spmd.bootstrap).

    ``jax.devices()`` orders devices process-major, so the slowest-varying
    reshape dim spans hosts: the mesh is built with ``process_axis`` outermost
    and then transposed back to the caller's axis order.
    """
    import jax
    from jax.sharding import Mesh

    if process_axis not in axes:
        raise ValueError(f"process_axis {process_axis!r} not in axes {list(axes)}")
    names = list(axes.keys())
    ordered = [process_axis] + [a for a in names if a != process_axis]
    built = make_mesh({a: axes[a] for a in ordered}, jax.devices())
    if ordered == names:
        return built
    # transpose the device array back to the caller's axis order
    perm = [ordered.index(a) for a in names]
    return Mesh(np.transpose(built.devices, perm), tuple(names))
