"""Object store: native shared-memory data plane + ownership semantics."""

from raydp_tpu.store.object_store import (
    ObjectHolder,
    ObjectRef,
    WritableBlock,
    create_block,
    delete,
    get_arrow_buffer,
    get_buffer,
    get_bytes,
    new_object_id,
    owner_of,
    put,
    read_arrow_batches,
    transfer,
)

__all__ = [
    "ObjectHolder",
    "ObjectRef",
    "WritableBlock",
    "create_block",
    "delete",
    "get_arrow_buffer",
    "get_buffer",
    "get_bytes",
    "new_object_id",
    "owner_of",
    "put",
    "read_arrow_batches",
    "transfer",
]
