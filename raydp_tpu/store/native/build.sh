#!/bin/sh
# Build the native store library. Idempotent and concurrency-safe: compile to a
# temp file, atomically rename into place.
set -e
cd "$(dirname "$0")"
tmp="libraydp_store.so.tmp.$$"
# -lrt: shm_open/shm_unlink live in librt on pre-2.34 glibc. Without the
# explicit link the library only loads in processes where something else
# already pulled librt in — light (python -S) actors that cold-start
# without the zygote template have no such luck and dlopen fails with
# "undefined symbol: shm_unlink".
g++ -O2 -fPIC -shared -std=c++17 -o "$tmp" store.cpp -lrt
mv -f "$tmp" libraydp_store.so
