#!/bin/sh
# Build the native store library. Idempotent and concurrency-safe: compile to a
# temp file, atomically rename into place.
set -e
cd "$(dirname "$0")"
tmp="libraydp_store.so.tmp.$$"
g++ -O2 -fPIC -shared -std=c++17 -o "$tmp" store.cpp
mv -f "$tmp" libraydp_store.so
