// Native data plane of the object store: POSIX shared-memory segments.
//
// This is the framework's equivalent of the role Ray's plasma store + the
// reference's JVM Arrow writers play on the hot data path (reference
// ObjectStoreWriter.scala:90-172 / ObjectStoreReader.scala:34-56): blocks of
// Arrow IPC bytes move between ETL executor processes and trainer processes
// through /dev/shm with zero serialization overhead beyond the Arrow encode
// itself. Metadata (ownership, sizes, GC) lives in the head process; this
// library only creates, maps and unlinks segments.
//
// Writers stream Arrow IPC directly into a created segment (no staging copy):
// create -> write via mapped pointer -> finalize(actual_size). Readers map
// read-only and hand the pointer to pyarrow as a foreign buffer (zero-copy).

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// Create a segment of `size` bytes and map it read-write.
// Returns the mapped pointer, or nullptr (errno preserved) on failure.
void* rtpu_shm_create(const char* name, uint64_t size) {
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(size)) != 0) {
    int saved = errno;
    close(fd);
    shm_unlink(name);
    errno = saved;
    return nullptr;
  }
  void* ptr = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (ptr == MAP_FAILED) {
    int saved = errno;
    shm_unlink(name);
    errno = saved;
    return nullptr;
  }
  return ptr;
}

// Shrink a finished segment to the bytes actually written. The caller's
// mapping (of the original size) stays valid for the written prefix.
int rtpu_shm_finalize(const char* name, uint64_t actual_size) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return -1;
  int rc = ftruncate(fd, static_cast<off_t>(actual_size));
  int saved = errno;
  close(fd);
  errno = saved;
  return rc;
}

// Map an existing segment; writable=0 -> read-only. Returns pointer or
// nullptr. out_size receives the segment size when non-null.
void* rtpu_shm_map(const char* name, uint64_t* out_size, int writable) {
  int fd = shm_open(name, writable ? O_RDWR : O_RDONLY, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    int saved = errno;
    close(fd);
    errno = saved;
    return nullptr;
  }
  uint64_t size = static_cast<uint64_t>(st.st_size);
  if (out_size) *out_size = size;
  if (size == 0) {
    close(fd);
    return nullptr;
  }
  int prot = writable ? (PROT_READ | PROT_WRITE) : PROT_READ;
  void* ptr = mmap(nullptr, size, prot, MAP_SHARED, fd, 0);
  close(fd);
  return ptr == MAP_FAILED ? nullptr : ptr;
}

int rtpu_shm_unmap(void* ptr, uint64_t size) { return munmap(ptr, size); }

// Unlink the name. Live mappings stay valid until unmapped (kernel refcount),
// which is exactly the GC semantics the ownership table relies on.
int rtpu_shm_unlink(const char* name) { return shm_unlink(name); }

// memcpy exposed for one-shot puts of already-materialized buffers.
int rtpu_shm_put(const char* name, const void* data, uint64_t size) {
  void* ptr = rtpu_shm_create(name, size ? size : 1);
  if (!ptr) return -1;
  if (size) memcpy(ptr, data, size);
  munmap(ptr, size ? size : 1);
  return 0;
}

int rtpu_errno() { return errno; }

}  // extern "C"
