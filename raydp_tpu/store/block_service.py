"""Per-host block service: blocks survive the executor that wrote them.

The reference avoids re-computing shuffle output on executor loss with
``RayExternalShuffleService`` (PAPER.md L3) — a per-node block server that
owns shuffle blocks independent of executor lifetime. This module is that
role for the native runtime: one :class:`BlockService` actor per shared-
memory namespace (= per host; every virtual node on one machine shares
/dev/shm), forked warm from the node's zygote like any light actor, whose
actor id is the OWNER of record for completed ETL/shuffle blocks.

The handoff is an ownership transfer of the existing segment — zero-copy
and zero extra RPCs. An executor's block registration (the PR 3
``batched_registration`` frame) carries a ``handoff`` flag; the head, which
knows actor liveness authoritatively, records the namespace's live block
service as the owner instead of the executor. Nothing moves: the segment
stays exactly where the executor wrote it, readers keep mapping shm
directly, and the registration reply tells the writer the effective owner
so its location cache (and the metas it pushes to peers) stay truthful.

What this buys (docs/fault_tolerance.md "Ownership tiers"):

- executor SIGKILL no longer loses blocks — the owner of record is alive,
  so nothing is unregistered, reads keep hitting shm, and lineage recovery
  (PR 8) demotes from the common path to the fallback;
- ``kill_executors`` scale-in skips the best-effort ``object_reown_all``
  sweep entirely (the blocks were never executor-owned);
- the lease-stamped head-bypass location cache never goes stale on
  executor death (the cached owner is the service, which is still alive);
- remote fetches get a first-class owner to talk to: the head advertises
  a live service's TCP socket as ``service_addr`` in location records, and
  the store's fetch path prefers it (with the jittered-backoff retry
  ladder in ``object_store._fetch_chunk`` riding out service restarts).

The service itself is deliberately STATELESS: segments live in /dev/shm
and ownership lives at the head, so a crash-restart (same actor identity,
``max_restarts``) loses nothing. An intentional kill (chaos, session stop)
is real loss — the head's owner-death path tombstones and unlinks every
service-owned block, and readers fall back to lineage re-execution.

``store.block_service`` session conf (default ON); OFF restores the PR 8
executor-owned behavior byte-for-byte (the A/B parity arm).
"""

from __future__ import annotations

from typing import Optional

BLOCK_SERVICE_SUFFIX = "_BLOCK_SERVICE"


class BlockService:
    """The per-host block-server actor. Owns completed blocks in the head's
    metadata table and serves their bytes to remote readers; holds no block
    state of its own (see module docstring — restart must be free)."""

    def __init__(self, app_name: str = ""):
        self.app_name = app_name
        import threading

        from raydp_tpu.sanitize import named_lock

        self._lock = named_lock("store.block_service", threading.Lock())
        self._stats = {"fetches": 0, "bytes_served": 0}  # guarded-by: self._lock

    def ping(self) -> str:
        return "pong"

    def block_fetch(self, shm_name: str, offset: int = 0, length: int = -1) -> bytes:
        """Serve a local block's bytes (either tier: shm segment or spill
        file) to a remote reader — the same primitive the head and node
        agents expose, now answered by the blocks' owner of record."""
        from raydp_tpu import obs
        from raydp_tpu.cluster.common import serve_block_bytes

        with obs.span("block_service.fetch", shm_name=shm_name):
            data = serve_block_bytes(shm_name, offset, length)
        obs.metrics.counter("block_service.fetches").inc()
        obs.metrics.counter("block_service.bytes_served").inc(len(data))
        with self._lock:
            self._stats["fetches"] += 1
            self._stats["bytes_served"] += len(data)
        from raydp_tpu.obs import flush_throttled

        flush_throttled(2.0)
        return data

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)


def service_block_fetch(
    addr: str, shm_name: str, offset: int, length: int,
    timeout: float = 300.0,
) -> bytes:
    """One ranged ``block_fetch`` against a BlockService ACTOR socket.
    Actors speak the 4-tuple method frame (worker.py), not the head/agent
    2-tuple op frame — this is the store's client for ``service_addr``
    location records."""
    from raydp_tpu.cluster.common import (
        connect,
        recv_frame,
        send_frame,
        traced_request,
    )

    with connect(addr, timeout) as sock:
        send_frame(
            sock,
            traced_request(
                ("block_fetch", (shm_name, offset, length), {}, False)
            ),
        )
        status, value = recv_frame(sock)
    if status == "ok":
        return value
    raise value


def service_for_namespace(shm_ns: str = "", tenant: str = "") -> Optional[str]:
    """The actor id of the block service registered for a shared-memory
    namespace — the ``tenant``-scoped entry first, the namespace's tenant-
    less fallback second (None when that host runs without one —
    registrations there keep executor ownership and rely on lineage, the
    PR 8 behavior)."""
    from raydp_tpu.cluster import api as cluster_api

    return cluster_api.head_rpc(
        "block_service_lookup", shm_ns=shm_ns, tenant=tenant
    )


def register_service(actor_id: str, tenant: str = "") -> str:
    """Record a spawned BlockService actor as its node namespace's owner of
    record at the head (scoped to ``tenant`` when given, so one session's
    service never adopts — or tombstones, at stop — another tenant's
    blocks); returns the namespace it now serves."""
    from raydp_tpu.cluster import api as cluster_api

    return cluster_api.head_rpc(
        "block_service_register", actor_id=actor_id, tenant=tenant
    )


def deregister_service(actor_id: str) -> bool:
    """Drop a service from the head's owner-kind table WITHOUT killing it:
    registrations fall back to executor ownership (the A/B toggle the
    bench's two-tier recovery probe flips mid-session)."""
    from raydp_tpu.cluster import api as cluster_api

    return cluster_api.head_rpc("block_service_unregister", actor_id=actor_id)
