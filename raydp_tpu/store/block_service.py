"""Per-host block service: blocks survive the executor that wrote them.

The reference avoids re-computing shuffle output on executor loss with
``RayExternalShuffleService`` (PAPER.md L3) — a per-node block server that
owns shuffle blocks independent of executor lifetime. This module is that
role for the native runtime: one :class:`BlockService` actor per shared-
memory namespace (= per host; every virtual node on one machine shares
/dev/shm), forked warm from the node's zygote like any light actor, whose
actor id is the OWNER of record for completed ETL/shuffle blocks.

The handoff is an ownership transfer of the existing segment — zero-copy
and zero extra RPCs. An executor's block registration (the PR 3
``batched_registration`` frame) carries a ``handoff`` flag; the head, which
knows actor liveness authoritatively, records the namespace's live block
service as the owner instead of the executor. Nothing moves: the segment
stays exactly where the executor wrote it, readers keep mapping shm
directly, and the registration reply tells the writer the effective owner
so its location cache (and the metas it pushes to peers) stay truthful.

What this buys (docs/fault_tolerance.md "Ownership tiers"):

- executor SIGKILL no longer loses blocks — the owner of record is alive,
  so nothing is unregistered, reads keep hitting shm, and lineage recovery
  (PR 8) demotes from the common path to the fallback;
- ``kill_executors`` scale-in skips the best-effort ``object_reown_all``
  sweep entirely (the blocks were never executor-owned);
- the lease-stamped head-bypass location cache never goes stale on
  executor death (the cached owner is the service, which is still alive);
- remote fetches get a first-class owner to talk to: the head advertises
  a live service's TCP socket as ``service_addr`` in location records, and
  the store's fetch path prefers it (with the jittered-backoff retry
  ladder in ``object_store._fetch_chunk`` riding out service restarts).

Since ISSUE 18 the service is also the cluster's cross-host DATA PLANE
(docs/cluster.md "Multi-host topology"):

- ``block_fetch_raw`` streams a block range zero-copy: the actor serve
  loop mmaps the segment (``common.serve_block_view``) and sendall()s the
  pages straight onto the socket — no pickle, no intermediate copy — and
  the client side receives with ``recv_into`` directly into the caller's
  destination buffer, so a fetched block lands as a mapped ``pa.Buffer``
  with exactly one wire copy end to end;
- ``service_block_fetch`` runs over a small per-process CONNECTION POOL
  (idle timeout + liveness probe) instead of a fresh TCP handshake per
  ranged read; ``object_store._remote_fetch`` issues multi-chunk reads in
  parallel over it;
- ``block_put`` accepts a remote writer's block and hosts it on THIS
  host — the third storage tier (``spill-to-remote``) the store escalates
  to when local shm is full and ``mem.pressure`` is high.

The service itself is deliberately STATELESS: segments live in /dev/shm
and ownership lives at the head, so a crash-restart (same actor identity,
``max_restarts``) loses nothing. An intentional kill (chaos, session stop)
is real loss — the head's owner-death path tombstones and unlinks every
service-owned block, and readers fall back to lineage re-execution.

``store.block_service`` session conf (default ON); OFF restores the PR 8
executor-owned behavior byte-for-byte (the A/B parity arm).
"""

from __future__ import annotations

import os
import select
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

BLOCK_SERVICE_SUFFIX = "_BLOCK_SERVICE"

STREAM_FETCH_ENV = "RAYDP_TPU_STREAM_FETCH"
POOL_SIZE_ENV = "RAYDP_TPU_FETCH_POOL"
POOL_IDLE_ENV = "RAYDP_TPU_FETCH_POOL_IDLE_S"


class BlockService:
    """The per-host block-server actor. Owns completed blocks in the head's
    metadata table and serves their bytes to remote readers; holds no block
    state of its own (see module docstring — restart must be free)."""

    def __init__(self, app_name: str = ""):
        self.app_name = app_name
        import threading

        from raydp_tpu.sanitize import named_lock

        self._lock = named_lock("store.block_service", threading.Lock())
        self._stats = {"fetches": 0, "bytes_served": 0, "puts": 0, "bytes_put": 0}  # guarded-by: self._lock

    def ping(self) -> str:
        return "pong"

    def block_fetch(self, shm_name: str, offset: int = 0, length: int = -1) -> bytes:
        """Serve a local block's bytes (either tier: shm segment or spill
        file) to a remote reader — the same primitive the head and node
        agents expose, now answered by the blocks' owner of record."""
        from raydp_tpu import obs
        from raydp_tpu.cluster.common import serve_block_bytes

        with obs.span("block_service.fetch", shm_name=shm_name):
            data = serve_block_bytes(shm_name, offset, length)
        self._count_fetch(len(data))
        return data

    def block_fetch_raw(self, shm_name: str, offset: int = 0, length: int = -1):
        """Streaming variant: return a :class:`common.RawView` over an mmap
        of the block range. The worker serve loop streams it onto the
        socket unpickled (``("raw", size)`` header + raw bytes) — the
        zero-copy half of the cross-host data plane."""
        from raydp_tpu import obs
        from raydp_tpu.cluster.common import serve_block_view

        with obs.span("block_service.fetch", shm_name=shm_name, raw=True):
            raw = serve_block_view(shm_name, offset, length)
        self._count_fetch(raw.size)
        return raw

    def block_put(self, object_id: str, payload: bytes, storage: str = "auto") -> dict:
        """Host a REMOTE writer's block on this service's host and register
        it under this actor's ownership — the spill-to-remote tier. The
        writer's local shm was full (``_should_spill``) and under memory
        pressure; rather than its own disk, the bytes land in a peer host's
        shm where readers reach them through the normal service fetch path.
        Returns the meta view the writer should cache as the location."""
        from raydp_tpu import obs
        from raydp_tpu.cluster import api as cluster_api
        from raydp_tpu.cluster.common import host_id, shm_namespace
        from raydp_tpu.cluster.worker import current_context
        from raydp_tpu.store import object_store as store

        payload = bytes(payload)
        with obs.span("block_service.put", object_id=object_id, n=len(payload)):
            shm_name = store.host_block_locally(object_id, payload, storage=storage)
            ctx = current_context()
            owner = ctx.actor_id if ctx is not None else store.current_owner()
            node_id = (ctx.node_id if ctx is not None else "") or "driver"
            cluster_api.head_rpc(
                "object_put", object_id=object_id, owner=owner,
                shm_name=shm_name, size=len(payload), node_id=node_id,
                shm_ns=shm_namespace(),
            )
        obs.metrics.counter("block_service.remote_puts").inc()
        with self._lock:
            self._stats["puts"] += 1
            self._stats["bytes_put"] += len(payload)
        from raydp_tpu.obs import flush_throttled

        flush_throttled(2.0)
        return {
            "object_id": object_id, "owner": owner, "shm_name": shm_name,
            "size": len(payload), "node_id": node_id,
            "shm_ns": shm_namespace(), "host": host_id(),
        }

    def _count_fetch(self, n: int) -> None:
        from raydp_tpu import obs

        obs.metrics.counter("block_service.fetches").inc()
        obs.metrics.counter("block_service.bytes_served").inc(n)
        with self._lock:
            self._stats["fetches"] += 1
            self._stats["bytes_served"] += n
        from raydp_tpu.obs import flush_throttled

        flush_throttled(2.0)

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)


# ---------------------------------------------------------------------------
# pooled streaming client
#
# One small per-process pool of actor-protocol connections, keyed by
# service address. A shuffle reduce fetches hundreds of ranged chunks from
# the same few services; a TCP handshake (and token round-trip) per chunk
# was measurable drag and file-descriptor churn. Entries carry an idle
# stamp (pruned past RAYDP_TPU_FETCH_POOL_IDLE_S) and are liveness-probed
# before reuse: the service never sends unsolicited bytes, so a readable
# pooled socket can only mean EOF/RST — a restarted or dead peer — and is
# dropped instead of reused. Errors mid-call close the socket rather than
# returning it (a half-consumed reply must never leak to the next caller).
# ---------------------------------------------------------------------------

def _pool_size() -> int:
    try:
        return max(1, int(os.environ.get(POOL_SIZE_ENV, "4")))
    except ValueError:
        return 4


def _pool_idle_s() -> float:
    try:
        return float(os.environ.get(POOL_IDLE_ENV, "30"))
    except ValueError:
        return 30.0


def _stream_fetch_enabled() -> bool:
    return os.environ.get(STREAM_FETCH_ENV, "1").lower() not in ("0", "false", "no")


class _ServicePool:
    def __init__(self):
        from raydp_tpu.sanitize import named_lock

        self._lock = named_lock("store.service_pool", threading.Lock())
        self._idle: Dict[str, List[Tuple[socket.socket, float]]] = {}  # guarded-by: self._lock
        self.stats = {  # guarded-by: self._lock
            "connections_opened": 0,
            "reuses": 0,
            "evicted_idle": 0,
            "evicted_stale": 0,
        }

    def acquire(self, addr: str, timeout: float) -> socket.socket:
        now = time.monotonic()
        idle_cut = now - _pool_idle_s()
        stale: List[socket.socket] = []
        sock: Optional[socket.socket] = None
        with self._lock:
            entries = self._idle.get(addr, [])
            while entries:
                cand, stamp = entries.pop()
                if stamp < idle_cut:
                    stale.append(cand)
                    self.stats["evicted_idle"] += 1
                    continue
                # liveness probe: readable ⇒ the peer closed (or spoke out
                # of turn — equally unusable); select on a connected TCP/UDS
                # socket with zero timeout is just a poll syscall
                try:
                    readable, _, _ = select.select([cand], [], [], 0)
                except (OSError, ValueError):
                    readable = [cand]
                if readable:
                    stale.append(cand)
                    self.stats["evicted_stale"] += 1
                    continue
                sock = cand
                self.stats["reuses"] += 1
                break
        for dead in stale:
            try:
                dead.close()
            except OSError:  # raydp-lint: disable=swallowed-exceptions (already dead)
                pass
        if sock is not None:
            sock.settimeout(timeout)
            return sock
        from raydp_tpu.cluster.common import connect

        sock = connect(addr, timeout)
        with self._lock:
            self.stats["connections_opened"] += 1
        return sock

    def release(self, addr: str, sock: socket.socket) -> None:
        now = time.monotonic()
        evict: Optional[socket.socket] = None
        with self._lock:
            entries = self._idle.setdefault(addr, [])
            if len(entries) >= _pool_size():
                evict = entries.pop(0)[0]
            entries.append((sock, now))
        if evict is not None:
            try:
                evict.close()
            except OSError:  # raydp-lint: disable=swallowed-exceptions (eviction is best-effort)
                pass

    def discard(self, sock: socket.socket) -> None:
        try:
            sock.close()
        except OSError:  # raydp-lint: disable=swallowed-exceptions (already closed)
            pass

    def close_all(self) -> None:
        with self._lock:
            entries = [s for lst in self._idle.values() for s, _ in lst]
            self._idle.clear()
        for sock in entries:
            try:
                sock.close()
            except OSError:  # raydp-lint: disable=swallowed-exceptions (teardown)
                pass

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self.stats)
            out["idle"] = sum(len(v) for v in self._idle.values())
            return out


_pool = _ServicePool()


def service_pool_stats() -> dict:
    """Pool counters for tests and the observatory (connections_opened is
    the regression signal: N sequential fetches to one service must not
    open N sockets)."""
    return _pool.snapshot()


def close_service_pool() -> None:
    """Drop every pooled connection (cluster shutdown / fork hygiene)."""
    _pool.close_all()


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    got, n = 0, len(view)
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed connection mid-stream")
        got += r


def _service_call(sock, method: str, args: tuple, into: Optional[memoryview]):
    """One request/reply on an established actor-protocol connection.
    Returns (bytes_like_or_len, raw_used)."""
    from raydp_tpu.cluster.common import (
        recv_frame,
        send_frame,
        traced_request,
    )

    send_frame(sock, traced_request((method, args, {}, False)))
    status, value = recv_frame(sock)
    if status == "raw":
        size = int(value)
        if into is not None:
            if size != len(into):
                # drain to keep the stream coherent, then fail loudly
                _recv_exact_into(sock, memoryview(bytearray(size)))
                raise ConnectionError(
                    f"raw block reply size {size} != expected {len(into)}"
                )
            _recv_exact_into(sock, into)
            return size, True
        buf = bytearray(size)
        _recv_exact_into(sock, memoryview(buf))
        return bytes(buf), True
    if status == "ok":
        if into is not None:
            data = memoryview(value)
            if len(data) != len(into):
                raise ConnectionError(
                    f"block reply size {len(data)} != expected {len(into)}"
                )
            into[:] = data
            return len(data), False
        return value, False
    # application-level error, shipped in a fully-consumed reply frame: the
    # connection is still coherent. Tag it so the pool RELEASES instead of
    # discarding — FileNotFoundError (segment gone) is an OSError subclass
    # and would otherwise be mistaken for a transport failure.
    try:
        value._raydp_stream_clean = True
    except (AttributeError, TypeError):  # raydp-lint: disable=swallowed-exceptions (tag is best-effort)
        pass
    raise value


def service_block_fetch(
    addr: str, shm_name: str, offset: int, length: int,
    timeout: float = 300.0, into: Optional[memoryview] = None,
):
    """One ranged ``block_fetch`` against a BlockService ACTOR socket over
    the pooled streaming transport. Actors speak the 4-tuple method frame
    (worker.py), not the head/agent 2-tuple op frame — this is the store's
    client for ``service_addr`` location records.

    With ``into`` the bytes land directly in the caller's buffer (parallel
    chunked fetch assembles one destination with no join copy) and the byte
    count is returned; without it a bytes object is returned."""
    method = "block_fetch_raw" if _stream_fetch_enabled() else "block_fetch"
    sock = _pool.acquire(addr, timeout)
    try:
        try:
            result, _ = _service_call(
                sock, method, (shm_name, offset, length), into
            )
        except AttributeError:
            # pre-ISSUE-18 service without block_fetch_raw: the error reply
            # leaves the stream clean, so fall back on the same connection
            result, _ = _service_call(
                sock, "block_fetch", (shm_name, offset, length), into
            )
    except OSError as exc:
        if getattr(exc, "_raydp_stream_clean", False):
            _pool.release(addr, sock)  # app error in OSError clothing
        else:
            _pool.discard(sock)
        raise
    except BaseException:
        # application-level error (e.g. FileNotFoundError pickled by the
        # service): the reply was fully consumed, the connection is clean
        _pool.release(addr, sock)
        raise
    else:
        _pool.release(addr, sock)
    return result


def service_block_put(
    addr: str, object_id: str, payload: bytes, storage: str = "auto",
    timeout: float = 300.0,
) -> dict:
    """Ship a block to a peer host's service (the spill-to-remote tier
    writer side) over the pooled transport; returns the registered meta."""
    sock = _pool.acquire(addr, timeout)
    try:
        result, _ = _service_call(
            sock, "block_put", (object_id, bytes(payload), storage), None
        )
    except OSError as exc:
        if getattr(exc, "_raydp_stream_clean", False):
            _pool.release(addr, sock)
        else:
            _pool.discard(sock)
        raise
    except BaseException:
        _pool.release(addr, sock)
        raise
    else:
        _pool.release(addr, sock)
    return result


def service_for_namespace(shm_ns: str = "", tenant: str = "") -> Optional[str]:
    """The actor id of the block service registered for a shared-memory
    namespace — the ``tenant``-scoped entry first, the namespace's tenant-
    less fallback second (None when that host runs without one —
    registrations there keep executor ownership and rely on lineage, the
    PR 8 behavior)."""
    from raydp_tpu.cluster import api as cluster_api

    return cluster_api.head_rpc(
        "block_service_lookup", shm_ns=shm_ns, tenant=tenant
    )


def service_peers(exclude_host: str = "") -> list:
    """Live block services on OTHER hosts, as ``{actor_id, shm_ns, host,
    service_addr}`` rows — the spill-to-remote tier's target list."""
    from raydp_tpu.cluster import api as cluster_api

    peers = cluster_api.head_rpc("block_service_peers") or []
    return [p for p in peers if p.get("host", "") != exclude_host]


def register_service(actor_id: str, tenant: str = "") -> str:
    """Record a spawned BlockService actor as its node namespace's owner of
    record at the head (scoped to ``tenant`` when given, so one session's
    service never adopts — or tombstones, at stop — another tenant's
    blocks); returns the namespace it now serves."""
    from raydp_tpu.cluster import api as cluster_api

    return cluster_api.head_rpc(
        "block_service_register", actor_id=actor_id, tenant=tenant
    )


def deregister_service(actor_id: str) -> bool:
    """Drop a service from the head's owner-kind table WITHOUT killing it:
    registrations fall back to executor ownership (the A/B toggle the
    bench's two-tier recovery probe flips mid-session)."""
    from raydp_tpu.cluster import api as cluster_api

    return cluster_api.head_rpc("block_service_unregister", actor_id=actor_id)
