"""Object store: shared-memory blocks with ownership semantics.

The exchange currency of the framework is the Arrow IPC stream block, exactly
as in the reference (SURVEY.md L5; wire format at ObjectStoreWriter.scala:55-85)
— but the store is native /dev/shm segments (C++, see native/store.cpp) instead
of Ray's plasma, and ownership lives in the head process:

- every object has an *owner* (an actor id, or the driver); when the owner
  dies, un-transferred objects are GC'd and reads raise ``OwnerDiedError``
  (parity: test_fail_without_data_ownership_transfer,
  reference test_data_owner_transfer.py:33-77);
- ``transfer()`` re-assigns ownership (to e.g. a long-lived holder actor) so
  data outlives the ETL engine that produced it (parity: _use_owner path,
  reference dataset.py:157-171, ObjectStoreWriter.scala:64-85).

Reads are zero-copy: the mapped segment is exposed to pyarrow as a foreign
buffer feeding ``ipc.open_stream`` directly.

Two storage tiers (parity: the reference's storage-level persist,
ObjectStoreWriter.scala:229-231): /dev/shm segments (fast path) and a DISK
spill tier (``<session>/spill/rtpu-*`` files, mmap'd on read). Writes spill
automatically when shm is near-full (or the ``RAYDP_TPU_SHM_CAPACITY`` cap is
exceeded) — a dataset larger than shm degrades to memory-and-disk instead of
failing. ``storage="disk"`` forces the spill tier (DISK_ONLY semantics).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import uuid
from dataclasses import dataclass
from typing import List, Optional, Sequence

from raydp_tpu.cluster import api as cluster_api
from raydp_tpu.cluster.common import (
    DRIVER_OWNER,
    ClusterError,
    OwnerDiedError,
    object_meta_entry,
    rpc,
    shm_namespace,
    unlink_block,
)

# observability: cross-node pulls vs local zero-copy maps (tests assert the
# pull path actually ran in multi-node scenarios)
stats = {"remote_fetches": 0, "remote_bytes": 0}

# ---------------------------------------------------------------------------
# head-bypass location cache
#
# Every registration already knows the full location record it sends to the
# head (object_meta_entry), so the writer caches it locally; readers resolve
# through the cache first and only RPC the head for misses. Compiled-plan
# dispatches additionally PUSH lease-stamped entries with the task specs
# (ReadSpec.metas), so a reducer resolving sibling map outputs — blocks it
# never wrote — still skips the head on the warm path. Entries are
# lease-stamped: expired entries take the head miss path, and a read that
# finds a cache-served segment gone retries once through the head (which is
# authoritative for deletion and owner-death, so OwnerDiedError semantics
# survive the bypass).
# ---------------------------------------------------------------------------

LOCATION_LEASE_ENV = "RAYDP_TPU_LOCATION_LEASE_S"
_LOCATION_CACHE_CAP = 8192
_location_enabled = True
_location_cache: dict = {}  # object_id -> (meta, stamp, lease_s); guarded-by: _location_lock


def default_location_lease_s() -> float:
    import os as _os

    try:
        return float(_os.environ.get(LOCATION_LEASE_ENV, "") or 120.0)
    except ValueError:
        return 120.0


def set_location_cache(enabled: bool) -> None:
    """Session-conf toggle (``planner.head_bypass``): off = every lookup is
    a head RPC, the pre-cache behavior (the A/B parity path)."""
    global _location_enabled
    _location_enabled = bool(enabled)
    if not enabled:
        with _location_lock:
            _location_cache.clear()


# ---------------------------------------------------------------------------
# block-service handoff (store/block_service.py, docs/fault_tolerance.md)
#
# With ``store.block_service`` on (session default), an ACTOR's block
# registrations are flagged for handoff: the head records the namespace's
# live per-host block service as the owner instead of this executor — an
# ownership transfer of the existing segment, zero-copy, riding the same
# (batched) registration frame. The reply names the effective owner so the
# writer's cached location (and the metas it pushes with task results)
# carry the service, keeping the head-bypass cache truthful across
# executor death.
#
# The MODULE default is off: only processes the ETL plane configures —
# executors (via their configs dict) and the session driver — participate.
# SPMD rank actors, holder actors, and standalone store users keep
# self-ownership exactly as before.
# ---------------------------------------------------------------------------

_block_service_on = False


def set_block_service(enabled: bool) -> None:
    """Session-conf toggle (``store.block_service``): off = executors own
    their blocks, the PR 8 behavior (lineage recovers on executor death) —
    the A/B parity arm."""
    global _block_service_on
    _block_service_on = bool(enabled)


def block_service_enabled() -> bool:
    return _block_service_on


def _adopt_owner(object_id: str, owner: str) -> None:
    """The head reassigned a handoff registration to the block service:
    patch this process's cached location so reads (and the pushed
    ReadSpec.metas built from ``local_meta``) name the LIVE owner, not the
    executor that happened to write the bytes."""
    from raydp_tpu.obs import metrics

    metrics.counter("block_service.handoffs").inc()
    with _location_lock:
        entry = _location_cache.get(object_id)
        if entry is not None:
            entry[0]["owner"] = owner


def cache_location(
    object_id: str, meta: dict, stamp: Optional[float] = None,
    lease_s: Optional[float] = None,
) -> None:
    import time as _time

    if not _location_enabled:
        return
    with _location_lock:
        if len(_location_cache) >= _LOCATION_CACHE_CAP:
            # FIFO eviction: dict order is insertion order
            for old in list(_location_cache)[: _LOCATION_CACHE_CAP // 8]:
                del _location_cache[old]
        _location_cache[object_id] = (
            dict(meta),
            _time.monotonic() if stamp is None else stamp,
            default_location_lease_s() if lease_s is None else lease_s,
        )


def cached_location(object_id: str) -> Optional[dict]:
    """A lease-fresh, locally-usable location record, or None (miss path).
    The returned dict is marked ``cached`` so readers know a mapping failure
    should retry through the head instead of raising."""
    import time as _time

    if not _location_enabled:
        return None
    with _location_lock:
        entry = _location_cache.get(object_id)
    if entry is None:
        return None
    meta, stamp, lease_s = entry
    if _time.monotonic() - stamp > lease_s:
        return None  # lease expired: authoritative path
    if meta.get("shm_ns", "") != shm_namespace() and not meta.get("fetch_addr"):
        return None  # foreign block with no pull address: must ask the head
    out = dict(meta)
    out["cached"] = True
    return out


def evict_location(object_id: str) -> None:
    with _location_lock:
        _location_cache.pop(object_id, None)


# ---------------------------------------------------------------------------
# dead-owner registry (head-bypass stale-location fast path)
#
# A cache-served read that finds its segment gone cannot tell "deleted /
# moved" (retry through the head — it may have been lineage-rebound) from
# "owner is dead" (the head would just raise OwnerDiedError). This process
# REMEMBERS owners it has seen die — from head OwnerDiedError replies and
# from the session's own intentional executor kills — so the stale-location
# path raises OwnerDiedError immediately and lineage recovery triggers
# without a wasted head round trip. Bounded; cleared with the cache toggle.
# ---------------------------------------------------------------------------

import collections as _collections  # noqa: E402

_DEAD_OWNER_CAP = 1024
_dead_owners: "_collections.OrderedDict" = _collections.OrderedDict()  # guarded-by: _location_lock


def note_owner_dead(owner: Optional[str]) -> None:
    """Record that ``owner``'s objects are gone for good (fed by head
    OwnerDiedError replies and by intentional executor kills)."""
    if not owner or owner == DRIVER_OWNER:
        return
    with _location_lock:
        _dead_owners[owner] = True
        _dead_owners.move_to_end(owner)
        while len(_dead_owners) > _DEAD_OWNER_CAP:
            _dead_owners.popitem(last=False)


def owner_known_dead(owner: Optional[str]) -> bool:
    if not owner:
        return False
    with _location_lock:
        return owner in _dead_owners


def _note_dead_owner_from(exc: BaseException) -> None:
    note_owner_dead(getattr(exc, "owner", None))


# ids THIS process deliberately deleted (bounded): lineage recovery refuses
# to resurrect them at depth 0 — "deleted" must stay deleted. Keyed locally
# (not by head tombstone absence) so a mass owner-death that overflows the
# head's tombstone table can never be misread as deletion and refused.
_RECENT_DELETE_CAP = 8192
_recent_deletes: "_collections.OrderedDict" = _collections.OrderedDict()  # guarded-by: _location_lock


def _note_deleted(object_ids) -> None:
    with _location_lock:
        for oid in object_ids:
            _recent_deletes[oid] = True
            _recent_deletes.move_to_end(oid)
        while len(_recent_deletes) > _RECENT_DELETE_CAP:
            _recent_deletes.popitem(last=False)


def was_deleted_here(object_id: str) -> bool:
    with _location_lock:
        return object_id in _recent_deletes


def seed_locations(entries: dict) -> None:
    """Adopt lease-stamped entries pushed with a task's ReadSpecs:
    ``{object_id: (meta, age_s)}`` where ``age_s`` is how old the entry
    already was when the DRIVER shipped it (monotonic clocks don't compare
    across processes, so the wire format carries age, not a timestamp)."""
    import time as _time

    now = _time.monotonic()
    for object_id, (meta, age_s) in entries.items():
        cache_location(object_id, meta, stamp=now - max(0.0, float(age_s)))

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libraydp_store.so")
from raydp_tpu import sanitize as _sanitize

_lib_lock = _sanitize.named_lock("store._lib_lock", threading.Lock())
_lib: Optional[ctypes.CDLL] = None  # guarded-by: _lib_lock
_location_lock = _sanitize.named_lock("store.location_cache", threading.Lock())


def _load_native() -> ctypes.CDLL:
    """Load (building if needed) the native store library. Cross-process safe:
    the build is guarded by an flock and renames atomically into place."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            import fcntl

            lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
            with open(lock_path, "w") as lock_file:
                fcntl.flock(lock_file, fcntl.LOCK_EX)
                if not os.path.exists(_LIB_PATH):
                    # raydp-lint: disable=blocking-under-lock (one-time lazy
                    # build of the native store: every caller needs the
                    # library before it can do anything, releasing the lock
                    # would only let threads race duplicate compiles, and
                    # this path takes no other lock — no inversion possible)
                    subprocess.run(
                        ["sh", os.path.join(_NATIVE_DIR, "build.sh")],
                        check=True,
                        capture_output=True,
                    )
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as exc:
            if "undefined symbol" not in str(exc):
                raise
            # a libraydp_store.so built without -lrt resolves shm_* only in
            # processes where librt is already mapped (full interpreters
            # load it via numpy/jax deps; cold python -S actors don't) —
            # preload it globally and retry before giving up
            ctypes.CDLL("librt.so.1", mode=ctypes.RTLD_GLOBAL)
            lib = ctypes.CDLL(_LIB_PATH)
        lib.rtpu_shm_create.restype = ctypes.c_void_p
        lib.rtpu_shm_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rtpu_shm_finalize.restype = ctypes.c_int
        lib.rtpu_shm_finalize.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rtpu_shm_map.restype = ctypes.c_void_p
        lib.rtpu_shm_map.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int,
        ]
        lib.rtpu_shm_unmap.restype = ctypes.c_int
        lib.rtpu_shm_unmap.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rtpu_shm_unlink.restype = ctypes.c_int
        lib.rtpu_shm_unlink.argtypes = [ctypes.c_char_p]
        lib.rtpu_shm_put.restype = ctypes.c_int
        lib.rtpu_shm_put.argtypes = [ctypes.c_char_p, ctypes.c_void_p, ctypes.c_uint64]
        lib.rtpu_errno.restype = ctypes.c_int
        _lib = lib
        return lib


def current_owner() -> str:
    """The owner id for objects created by this process: the enclosing actor,
    or the driver sentinel."""
    from raydp_tpu.cluster.worker import current_context

    ctx = current_context()
    return ctx.actor_id if ctx is not None else DRIVER_OWNER


@dataclass(frozen=True)
class ObjectRef:
    """Picklable reference to one stored block."""

    object_id: str
    size: int

    @property
    def shm_name(self) -> str:
        """The segment name in THIS node's namespace — valid for writers
        (who create locally); readers must use the registered shm_name from
        the head, which carries the PRODUCER's namespace."""
        return _local_shm_name(self.object_id)


def _local_shm_name(object_id: str) -> str:
    ns = shm_namespace()
    return f"/rtpu-{ns}-{object_id}" if ns else f"/rtpu-{object_id}"


class _MappedBuffer:
    """Owns an mmap of a segment; keeps it alive for zero-copy consumers.
    ``size`` is the logical object size; ``mapped_size`` the mapping length."""

    def __init__(self, lib: ctypes.CDLL, ptr: int, size: int, mapped_size: Optional[int] = None):
        self._lib = lib
        self.ptr = ptr
        self.size = size
        self.mapped_size = size if mapped_size is None else mapped_size

    def memoryview(self) -> memoryview:
        if self.size == 0:
            return memoryview(b"")
        # route through an arrow foreign buffer so the returned view keeps this
        # mapping alive (ctypes.from_address would dangle after GC → segfault)
        import pyarrow as pa

        return memoryview(pa.foreign_buffer(self.ptr, self.size, base=self))

    def __del__(self):
        try:
            if self.ptr:
                self._lib.rtpu_shm_unmap(ctypes.c_void_p(self.ptr), self.mapped_size)
        except Exception:  # raydp-lint: disable=swallowed-exceptions (__del__ teardown must never raise)
            pass


class WritableBlock:
    """A created-but-unsealed segment writers stream Arrow IPC into directly
    (no staging copy): ``block = create_block(cap); sink = block.arrow_sink();
    ... ; ref = block.seal(owner)``."""

    def __init__(self, object_id: str, capacity: int):
        import mmap as _mmap

        self.object_id = object_id
        self.capacity = capacity
        self._lib = _load_native()
        self._name = _local_shm_name(object_id).encode()
        ptr = self._lib.rtpu_shm_create(self._name, capacity)
        if not ptr:
            raise OSError(
                f"shm create failed (errno={self._lib.rtpu_errno()}) for {capacity} bytes"
            )
        # drop the C++ mapping; writers need a *writable* python-buffer view,
        # which pyarrow only honors through the buffer protocol (mmap)
        self._lib.rtpu_shm_unmap(ctypes.c_void_p(ptr), capacity)
        self._file = open("/dev/shm" + self._name.decode(), "r+b")
        self._mmap = _mmap.mmap(self._file.fileno(), capacity)
        self._sealed = False
        _sanitize.track_block(
            self._name.decode(), "/dev/shm" + self._name.decode()
        )

    def arrow_sink(self):
        """A pyarrow FixedSizeBufferWriter over the raw segment (writes stream
        straight into shared memory; no staging copy)."""
        import pyarrow as pa

        return pa.FixedSizeBufferWriter(pa.py_buffer(self._mmap))

    def writable_view(self) -> memoryview:
        """A writable memoryview over the raw segment, for callers that use
        the block as a long-lived mutable arena (the serve KV cache) rather
        than a seal-once IPC sink. The block stays unsealed; release with
        ``abort()`` when the arena is retired. Living in shm keeps the arena
        visible to the memory-watermark plane (``mem.shm_bytes`` scans
        /dev/shm) and the leak audit."""
        if self._sealed:
            raise ClusterError("block already sealed")
        return memoryview(self._mmap)

    def _close_mapping(self) -> None:
        try:
            self._mmap.close()
        except BufferError:  # raydp-lint: disable=swallowed-exceptions (an arrow sink still holds the buffer; kernel keeps the pages)
            pass  # an arrow sink still holds the buffer; kernel keeps the pages
        self._file.close()

    def seal(self, written: int, owner: Optional[str] = None) -> ObjectRef:
        if self._sealed:
            raise ClusterError("block already sealed")
        if written > self.capacity:
            raise ClusterError(f"wrote {written} past capacity {self.capacity}")
        self._close_mapping()
        if written < self.capacity:
            if self._lib.rtpu_shm_finalize(self._name, written) != 0:
                err = self._lib.rtpu_errno()
                self._lib.rtpu_shm_unlink(self._name)
                self._sealed = True
                raise OSError(f"shm finalize failed (errno={err})")
        ref = ObjectRef(self.object_id, written)
        try:
            _register(ref, owner)
        except BaseException:
            self._lib.rtpu_shm_unlink(self._name)
            self._sealed = True
            raise
        self._sealed = True
        return ref

    def abort(self) -> None:
        if not self._sealed:
            self._close_mapping()
            self._lib.rtpu_shm_unlink(self._name)
            self._sealed = True


def _register(ref: ObjectRef, owner: Optional[str], shm_name: Optional[str] = None) -> None:
    from raydp_tpu.cluster.worker import current_context
    from raydp_tpu.obs import metrics

    metrics.counter("store.blocks_written").inc()
    metrics.counter("store.bytes_written").inc(ref.size)
    if (shm_name or "").startswith("file://"):
        metrics.counter("store.blocks_spilled").inc()
    if cluster_api.is_tcp_client():
        raise ClusterError(
            "tcp:// client processes cannot host object-store blocks (no "
            "block server runs on a client machine, so nothing could ever "
            "serve them); create data through the cluster — e.g. "
            "session.read_parquet / executor-side tasks — or attach by "
            "session dir on the head host"
        )
    ctx = current_context()
    entry = object_meta_entry(
        object_id=ref.object_id,
        owner=owner or current_owner(),
        shm_name=shm_name or ref.shm_name,
        size=ref.size,
        node_id=ctx.node_id if ctx else "driver",
        shm_ns=shm_namespace(),
    )
    # the writer knows the full location record: cache it so this process's
    # own reads (and the compiled-plan dispatches that push it to peers)
    # never ask the head where the block lives
    cache_location(ref.object_id, entry)
    wire = entry
    if _block_service_on and ctx is not None and owner is None:
        # actor-produced block with default self-ownership: flag it for the
        # per-host block-service handoff. The HEAD decides (it knows the
        # service's liveness) and the reply names the effective owner; an
        # explicit owner (ObjectHolder, recovery rebinds with a pinned
        # target) is never second-guessed.
        wire = dict(entry, handoff=True)
    staged = getattr(_register_batch_tls, "stack", None)
    if staged:
        # a batched_registration() scope is active on this thread: stage the
        # entry; ONE object_put_batch frame ships everything at scope exit
        staged[-1].append(wire)
        return
    effective = cluster_api.head_rpc("object_put", **wire)
    if isinstance(effective, str) and effective != entry["owner"]:
        _adopt_owner(ref.object_id, effective)


# ---------------------------------------------------------------------------
# batched metadata registration
# ---------------------------------------------------------------------------

_register_batch_tls = threading.local()


def _flush_register_batch(entries: List[dict]) -> None:
    """Ship staged registrations as one RPC frame; falls back to per-entry
    puts against an older head that lacks the batch handler."""
    if not entries:
        return
    if len(entries) == 1:
        effective = cluster_api.head_rpc("object_put", **entries[0])
        if isinstance(effective, str) and effective != entries[0]["owner"]:
            _adopt_owner(entries[0]["object_id"], effective)
        return
    from raydp_tpu.obs import metrics

    try:
        reassigned = cluster_api.head_rpc("object_put_batch", entries=entries)
        metrics.counter("store.register_batches").inc()
        if isinstance(reassigned, dict):
            # block-service handoff: the head named the effective owner for
            # every reassigned entry — correct the cache in the same frame
            for object_id, owner in reassigned.items():
                _adopt_owner(object_id, owner)
    except ClusterError as exc:
        if "unknown head method" not in str(exc):
            raise
        for entry in entries:
            # an older head has no batch op — and no handoff kwarg (nor a
            # service to adopt): strip the flag so the compat path degrades
            # to executor ownership instead of a TypeError
            cluster_api.head_rpc(
                "object_put",
                **{k: v for k, v in entry.items() if k != "handoff"},
            )


def _discard_staged(entries: List[dict]) -> None:
    """Failure cleanup for a batched-registration scope: some entries MAY
    have registered (partial per-entry fallback, or a batch frame that
    applied but whose reply was lost) — head metadata left pointing at
    locally-unlinked segments would turn later reads into serve failures
    instead of clean not-found errors. Best-effort delete through the head
    FIRST (pops metadata and unlinks registered segments), then unlink
    locally for the never-registered rest."""
    try:
        cluster_api.head_rpc(
            "object_delete", object_ids=[e["object_id"] for e in entries]
        )
    except Exception:
        # head unreachable: metadata dies with the session — counted like
        # _delete_blocks failures so quiet leaks stay visible
        from raydp_tpu.obs import metrics

        metrics.counter("store.delete_failures").inc(len(entries))
    for entry in entries:
        evict_location(entry["object_id"])
        unlink_block(entry["shm_name"])


class batched_registration:
    """Defer this thread's block registrations into ONE ``object_put_batch``
    RPC at scope exit — the metadata side of the shuffle map path (a task
    batch's blocks register in one frame instead of one RPC each). Scopes
    nest (each flushes its own entries). On failure — the scope body raising,
    or the flush itself failing — the staged (never-registered) segments are
    unlinked, matching ``seal()``'s register-failure cleanup."""

    def __enter__(self) -> "batched_registration":
        stack = getattr(_register_batch_tls, "stack", None)
        if stack is None:
            stack = _register_batch_tls.stack = []
        self._entries: List[dict] = []
        stack.append(self._entries)
        return self

    def __exit__(self, exc_type, exc, tb):
        _register_batch_tls.stack.pop()
        if exc_type is not None:
            _discard_staged(self._entries)
            return False
        try:
            _flush_register_batch(self._entries)
        except BaseException:
            _discard_staged(self._entries)
            raise
        return False


# ---------------------------------------------------------------------------
# tenant block namespaces (raydp_tpu.tenancy, docs/multitenancy.md)
#
# Object ids minted by a tenant-scoped writer carry the tenant namespace as
# an id prefix (``<tenant>.<hex16>``): the head attributes bytes/quota per
# tenant from the id alone, lineage records / tombstones / deletion records
# are per-tenant by construction (they are keyed by id), and the block-
# service owner table keys on (shm namespace, tenant) so one tenant's stop
# can never adopt or GC another tenant's blocks. Two scopes compose:
#
# - the PROCESS default (``set_tenant_namespace``) — executors belong to
#   exactly one session, so their whole process writes under that tenant;
# - a THREAD overlay (``tenant_scope``) — the driver hosts many sessions,
#   so each query/conversion wraps its writes in the owning session's scope.
#
# Default empty: unprefixed ids, zero behavior change (the tenancy-off A/B
# arm and every pre-tenancy process).
# ---------------------------------------------------------------------------

_default_tenant_ns = ""
_tenant_tls = threading.local()


def set_tenant_namespace(ns: str) -> None:
    """Process-default tenant namespace for newly minted object ids
    (executors set this from their session configs at spawn)."""
    global _default_tenant_ns
    _default_tenant_ns = ns or ""


class tenant_scope:
    """Thread-scoped tenant namespace overlay (driver-side: one process
    hosts many sessions, so each query's writes ride the owning session's
    scope). Nests; restores the previous overlay on exit."""

    def __init__(self, ns: str):
        self._ns = ns or ""
        self._prev: Optional[str] = None

    def __enter__(self) -> "tenant_scope":
        self._prev = getattr(_tenant_tls, "ns", None)
        _tenant_tls.ns = self._ns
        return self

    def __exit__(self, *exc) -> None:
        if self._prev is None:
            _tenant_tls.ns = ""
            del _tenant_tls.ns
        else:
            _tenant_tls.ns = self._prev


def current_tenant_namespace() -> str:
    ns = getattr(_tenant_tls, "ns", None)
    if ns:
        return ns
    return _default_tenant_ns


def new_object_id() -> str:
    ns = current_tenant_namespace()
    suffix = uuid.uuid4().hex[:16]
    return f"{ns}.{suffix}" if ns else suffix


# ---------------------------------------------------------------------------
# disk spill tier
# ---------------------------------------------------------------------------

SHM_CAPACITY_ENV = "RAYDP_TPU_SHM_CAPACITY"
_SHM_HEADROOM = 64 << 20  # never fill /dev/shm to the last byte


def _spill_dir() -> str:
    """This node's spill directory (under the session/local dir so cluster
    teardown removes it with everything else)."""
    base = os.environ.get("RAYDP_TPU_SESSION")
    if not base:
        try:
            base = cluster_api.session_dir()
        except Exception:
            import tempfile

            base = tempfile.gettempdir()
    d = os.path.join(base, "spill")
    os.makedirs(d, exist_ok=True)
    return d


def _should_spill(capacity: int) -> bool:
    """Spill when the write wouldn't fit shm: under an explicit test/ops cap
    (total bytes of this framework's segments), or within the headroom of the
    real tmpfs free space."""
    cap = int(os.environ.get(SHM_CAPACITY_ENV, "0") or "0")
    if cap:
        try:
            used = sum(
                e.stat().st_size
                for e in os.scandir("/dev/shm")
                if e.name.startswith("rtpu-")
            )
        except OSError:
            used = 0
        return used + capacity > cap
    try:
        st = os.statvfs("/dev/shm")
        return capacity > st.f_bavail * st.f_frsize - _SHM_HEADROOM
    except OSError:
        return False


class _SpillBlock:
    """WritableBlock's disk twin: a plain file in the spill dir, written
    through the same mmap/arrow-sink interface, registered as ``file://``."""

    def __init__(self, object_id: str, capacity: int):
        import mmap as _mmap

        self.object_id = object_id
        self.capacity = capacity
        self.path = os.path.join(_spill_dir(), f"rtpu-{object_id}")
        self._file = open(self.path, "w+b")
        os.ftruncate(self._file.fileno(), max(capacity, 1))
        self._mmap = _mmap.mmap(self._file.fileno(), max(capacity, 1))
        self._sealed = False
        _sanitize.track_block(f"file://{self.path}", self.path, kind="spill")

    def arrow_sink(self):
        import pyarrow as pa

        return pa.FixedSizeBufferWriter(pa.py_buffer(self._mmap))

    def _close_mapping(self) -> None:
        try:
            self._mmap.close()
        except BufferError:  # raydp-lint: disable=swallowed-exceptions (an arrow sink still holds the buffer)
            pass
        self._file.close()

    def seal(self, written: int, owner: Optional[str] = None) -> ObjectRef:
        if self._sealed:
            raise ClusterError("block already sealed")
        if written > self.capacity:
            raise ClusterError(f"wrote {written} past capacity {self.capacity}")
        self._close_mapping()
        os.truncate(self.path, written)
        ref = ObjectRef(self.object_id, written)
        try:
            _register(ref, owner, shm_name=f"file://{self.path}")
        except BaseException:
            try:
                os.unlink(self.path)
            except OSError:  # raydp-lint: disable=swallowed-exceptions (spill file may already be gone)
                pass
            self._sealed = True
            raise
        self._sealed = True
        return ref

    def abort(self) -> None:
        if not self._sealed:
            self._close_mapping()
            try:
                os.unlink(self.path)
            except OSError:  # raydp-lint: disable=swallowed-exceptions (spill file may already be gone)
                pass
            self._sealed = True


_PROXY_CHUNK = 64 << 20  # stay far under the transport's 1 GiB frame cap


def _proxy_put(
    object_id: str, payload: bytes, owner: Optional[str], storage: str = "auto"
) -> None:
    """Ship a tcp client's block to the head, chunked so arbitrarily large
    puts never hit the frame-size cap (the read side chunks the same way).
    ``storage`` forwards the tier request — ``disk`` must mean DISK_ONLY on
    the head too, not wherever the head's own shm pressure happens to be."""
    owner = owner or current_owner()
    if len(payload) <= _PROXY_CHUNK:
        cluster_api.head_rpc(
            "object_put_proxy",
            object_id=object_id,
            payload=payload,
            owner=owner,
            storage=storage,
            timeout=120.0,
        )
        return
    view = memoryview(payload)
    total = -(-len(payload) // _PROXY_CHUNK)
    try:
        for seq in range(total):
            cluster_api.head_rpc(
                "object_put_proxy_chunk",
                object_id=object_id,
                seq=seq,
                payload=bytes(view[seq * _PROXY_CHUNK : (seq + 1) * _PROXY_CHUNK]),
                timeout=120.0,
            )
        cluster_api.head_rpc(
            "object_put_proxy_commit",
            object_id=object_id,
            owner=owner,
            total_chunks=total,
            storage=storage,
            timeout=120.0,
        )
    except BaseException:
        # a failed multi-chunk upload must not pin its partial chunks in head
        # memory until the TTL sweep; best-effort — the head GCs stragglers
        try:
            cluster_api.head_rpc(
                "object_put_proxy_abort", object_id=object_id, timeout=5.0
            )
        except Exception:  # raydp-lint: disable=swallowed-exceptions (abort rpc is best-effort; the TTL sweep GCs the staging)
            pass
        raise


class _ProxyBlock:
    """Writable block for tcp:// client drivers: buffers the Arrow stream
    locally and ships it to the HEAD at seal, which hosts (and serves) the
    bytes on its own node — the analog of ray client proxying ``ray.put``
    through the server (the reference's client-mode tests rely on exactly
    that). Same interface as WritableBlock/_SpillBlock."""

    def __init__(self, object_id: str, capacity: int, storage: str = "auto"):
        import pyarrow as pa

        self.object_id = object_id
        self.capacity = capacity
        self.storage = storage
        self._out = pa.BufferOutputStream()
        self._sealed = False

    def arrow_sink(self):
        return self._out

    def seal(self, written: int, owner: Optional[str] = None) -> ObjectRef:
        if self._sealed:
            raise ClusterError("block already sealed")
        if written > self.capacity:  # same contract as WritableBlock
            raise ClusterError(f"wrote {written} past capacity {self.capacity}")
        buf = self._out.getvalue()
        _proxy_put(
            self.object_id, bytes(memoryview(buf)[:written]), owner,
            storage=self.storage,
        )
        self._sealed = True
        return ObjectRef(self.object_id, written)

    def abort(self) -> None:
        self._sealed = True


class _RemoteBlock:
    """Writable block for the spill-to-remote tier: buffers the Arrow
    stream in anonymous memory and ships it to a PEER host's block service
    at seal (local shm was full and under pressure — see
    ``_spill_remote_target``). The peer's service becomes the owner of
    record; on any shipping failure the bytes fall back to the local disk
    tier, so remote spill is strictly opportunistic. Same interface as
    WritableBlock/_SpillBlock/_ProxyBlock."""

    def __init__(self, object_id: str, capacity: int, peer: dict):
        import pyarrow as pa

        self.object_id = object_id
        self.capacity = capacity
        self.peer = peer
        self._out = pa.BufferOutputStream()
        self._sealed = False

    def arrow_sink(self):
        return self._out

    def seal(self, written: int, owner: Optional[str] = None) -> ObjectRef:
        if self._sealed:
            raise ClusterError("block already sealed")
        if written > self.capacity:
            raise ClusterError(f"wrote {written} past capacity {self.capacity}")
        import pyarrow as pa

        buf = pa.py_buffer(memoryview(self._out.getvalue())[:written])
        self._sealed = True
        try:
            return _put_remote(self.object_id, buf, self.peer)
        except Exception:  # raydp-lint: disable=swallowed-exceptions (remote tier is opportunistic; local disk always works)
            from raydp_tpu.obs import metrics

            metrics.counter("store.remote_spill_failures").inc()
            return _put_spill(self.object_id, buf, owner)

    def abort(self) -> None:
        self._sealed = True


def host_block_locally(
    object_id: str, payload: bytes, spill_dir: Optional[str] = None,
    storage: str = "auto",
) -> str:
    """Write bytes into THIS process's node shm (falling back to the disk
    tier; ``storage="disk"`` forces disk, ``"shm"`` is strict and raises on
    failure — same tier contract as ``put``) WITHOUT registering them — the
    head calls this to host a tcp client's proxied block, then inserts the
    metadata itself. Returns the shm/file name to register."""
    n = len(payload)
    name = _local_shm_name(object_id)
    want_shm = storage == "shm" or (
        storage != "disk" and n and not _should_spill(n)
    )
    if want_shm:
        lib = _load_native()
        # the native layer owns the empty-object invariant (size-0 maps a
        # 1-byte segment, store.cpp); the registered size stays authoritative
        cbuf = (ctypes.c_char * max(n, 1)).from_buffer_copy(payload or b"\0")
        rc = lib.rtpu_shm_put(
            name.encode(), ctypes.cast(cbuf, ctypes.c_void_p), n
        )
        if rc == 0:
            _sanitize.track_block(name, "/dev/shm" + name)
            return name
        if storage == "shm":  # strict tier: no silent downgrade to disk
            raise OSError(f"shm put failed (errno={lib.rtpu_errno()})")
    base = spill_dir or _spill_dir()
    os.makedirs(base, exist_ok=True)
    path = os.path.join(base, f"rtpu-{object_id}")
    with open(path, "wb") as f:
        f.write(payload)
    _sanitize.track_block(f"file://{path}", path, kind="spill")
    return f"file://{path}"


def create_block(capacity: int, storage: str = "auto"):
    """A writable block in the requested tier: "auto" prefers shm and spills
    to disk when shm is (nearly) full, "shm" is strict, "disk" forces the
    spill tier (DISK_ONLY semantics). tcp:// client drivers get a proxy
    block hosted on the head at seal (ray-client put parity)."""
    object_id = new_object_id()
    if cluster_api.is_tcp_client():
        return _ProxyBlock(object_id, capacity, storage)
    if storage == "disk":
        return _SpillBlock(object_id, capacity)
    if storage == "auto" and _should_spill(capacity):
        peer = _spill_remote_target(capacity)
        if peer is not None:
            return _RemoteBlock(object_id, capacity, peer)
        return _SpillBlock(object_id, capacity)
    try:
        return WritableBlock(object_id, capacity)
    except OSError:
        if storage == "shm":
            raise
        return _SpillBlock(object_id, capacity)


def put(data, owner: Optional[str] = None, storage: str = "auto") -> ObjectRef:
    """Store a materialized buffer (bytes / memoryview / arrow Buffer)."""
    import pyarrow as pa

    buf = data if isinstance(data, pa.Buffer) else pa.py_buffer(data)
    object_id = new_object_id()
    if cluster_api.is_tcp_client():
        # proxy through the head (ray-client put parity): the client has no
        # block server, so the head hosts and serves the bytes
        _proxy_put(object_id, bytes(memoryview(buf)), owner, storage=storage)
        return ObjectRef(object_id, buf.size)
    if storage == "disk" or (storage == "auto" and _should_spill(buf.size)):
        if storage == "auto":
            peer = _spill_remote_target(buf.size)
            if peer is not None:
                try:
                    return _put_remote(object_id, buf, peer)
                except Exception:  # raydp-lint: disable=swallowed-exceptions (remote tier is opportunistic; local disk always works)
                    from raydp_tpu.obs import metrics

                    metrics.counter("store.remote_spill_failures").inc()
        return _put_spill(object_id, buf, owner)
    lib = _load_native()
    ref = ObjectRef(object_id, buf.size)
    rc = lib.rtpu_shm_put(
        ref.shm_name.encode(), ctypes.c_void_p(buf.address), buf.size
    )
    if rc != 0:
        if storage == "shm":
            raise OSError(f"shm put failed (errno={lib.rtpu_errno()})")
        return _put_spill(object_id, buf, owner)
    _sanitize.track_block(ref.shm_name, "/dev/shm" + ref.shm_name)
    try:
        _register(ref, owner)
    except BaseException:
        lib.rtpu_shm_unlink(ref.shm_name.encode())
        raise
    return ref


def _put_spill(object_id: str, buf, owner: Optional[str]) -> ObjectRef:
    path = os.path.join(_spill_dir(), f"rtpu-{object_id}")
    with open(path, "wb") as f:
        f.write(memoryview(buf))
    _sanitize.track_block(f"file://{path}", path, kind="spill")
    ref = ObjectRef(object_id, buf.size)
    try:
        _register(ref, owner, shm_name=f"file://{path}")
    except BaseException:
        try:
            os.unlink(path)
        except OSError:  # raydp-lint: disable=swallowed-exceptions (cleanup of a failed spill write)
            pass
        raise
    return ref


# ---------------------------------------------------------------------------
# spill-to-remote: the third storage tier (ISSUE 18)
#
# tier order under "auto": local shm → (under memory pressure, with a peer
# host available) a peer host's shm via its block service → local disk.
# Remote beats disk only when this host is genuinely squeezed — the gate is
# the conjunction of _should_spill (the write doesn't fit shm) and the
# mem.pressure watermark the profiler maintains — so single-host runs and
# unpressured spills keep the exact PR-era disk behavior.
# ---------------------------------------------------------------------------

REMOTE_SPILL_ENV = "RAYDP_TPU_REMOTE_SPILL"
REMOTE_SPILL_PRESSURE_ENV = "RAYDP_TPU_REMOTE_SPILL_PRESSURE"
# a remote spill is one pooled block_put frame; bigger blocks take local disk
_REMOTE_SPILL_MAX = 256 << 20
_PEER_CACHE_TTL_S = 5.0
_peer_cache_lock = _sanitize.named_lock("store.remote_spill_peers", threading.Lock())
_peer_cache: List = [0.0, None]  # guarded-by: _peer_cache_lock


def _remote_spill_enabled() -> bool:
    return os.environ.get(REMOTE_SPILL_ENV, "1").lower() not in ("0", "false", "no")


def _remote_spill_pressure() -> float:
    try:
        return float(os.environ.get(REMOTE_SPILL_PRESSURE_ENV, "0.85"))
    except ValueError:
        return 0.85


def _remote_spill_peer() -> Optional[dict]:
    """A live block service on ANOTHER host (addr + namespace row), or None.
    Cached a few seconds: the spill path must not add a head RPC per block
    while a query churns through a full shm."""
    import time as _time

    from raydp_tpu.cluster.common import host_id

    now = _time.monotonic()
    with _peer_cache_lock:
        stamp, peers = _peer_cache
        if peers is None or now - stamp > _PEER_CACHE_TTL_S:
            peers = ()
            try:
                from raydp_tpu.store.block_service import service_peers

                peers = tuple(
                    p for p in service_peers(exclude_host=host_id())
                    if p.get("service_addr")
                )
            except Exception:  # raydp-lint: disable=swallowed-exceptions (no head / old head: remote tier simply unavailable)
                peers = ()
            _peer_cache[0] = now
            _peer_cache[1] = peers
    return peers[0] if peers else None


def _spill_remote_target(capacity: int) -> Optional[dict]:
    """The peer to remote-spill to, or None ⇒ take the local disk tier."""
    if not _remote_spill_enabled() or capacity > _REMOTE_SPILL_MAX:
        return None
    try:
        from raydp_tpu import obs

        obs.sample_memory()
        pressure = obs.metrics.gauge("mem.pressure").value
    except Exception:  # raydp-lint: disable=swallowed-exceptions (no obs plane: treat as unpressured)
        return None
    if pressure < _remote_spill_pressure():
        return None
    return _remote_spill_peer()


def _put_remote(object_id: str, buf, peer: dict) -> ObjectRef:
    """Ship a block to a peer host's service and adopt the returned meta as
    this process's cached location (owner = the peer service, namespace =
    the peer host's — readers go through the normal remote-fetch path)."""
    from raydp_tpu.cluster.common import host_id, host_label
    from raydp_tpu.obs import metrics
    from raydp_tpu.store.block_service import service_block_put

    payload = bytes(memoryview(buf))
    meta = service_block_put(peer["service_addr"], object_id, payload)
    meta = dict(meta)
    meta.setdefault("service_addr", peer["service_addr"])
    cache_location(object_id, meta)
    metrics.counter("store.blocks_spilled_remote").inc()
    metrics.counter("rpc.bytes_over_wire").inc(len(payload))
    src = host_label(host_id())
    dst = host_label(meta.get("host", "") or meta.get("shm_ns", ""))
    metrics.counter(f"rpc.bytes_over_wire.{src}.{dst}").inc(len(payload))
    return ObjectRef(object_id, len(payload))


def _lookup(ref: ObjectRef, fresh: bool = False) -> dict:
    if not fresh:
        meta = cached_location(ref.object_id)
        if meta is not None:
            from raydp_tpu.obs import metrics

            metrics.counter("rpc.head_bypass_hits").inc()
            return meta
    try:
        meta = cluster_api.head_rpc("object_lookup", object_id=ref.object_id)
    except OwnerDiedError as exc:
        _note_dead_owner_from(exc)
        raise
    if meta is None:
        err = ClusterError(
            f"object {ref.object_id} not found (already deleted?)"
        )
        err.object_ids = [ref.object_id]
        raise err
    cache_location(ref.object_id, meta)
    return meta


def _lookup_batch_rpc(ids: List[str]) -> dict:
    """One head round trip for many ids — the lease-stamped op when the head
    has it (entries enter the cache with the SERVER's lease), the PR 3 batch
    lookup otherwise, per-ref lookups against the oldest heads."""
    try:
        metas = cluster_api.head_rpc("object_lookup_lease", object_ids=ids)
    except OwnerDiedError as exc:
        _note_dead_owner_from(exc)
        raise
    except ClusterError as exc:
        if "unknown head method" not in str(exc):
            raise
        try:
            metas = cluster_api.head_rpc("object_lookup_batch", object_ids=ids)
        except ClusterError as exc2:
            if "unknown head method" not in str(exc2):
                raise
            metas = {
                oid: cluster_api.head_rpc("object_lookup", object_id=oid)
                for oid in ids
            }
    for oid, meta in metas.items():
        if meta is not None:
            cache_location(oid, meta, lease_s=meta.get("lease_s"))
    return metas


def lookup_many(refs: Sequence[ObjectRef]) -> dict:
    """Resolve many refs' metadata: {object_id: meta}. The reduce side of a
    shuffle resolves every input slice's block through this. Warm entries —
    writer-cached, lease entries pushed with the task spec, or previously
    fetched — are served from the local location cache (counted as
    ``rpc.head_bypass_hits``); only misses cost a head round trip. Raises
    (like ``_lookup``) if any object is missing or its owner died."""
    ids = list({r.object_id for r in refs})
    if not ids:
        return {}
    metas: dict = {}
    missing: List[str] = []
    for oid in ids:
        meta = cached_location(oid)
        if meta is not None:
            metas[oid] = meta
        else:
            missing.append(oid)
    if metas:
        from raydp_tpu.obs import metrics

        metrics.counter("rpc.head_bypass_hits").inc(len(metas))
    if missing:
        metas.update(_lookup_batch_rpc(missing))
    absent = [oid for oid in ids if metas.get(oid) is None]
    if absent:
        err = ClusterError(
            f"object(s) {absent[:3]} not found (already deleted?)"
        )
        err.object_ids = absent
        raise err
    return metas


def local_meta(object_id: str):
    """The raw cache entry ``(meta, age_s)`` for a block THIS process knows
    about, in the wire form ReadSpec.metas carries (age, not timestamp —
    monotonic clocks don't compare across processes). None when unknown or
    the cache is disabled."""
    import time as _time

    if not _location_enabled:
        return None
    with _location_lock:
        entry = _location_cache.get(object_id)
    if entry is None:
        return None
    meta, stamp, _lease = entry
    return dict(meta), max(0.0, _time.monotonic() - stamp)


class _FetchedBuffer:
    """A block pulled over the network from its owning node (no local
    mapping exists for foreign-namespace objects)."""

    def __init__(self, data: bytes):
        self._data = data
        self.size = len(data)

    def memoryview(self) -> memoryview:
        return memoryview(self._data)


class _FileBuffer:
    """A spilled block mmap'd read-only from the local spill dir."""

    def __init__(self, path: str, size: int):
        import mmap as _mmap

        self._file = open(path, "rb")
        self.size = size
        self._mmap = (
            _mmap.mmap(self._file.fileno(), size, access=_mmap.ACCESS_READ)
            if size
            else None
        )

    def memoryview(self) -> memoryview:
        if self._mmap is None:
            return memoryview(b"")
        return memoryview(self._mmap)

    def __del__(self):
        try:
            if self._mmap is not None:
                self._mmap.close()
            self._file.close()
        except Exception:  # raydp-lint: disable=swallowed-exceptions (close teardown must never raise)
            pass


# RPC robustness for the block-fetch path (docs/fault_tolerance.md "RPC
# retry ladder"): a reader hitting a RESTARTING block service (or a briefly
# unreachable agent) backs off with jitter and retries under a per-call
# deadline instead of surfacing a raw ConnectionRefusedError — and past the
# deadline it raises a lost-block-shaped error so the caller degrades to
# lineage recovery. Counted: ``rpc.retries`` / ``rpc.deadline_exceeded``.
FETCH_DEADLINE_ENV = "RAYDP_TPU_FETCH_DEADLINE_S"
_FETCH_BACKOFF_BASE_S = 0.05
_FETCH_BACKOFF_CAP_S = 2.0


def _fetch_deadline_s() -> float:
    try:
        return float(os.environ.get(FETCH_DEADLINE_ENV, "") or 30.0)
    except ValueError:
        return 30.0


def _fetch_chunk(
    ref: ObjectRef, meta: dict, offset: int, length: int, deadline: float,
    into: Optional[memoryview] = None,
):
    """One ranged chunk pull with the jittered-backoff retry ladder.
    Prefers the block service's own socket (``service_addr`` — the
    first-class owner, over the ISSUE-18 pooled streaming transport) over
    the node's agent/head ``fetch_addr``; every few failed attempts the
    location is re-resolved through the head, so a service that restarted
    onto a fresh socket is found mid-ladder (and an owner the head reports
    dead propagates OwnerDiedError → lineage).

    With ``into`` the chunk is received directly into the caller's
    destination view (the parallel assembly path — no join copy) and the
    byte count is returned; otherwise the bytes are returned."""
    import random
    import socket as _socket
    import time as _time

    from raydp_tpu.obs import metrics

    request = {"shm_name": meta["shm_name"], "offset": offset, "length": length}
    attempt = 0
    while True:
        service_addr = meta.get("service_addr")
        try:
            if service_addr:
                from raydp_tpu.store.block_service import service_block_fetch

                return service_block_fetch(
                    service_addr, meta["shm_name"], offset, length, into=into
                )
            data = rpc(meta["fetch_addr"], ("block_fetch", request), timeout=300)
            if into is None:
                return data
            view = memoryview(data)
            into[: len(view)] = view
            return len(view)
        except (EOFError, OSError) as exc:
            if isinstance(exc, FileNotFoundError):
                # a remote "segment/file is gone" is NOT transient: the
                # bytes are gone while the head meta survives, and retrying
                # would stall the reader for the whole deadline against the
                # same answer — surface it now (the caller's stale-location
                # retry / lineage fallback is the right escalation)
                raise
            metrics.counter("rpc.retries").inc()
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                metrics.counter("rpc.deadline_exceeded").inc()
                err = ClusterError(
                    f"object {ref.object_id} fetch from "
                    f"{service_addr or meta.get('fetch_addr')} kept failing "
                    f"past the {_fetch_deadline_s():.0f}s deadline ({exc})"
                )
                # lost-block-shaped: the reader's lineage fallback takes over
                err.object_ids = [ref.object_id]
                raise err from exc
            delay = min(
                _FETCH_BACKOFF_CAP_S, _FETCH_BACKOFF_BASE_S * (2 ** attempt)
            )
            # jitter: a herd of readers bounced off one restarting service
            # must not retry in lockstep
            delay *= 0.5 + random.random()
            _time.sleep(min(delay, max(0.0, remaining)))
            attempt += 1
            if attempt % 3 == 0:
                # authoritative re-resolution: a restarted service binds a
                # FRESH socket; the head's live view carries it (and raises
                # OwnerDiedError / not-found when the block is really gone,
                # which must propagate — that IS the lineage trigger).
                # Updated IN PLACE: _remote_fetch shares one meta dict
                # across chunks, so later chunks of a large fetch start at
                # the re-resolved address instead of re-paying the ladder.
                fresh = _lookup(ref, fresh=True)
                meta.clear()
                meta.update(fresh)
                request["shm_name"] = meta["shm_name"]


def _fetch_parallelism() -> int:
    try:
        return max(1, int(os.environ.get("RAYDP_TPU_FETCH_PARALLEL", "4")))
    except ValueError:
        return 4


def _count_over_wire(meta: dict, nbytes: int, fetches: int = 1) -> None:
    """The observatory's view of the cross-host data plane: every remote
    byte is counted, totalled and per host edge. Flat dotted names stand in
    for labels (metrics.py has none): ``rpc.bytes_over_wire`` is the total,
    ``rpc.bytes_over_wire.<src_host>.<dst_host>`` one directed edge —
    src is the host SERVING the bytes, dst the host reading them."""
    from raydp_tpu.cluster.common import host_id, host_label
    from raydp_tpu.obs import metrics

    metrics.counter("rpc.remote_fetches").inc(fetches)
    metrics.counter("rpc.bytes_over_wire").inc(nbytes)
    src = host_label(meta.get("host", "") or meta.get("shm_ns", ""))
    dst = host_label(host_id())
    metrics.counter(f"rpc.bytes_over_wire.{src}.{dst}").inc(nbytes)
    from raydp_tpu.obs import flush_throttled

    flush_throttled(2.0)


def _remote_fetch(ref: ObjectRef, meta: dict, offset: int, length: int):
    """Ranged network pull of ``[offset, offset+length)`` from the owning
    node's block server (chunked: stays under the wire frame cap for
    arbitrarily large reads and bounds per-chunk copies). The server's
    ``block_fetch`` is range-native, so a reducer pulling its slice of an
    indexed shuffle block moves only that slice's bytes over the network.
    Multi-chunk reads fan out in parallel over the service connection pool,
    each chunk landing directly in its slice of one preallocated buffer —
    no join copy. Each chunk rides the retry ladder (``_fetch_chunk``): a
    restarting block service degrades to backoff-and-retry, then to
    lineage recovery at the deadline, never to a raw
    ConnectionRefusedError."""
    import time as _time

    chunk = 64 << 20
    deadline = _time.monotonic() + _fetch_deadline_s()
    nchunks = max(1, -(-length // chunk))
    workers = min(_fetch_parallelism(), nchunks)
    if nchunks == 1 or workers <= 1:
        parts = []
        pulled = 0
        # one shared copy: a mid-ladder re-resolution in _fetch_chunk
        # updates it in place, so every later chunk starts at the live
        # address
        meta = dict(meta)
        while pulled < length:
            part = _fetch_chunk(
                ref, meta, offset + pulled, min(chunk, length - pulled), deadline
            )
            if not part:
                break
            parts.append(part)
            pulled += len(part)
        data = parts[0] if len(parts) == 1 else b"".join(parts)
    else:
        from concurrent.futures import ThreadPoolExecutor

        buf = bytearray(length)
        mv = memoryview(buf)
        src = dict(meta)

        def pull(i: int) -> int:
            start = i * chunk
            ln = min(chunk, length - start)
            # per-worker meta copy: the in-place re-resolution contract
            # assumes a single ladder walking the dict; concurrent ladders
            # each re-resolve their own
            return _fetch_chunk(
                ref, dict(src), offset + start, ln, deadline,
                into=mv[start:start + ln],
            )

        with ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="rtpu-fetch"
        ) as pool:
            counts = list(pool.map(pull, range(nchunks)))
        short = sum(
            1 for i, n in enumerate(counts)
            if n < min(chunk, length - i * chunk)
        )
        data = buf if not short else bytes()
    stats["remote_fetches"] += 1
    stats["remote_bytes"] += len(data)
    from raydp_tpu.obs import metrics

    metrics.counter("store.remote_fetches").inc()
    metrics.counter("store.remote_bytes").inc(len(data))
    _count_over_wire(meta, len(data))
    if len(data) < length:
        raise ClusterError(
            f"object {ref.object_id} remote fetch truncated: "
            f"{len(data)} < {length}"
        )
    return data if len(data) == length else data[:length]


def _retry_uncached(ref: ObjectRef, meta: Optional[dict], exc: BaseException):
    """A read through a CACHE-SERVED location that found the segment/file
    gone re-resolves through the head once — the head is authoritative for
    deletion and owner death, so the caller gets OwnerDiedError / a clean
    not-found instead of a stale-bypass artifact. Returns the fresh meta, or
    re-raises ``exc`` when the location didn't come from the cache.

    Fast path: when the stale entry's recorded owner is ALREADY known dead
    in this process (head OwnerDiedError seen before / intentional executor
    kill), raise OwnerDiedError immediately — lineage recovery is the only
    way forward, and the head round trip would just say the same thing. A
    block the recovery layer REBOUND carries the new (live) owner in its
    refreshed records, so rebound reads never hit this path."""
    if meta is None or not meta.get("cached"):
        raise exc
    evict_location(ref.object_id)
    if owner_known_dead(meta.get("owner")):
        from raydp_tpu.obs import metrics

        metrics.counter("store.dead_owner_fastpath").inc()
        err = OwnerDiedError(
            f"object {ref.object_id}: cached location's owner "
            f"{meta.get('owner')!r} is known dead (head-bypass fast path)"
        )
        err.object_ids = [ref.object_id]
        err.owner = meta.get("owner")
        raise err from exc
    return _lookup(ref, fresh=True)


def get_buffer(ref: ObjectRef, meta: Optional[dict] = None):
    """View of the object's bytes: a zero-copy shm mapping when the object
    lives in THIS node's namespace, otherwise a network pull from the owning
    node's block server (head or node agent) — the cross-host data plane
    (parity: Ray's plasma pulls; reference reads blocks on the owner node
    via RayDatasetRDD locality, SURVEY §2.2 S7/S8). Raises OwnerDiedError
    via head if the owner died untransferred. The registered size is
    authoritative — the segment may be 1 byte for empty objects or
    capacity-sized if finalize was skipped. ``meta`` (from ``lookup_many``)
    skips the per-object lookup RPC; a cache-served meta whose segment turns
    out gone retries once through the head."""
    if meta is None:
        meta = _lookup(ref)
    try:
        return _get_buffer_resolved(ref, meta)
    except (ClusterError, OSError) as exc:
        if isinstance(exc, OwnerDiedError):
            raise
        fresh = _retry_uncached(ref, meta, exc)
        return _get_buffer_resolved(ref, fresh)


def _get_buffer_resolved(ref: ObjectRef, meta: Optional[dict] = None):
    if meta is None:
        meta = _lookup(ref)
    if meta["size"] == 0:
        return _MappedBuffer(_load_native(), 0, 0)
    if meta.get("shm_ns", "") != shm_namespace():
        return _FetchedBuffer(_remote_fetch(ref, meta, 0, meta["size"]))
    if meta["shm_name"].startswith("file://"):
        # spilled block on THIS node: mmap the file (still no payload copy)
        path = meta["shm_name"][len("file://"):]
        try:
            return _FileBuffer(path, meta["size"])
        except OSError as exc:
            err = ClusterError(
                f"object {ref.object_id} metadata exists but spill file is "
                f"gone ({exc})"
            )
            err.object_ids = [ref.object_id]
            raise err
    lib = _load_native()
    seg_size = ctypes.c_uint64()
    ptr = lib.rtpu_shm_map(meta["shm_name"].encode(), ctypes.byref(seg_size), 0)
    if not ptr:
        err = ClusterError(
            f"object {ref.object_id} metadata exists but segment is gone"
        )
        err.object_ids = [ref.object_id]
        raise err
    if seg_size.value < meta["size"]:
        lib.rtpu_shm_unmap(ctypes.c_void_p(ptr), seg_size.value)
        err = ClusterError(
            f"object {ref.object_id} segment truncated: "
            f"{seg_size.value} < {meta['size']}"
        )
        err.object_ids = [ref.object_id]
        raise err
    return _MappedBuffer(lib, ptr, meta["size"], mapped_size=seg_size.value)


def get_bytes(ref: ObjectRef) -> bytes:
    return bytes(get_buffer(ref).memoryview())


def get_arrow_buffer(
    ref: ObjectRef,
    offset: int = 0,
    length: int = -1,
    meta: Optional[dict] = None,
):
    """The object's bytes — or a ``[offset, offset+length)`` RANGE of them —
    as a pyarrow Buffer. Local objects stay zero-copy: the range is a window
    over the shared mapping (shm) or the spill-file mmap; cross-node reads
    pull ONLY the requested range from the owning node's block server. The
    range path is the read side of indexed shuffle blocks: a reducer views
    just its slice of a map task's single output block. ``meta`` (from
    ``lookup_many``) skips the per-object lookup RPC."""
    import pyarrow as pa

    if meta is None:
        meta = _lookup(ref)
    size = meta["size"]
    if length is None or length < 0:
        length = size - offset
    if offset < 0 or length < 0 or offset + length > size:
        raise ClusterError(
            f"object {ref.object_id} range [{offset}, {offset + length}) "
            f"out of bounds for size {size}"
        )
    ranged = not (offset == 0 and length == size)
    if length == 0:
        return pa.py_buffer(b"")
    if ranged and meta.get("shm_ns", "") != shm_namespace():
        # ranged network pull: only the slice crosses the wire
        try:
            return pa.py_buffer(_remote_fetch(ref, meta, offset, length))
        except (ClusterError, OSError) as exc:
            if isinstance(exc, OwnerDiedError):
                raise
            fresh = _retry_uncached(ref, meta, exc)
            return pa.py_buffer(_remote_fetch(ref, fresh, offset, length))
    buf = get_buffer(ref, meta=meta)
    if ranged:
        from raydp_tpu.obs import metrics

        metrics.counter("store.range_reads").inc()
    if isinstance(buf, (_FetchedBuffer, _FileBuffer)):
        # py_buffer wraps the existing memory (network bytes or spill mmap)
        # without copying; the memoryview inside keeps the backing alive
        view = buf.memoryview()
        return pa.py_buffer(view[offset : offset + length] if ranged else view)
    return pa.foreign_buffer(buf.ptr + offset, length, base=buf)


def read_arrow_batches(
    ref: ObjectRef,
    offset: int = 0,
    length: int = -1,
    meta: Optional[dict] = None,
):
    """Decode an Arrow-IPC-stream object (or an IPC-stream RANGE of one —
    an indexed shuffle block's slice) into (schema, [RecordBatch...])."""
    import pyarrow as pa

    with pa.ipc.open_stream(
        get_arrow_buffer(ref, offset, length, meta=meta)
    ) as reader:
        schema = reader.schema
        batches = list(reader)
    return schema, batches


def transfer(refs: Sequence[ObjectRef], new_owner: str) -> None:
    """Re-own objects (e.g. to a long-lived holder actor) so they survive their
    producer's death."""
    cluster_api.head_rpc(
        "object_transfer_owner",
        object_ids=[r.object_id for r in refs],
        new_owner=new_owner,
    )


def delete(refs: Sequence[ObjectRef]) -> None:
    for r in refs:
        evict_location(r.object_id)
    _note_deleted([r.object_id for r in refs])
    cluster_api.head_rpc("object_delete", object_ids=[r.object_id for r in refs])


def owner_of(ref: ObjectRef) -> Optional[str]:
    return cluster_api.head_rpc("object_owner_of", object_id=ref.object_id)


class ObjectHolder:
    """Long-lived actor pinning ObjectRefs per dataset uuid — the ownership-
    transfer target. Parity: RayDPSparkMaster.add_objects/get_object
    (reference ray_cluster_master.py:187-191)."""

    def __init__(self):
        self._objects = {}

    def add_objects(self, dataset_uuid: str, refs: List[ObjectRef]) -> int:
        self._objects[dataset_uuid] = list(refs)
        transfer(refs, current_owner())
        return len(refs)

    def get_objects(self, dataset_uuid: str) -> Optional[List[ObjectRef]]:
        return self._objects.get(dataset_uuid)

    def get_object(self, dataset_uuid: str, index: int) -> ObjectRef:
        return self._objects[dataset_uuid][index]

    def remove_objects(self, dataset_uuid: str, delete_data: bool = True) -> bool:
        refs = self._objects.pop(dataset_uuid, None)
        if refs is None:
            return False
        if delete_data:
            delete(refs)
        return True
