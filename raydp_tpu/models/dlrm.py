"""DLRM — the Criteo workload (reference examples/pytorch_dlrm.ipynb "DLRM
Model" cells: bottom MLP over dense features, one embedding table per
categorical feature, pairwise dot interaction, top MLP).

TPU-first differences from the reference:
- embedding tables are **vocab-sharded over the "model" mesh axis** via
  NamedSharding rules (``dlrm_sharding_rules``) — XLA partitions the gathers
  and inserts the collectives (the reference trains pure-DP with replicated
  tables; BASELINE.md asks for sharded);
- the interaction is the fused op from raydp_tpu.ops.interaction (MXU batched
  Gram matmul), optionally the pallas kernel;
- bfloat16 compute path for the MXU via ``dtype=jnp.bfloat16``.

Input convention — two forms:
- preferred (the estimator's ``categorical_columns`` mixed-dtype path):
  ``x = (dense, ids)`` with dense float [B, num_dense] and ids integer
  [B, S] — exact at ANY vocab size (reference pytorch_dlrm.ipynb feeds
  int64 ids through torch tensors; this is the jax-native equivalent);
- legacy single float matrix: x[:, :num_dense] dense, x[:, num_dense:]
  categorical ids cast back to int32 (guarded — float32 represents
  integers exactly only up to 2^24, so big vocabs must use the tuple form).
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from raydp_tpu.ops.interaction import dot_interaction, dot_interaction_fused


class DLRM(nn.Module):
    vocab_sizes: Sequence[int]
    num_dense: int
    embed_dim: int = 16
    bottom_mlp: Sequence[int] = (64, 32)
    top_mlp: Sequence[int] = (64, 32)
    use_pallas_interaction: Optional[bool] = None  # None = pallas on TPU
    dtype: jnp.dtype = jnp.float32

    def _check_float_ids(self, dtype) -> None:
        """Trace-time guard: floats represent integers exactly only up to
        2^mantissa — beyond that, distinct ids silently collapse onto the
        same embedding row (dtype and vocab sizes are static)."""
        if not jnp.issubdtype(dtype, jnp.floating):
            return
        mantissa = jnp.finfo(dtype).nmant + 1
        max_vocab = max(self.vocab_sizes)
        # ints up to 2^mantissa INCLUSIVE are exact; max id is vocab-1
        if max_vocab - 1 > 2**mantissa:
            raise ValueError(
                f"vocab size {max_vocab} exceeds exact-integer range of "
                f"{dtype} ids (2^{mantissa}); pass ids as a separate integer "
                "array (JaxEstimator categorical_columns / x=(dense, ids))"
            )

    @nn.compact
    def __call__(self, x):
        if isinstance(x, (tuple, list)):
            # mixed-dtype input (dense, ids): integer ids are exact at any
            # vocab size; float ids get the same guard as the legacy path
            dense, ids = x
            dense = dense.astype(self.dtype)
            self._check_float_ids(ids.dtype)
            ids = ids.astype(jnp.int32)
        else:
            dense = x[:, : self.num_dense].astype(self.dtype)
            self._check_float_ids(x.dtype)
            ids = x[:, self.num_dense :].astype(jnp.int32)  # [B, S]

        # bottom MLP → dense embedding of dim embed_dim
        h = dense
        for width in self.bottom_mlp:
            h = nn.relu(nn.Dense(width, dtype=self.dtype)(h))
        h = nn.Dense(self.embed_dim, dtype=self.dtype, name="bottom_proj")(h)

        # per-feature embedding tables (vocab-sharded under the rules below)
        stacked = [h]
        for i, vocab in enumerate(self.vocab_sizes):
            table = self.param(
                f"embedding_{i}",
                nn.initializers.normal(stddev=1.0 / self.embed_dim**0.5),
                (vocab, self.embed_dim),
                jnp.float32,
            )
            rows = jnp.take(
                table.astype(self.dtype), jnp.clip(ids[:, i], 0, vocab - 1), axis=0
            )
            stacked.append(rows)
        t = jnp.stack(stacked, axis=1)  # [B, 1+S, D]

        use_pallas = self.use_pallas_interaction
        if use_pallas is None:
            import jax

            # the fused kernel measures 1.46x the einsum on TPU; multi-device
            # meshes run it per-shard via shard_map (dot_interaction_fused) —
            # the dp×tp path keeps the kernel instead of falling back
            use_pallas = jax.default_backend() == "tpu"
        interact = dot_interaction_fused(t) if use_pallas else dot_interaction(t)
        z = jnp.concatenate([h, interact.astype(self.dtype)], axis=1)

        for width in self.top_mlp:
            z = nn.relu(nn.Dense(width, dtype=self.dtype)(z))
        return nn.Dense(1, dtype=self.dtype, name="head")(z)


def dlrm_optimizer(embedding_lr: float = 1e-2, dense_lr: float = 1e-3):
    """The Criteo-scale optimizer: Adafactor for the embedding tables,
    Adam for everything else (``optax.multi_transform`` keyed on param
    names). Dense Adam keeps TWO full-table moment copies — at a 2^25-row
    table that is 4.3GB of extra HBM and enough, with the dense gradient,
    to overflow a v5e chip (measured: OOM, or ~0.4s/step when it squeaks
    by). Adafactor with the factoring threshold lowered to cover embedding
    shapes keeps O(rows + cols) second-moment state: the same big-vocab
    step measures ~34ms (>10x) and fits comfortably. Pass the result as
    ``JaxEstimator(optimizer=dlrm_optimizer())``."""
    import optax

    def label_fn(params):
        import flax

        flat = flax.traverse_util.flatten_dict(params)
        labels = {
            k: ("embed" if any("embedding_" in str(p) for p in k) else "dense")
            for k in flat
        }
        return flax.traverse_util.unflatten_dict(labels)

    return optax.multi_transform(
        {
            # min_dim_size_to_factor=0: optax only factors the second
            # moment when the smaller dim is >=128 by default — embedding
            # tables are [vocab, 16..64], so without this the "factored"
            # moment silently stays a full table copy
            "embed": optax.adafactor(embedding_lr, min_dim_size_to_factor=0),
            "dense": optax.adam(dense_lr),
        },
        label_fn,
    )


def dlrm_sharding_rules():
    """param_sharding_rules for JaxEstimator: embedding tables vocab-sharded
    over the "model" axis, everything else replicated."""
    from jax.sharding import PartitionSpec as P

    from raydp_tpu.parallel.sharding import sharding_rules_fn

    return sharding_rules_fn([(r"embedding_\d+", P("model", None))])
