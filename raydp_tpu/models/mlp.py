"""MLP models — the NYCTaxi workload family (reference
examples/pytorch_nyctaxi.py builds a 5-layer torch MLP; this is the flax
equivalent used by examples, tests, and bench.py)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLPRegressor(nn.Module):
    """Dense → relu stack → scalar head. hidden=(256,128,64,16) matches the
    reference NYCTaxi model's widths (examples/pytorch_nyctaxi.py:34-49)."""

    hidden: Sequence[int] = (256, 128, 64, 16)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for width in self.hidden:
            x = nn.relu(nn.Dense(width, dtype=self.dtype)(x))
        return nn.Dense(1, dtype=self.dtype)(x)


class MLPClassifier(nn.Module):
    hidden: Sequence[int] = (256, 128, 64)
    num_classes: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for width in self.hidden:
            x = nn.relu(nn.Dense(width, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)
