"""Model zoo: the reference's workload families, TPU-native."""

from raydp_tpu.models.dlrm import DLRM, dlrm_optimizer, dlrm_sharding_rules
from raydp_tpu.models.mlp import MLPClassifier, MLPRegressor
from raydp_tpu.models.transformer import TransformerLM, sequence_parallel_apply

__all__ = [
    "DLRM",
    "MLPClassifier",
    "MLPRegressor",
    "TransformerLM",
    "dlrm_optimizer",
    "dlrm_sharding_rules",
    "sequence_parallel_apply",
]
