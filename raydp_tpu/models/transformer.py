"""Causal transformer LM with pluggable long-context attention.

Nothing like this exists in the reference (no sequence models at all); it is
here because long-context is first-class in this framework: the same block
runs single-device full attention, ring attention (sequence ring-sharded over
an ``sp`` mesh axis, raydp_tpu.parallel.ring_attention), or Ulysses
all-to-all head parallelism — selected by config, identical math.

bfloat16 by default: attention/matmul FLOPs target the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from raydp_tpu.parallel.ring_attention import (
    full_attention,
    ring_attention,
    ulysses_attention,
)


def _attend(q, k, v, *, impl: str, axis: str, causal: bool):
    if impl == "skip":
        # diagnostic: attention replaced by identity — isolates the
        # non-attention step time for roofline decomposition (bench only)
        return v
    if impl == "full":
        return full_attention(q, k, v, causal=causal)
    if impl == "flash":
        from raydp_tpu.ops.flash_attention import flash_attention

        # default blocks = pick_blocks: the measured-fastest large tiles
        return flash_attention(q, k, v, causal)
    if impl == "ring":
        return ring_attention(q, k, v, axis_name=axis, causal=causal)
    if impl == "ring_flash":
        # ring schedule with the fused pallas flash kernel computing each
        # (Q-block, K/V-block) product — the long-context production path:
        # O(T_local) memory from the ring AND VMEM-blocked exact attention
        # per step
        return ring_attention(
            q, k, v, axis_name=axis, causal=causal, use_flash=True
        )
    if impl == "ulysses":
        return ulysses_attention(q, k, v, axis_name=axis, causal=causal)
    if impl == "ulysses_flash":
        # all-to-all head parallelism with the fused flash kernel on the
        # gathered local sequence
        return ulysses_attention(
            q, k, v, axis_name=axis, causal=causal, use_flash=True
        )
    raise ValueError(f"unknown attention impl {impl!r}")


def _scatter_rows(cache, new, starts):
    """Insert ``new`` [B, H, t, D] into ``cache`` [B, H, T, D] at per-batch
    position ``starts`` [B] along the sequence dim (vmapped dynamic update —
    each sequence in a decode batch sits at its own length)."""
    def one(c, n, s):
        return jax.lax.dynamic_update_slice(c, n.astype(c.dtype), (0, s, 0))

    return jax.vmap(one)(cache, new, starts)


def _scatter_scales(cache, new, starts):
    """Same as ``_scatter_rows`` for [B, H, T] per-row scale planes."""
    def one(c, n, s):
        return jax.lax.dynamic_update_slice(c, n.astype(c.dtype), (0, s))

    return jax.vmap(one)(cache, new, starts)


def _decode_attend(q, k_new, v_new, decode_kv, kv_len):
    """Incremental-decode attention: the new rows' K/V join the cached
    sequence in-graph (per-batch scatter at each sequence's length), then
    ``ops.flash_decode`` attends the last ``t`` positions against the whole
    cache with per-sequence valid-length masking. ``decode_kv`` is either
    (k, v) dense f32 caches [B, H, Tcap, D] — the bit-exact mode the
    decode-vs-prefill determinism contract is stated for — or
    (k_int8, k_scale, v_int8, v_scale) with on-the-fly dequant in-kernel."""
    from raydp_tpu.ops.flash_attention import flash_decode

    t = q.shape[2]
    starts = kv_len - t
    if len(decode_kv) == 2:
        k_cache, v_cache = decode_kv
        k_full = _scatter_rows(k_cache, k_new, starts)
        v_full = _scatter_rows(v_cache, v_new, starts)
        return flash_decode(q, k_full, v_full, kv_len)

    from raydp_tpu.ops.quantization import quantize_int8

    k8, k_sc, v8, v_sc = decode_kv
    b, h, tn, d = k_new.shape

    def quant(x):
        vals, scales = quantize_int8(x.astype(jnp.float32).reshape(b * h * tn, d))
        return vals.reshape(b, h, tn, d), scales.reshape(b, h, tn)

    kq, kqs = quant(k_new)
    vq, vqs = quant(v_new)
    return flash_decode(
        q,
        _scatter_rows(k8, kq, starts),
        _scatter_rows(v8, vq, starts),
        kv_len,
        k_scale=_scatter_scales(k_sc, kqs, starts),
        v_scale=_scatter_scales(v_sc, vqs, starts),
    )


class Block(nn.Module):
    num_heads: int
    attn_impl: str = "full"
    seq_axis: str = "sp"
    dtype: jnp.dtype = jnp.bfloat16
    # forward MLP matmuls on the MXU's int8 path (2x the bf16 rate on
    # v5e/v5p; ops/quantization.int8_matmul — straight-through gradients,
    # backward stays bf16). Opt-in: ~0.4% relative quantization error per
    # matmul on the forward activations.
    quantized_mlp: bool = False

    @nn.compact
    def __call__(self, x, *, decode_kv=None, kv_len=None, return_kv=False):
        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        y = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * d_model, dtype=self.dtype, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(z):  # [B, T, D] -> [B, H, T, Dh]
            b, t, _ = z.shape
            return z.reshape(b, t, self.num_heads, head_dim).transpose(0, 2, 1, 3)

        q_h, k_h, v_h = heads(q), heads(k), heads(v)
        if decode_kv is not None:
            o = _decode_attend(q_h, k_h, v_h, decode_kv, kv_len)
        else:
            o = _attend(
                q_h, k_h, v_h,
                impl=self.attn_impl, axis=self.seq_axis, causal=True,
            )
        b, h, t, hd = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(b, t, h * hd)
        x = x + nn.Dense(d_model, dtype=self.dtype, name="proj")(o)

        y = nn.LayerNorm(dtype=self.dtype)(x)
        mlp_kw = {}
        if self.quantized_mlp:
            from raydp_tpu.ops.quantization import int8_dot_general

            # same nn.Dense modules, custom contraction: the param tree is
            # identical to the bf16 path, so checkpoints interchange freely
            mlp_kw["dot_general"] = int8_dot_general
        y = nn.Dense(4 * d_model, dtype=self.dtype, **mlp_kw)(y)
        y = nn.gelu(y)
        y = nn.Dense(d_model, dtype=self.dtype, **mlp_kw)(y)
        out = x + y
        if decode_kv is not None or return_kv:
            # the new rows' K/V in head layout — the decode engine appends
            # them to its paged cache after the step
            return out, (k_h, v_h)
        return out


class TransformerLM(nn.Module):
    vocab_size: int
    d_model: int = 256
    num_heads: int = 8
    num_layers: int = 4
    max_len: int = 8192
    attn_impl: str = "full"  # "full" | "flash" | "ring" | "ring_flash" | "ulysses"
    seq_axis: str = "sp"
    dtype: jnp.dtype = jnp.bfloat16
    remat: bool = False
    quantized_mlp: bool = False  # int8-MXU forward MLP matmuls (see Block)

    @nn.compact
    def __call__(
        self, tokens, seq_offset=0, *, kv_caches=None, kv_len=None,
        return_kv=False,
    ):  # tokens [B, T_local] int32
        """``seq_offset`` is this shard's global position offset (0 when the
        full sequence is local; axis_index * T_local under shard_map).

        Incremental decode (``kv_caches``/``kv_len``): ``tokens`` holds each
        sequence's newest ``t`` tokens, ``kv_len`` [B] int32 their total
        lengths INCLUDING those tokens, and ``kv_caches`` one per-layer dense
        cache tuple (see ``_decode_attend``). Positions come from ``kv_len``
        per sequence, overriding ``seq_offset``. Returns (logits, new_kv)
        where ``new_kv`` is a per-layer list of the new rows' (k, v) in
        [B, H, t, Dh] layout for the caller's paged cache. ``return_kv``
        gives the same (logits, new_kv) from a prefill pass — the cache-warm
        path."""
        decode = kv_caches is not None
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype)(tokens)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (self.max_len, self.d_model),
            jnp.float32,
        )
        t = tokens.shape[1]
        if decode:
            starts = jnp.asarray(kv_len, jnp.int32) - t
            pos_slice = jax.vmap(
                lambda s: jax.lax.dynamic_slice_in_dim(pos, s, t, axis=0)
            )(starts)  # [B, t, d_model]
        else:
            pos_slice = jax.lax.dynamic_slice_in_dim(pos, seq_offset, t, axis=0)
        x = x + pos_slice.astype(self.dtype)
        block_cls = Block
        if self.remat:
            block_cls = nn.remat(Block)
        new_kv = []
        for layer in range(self.num_layers):
            block = block_cls(
                num_heads=self.num_heads,
                attn_impl=self.attn_impl,
                seq_axis=self.seq_axis,
                dtype=self.dtype,
                quantized_mlp=self.quantized_mlp,
            )
            if decode:
                x, kv = block(x, decode_kv=kv_caches[layer], kv_len=kv_len)
                new_kv.append(kv)
            elif return_kv:
                x, kv = block(x, return_kv=True)
                new_kv.append(kv)
            else:
                x = block(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        logits = nn.Dense(self.vocab_size, dtype=jnp.float32, name="lm_head")(x)
        if decode or return_kv:
            return logits, new_kv
        return logits


def sequence_parallel_apply(model: TransformerLM, params, tokens, mesh):
    """Apply a ring/ulysses TransformerLM with the sequence sharded over the
    model's ``seq_axis``: params replicated, tokens [B, T] split on dim 1,
    logits returned with the same sequence sharding."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from raydp_tpu.parallel.sharding import shard_map_compat

    axis = model.seq_axis

    def body(p, tok):
        offset = lax.axis_index(axis) * tok.shape[1]
        return model.apply(p, tok, seq_offset=offset)

    # *_flash: the pallas interpreter can't reconcile invariant grid
    # slices with varying operands; numerics are test-validated against full
    # attention
    check_vma = (
        False if model.attn_impl in ("ring_flash", "ulysses_flash") else None
    )
    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(P(), P(None, axis)),
        out_specs=P(None, axis, None),
        check_vma=check_vma,
    )(params, tokens)
