"""Runtime sanitizers (``RAYDP_TPU_SANITIZE=donation,lockdep,leaks``).

Three independent modes, comma-separated in the env var, all default OFF and
all ON suite-wide in tests/conftest.py:

- ``donation`` — the donation-aliasing sanitizer documented below (the
  original mode; its substring-based enable check predates the mode list and
  is kept compatible).
- ``lockdep`` — every named lock in the package (``named_lock``) is wrapped
  in an :class:`InstrumentedLock` proxy that records the per-thread held-set
  and a process-global lock-acquisition-order graph, raising
  :class:`LockOrderError` with BOTH acquisition stacks the moment an
  acquisition closes a cycle — catching lock-order inversions that never
  actually deadlocked in the run (the run that deadlocks is the one you
  don't get a stack from). The static counterpart is the ``lock-order``
  rule in tools/analyze.
- ``leaks`` — a per-process resource inventory: baseline snapshot at
  startup (:func:`snapshot_baseline` — threads, fds, plus exact tracking of
  native-store shm segments and spill files via
  :func:`track_block`/:func:`untrack_block`), audited back to baseline by
  ``cluster.shutdown()`` / worker graceful exit (:func:`audit_leaks`, which
  exports ``sanitize.leaked_*`` gauges and logs leaks). ``leaks-strict``
  additionally raises :class:`LeakError` on leaked segments/spill files.

Runtime donation-aliasing sanitizer (``RAYDP_TPU_SANITIZE=donation``).

The ASan/TSan-style counterpart of the static ``donation-aliasing`` lint
rule (tools/analyze): on CPU jax, ``jax.device_put``/``jnp.asarray``
zero-copy suitably-aligned numpy arrays, so a device array staged from an
externally-owned host buffer (orbax restore results, Arrow ``to_numpy``
views, reusable staging buffers) ALIASES memory jax does not own. Donating
such an array (``donate_argnums``) lets XLA scribble over it — the PR 2
"streaming NaN" use-after-free, which corrupted restored params silently and
took 8 repro rounds on a 2-core box to pin down.

The sanitizer turns that silent corruption into an immediate, attributable
error:

- staging sites register the host buffers they do not own
  (:func:`note_external_host_buffer` — wired into the estimator's checkpoint
  restore and ``jax_io.SegmentUploader``);
- :func:`checked_jit` wraps ``jax.jit`` and, before each dispatch, verifies
  no donated argument's device buffer overlaps a registered external range
  (``unsafe_buffer_pointer`` per addressable shard vs the registered
  ``__array_interface__`` spans), raising :class:`DonationAliasError`
  instead of corrupting params.

Default OFF: with the env var unset, registration is a no-op and the
per-dispatch check short-circuits on its first comparison (the env is read
at DISPATCH time, so a jit built before the var was set is still covered
once it is). Tier-1 tests enable it (tests/conftest.py), so any future
staging path that re-introduces the hazard fails loudly in CI rather than
as a flake. Registered ranges are dropped
automatically when the registering array is garbage collected (a freed range
must not indict an unrelated later allocation at the same address).
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "DonationAliasError",
    "LockOrderError",
    "LeakError",
    "donation_check_enabled",
    "lockdep_enabled",
    "leaks_enabled",
    "leaks_strict",
    "note_external_host_buffer",
    "checked_jit",
    "guard_donated_args",
    "external_range_count",
    "named_lock",
    "InstrumentedLock",
    "reset_lockdep",
    "lock_order_edges",
    "snapshot_baseline",
    "track_block",
    "untrack_block",
    "leak_report",
    "audit_leaks",
]


class DonationAliasError(RuntimeError):
    """A donated jit argument aliases externally-owned host memory."""


class LockOrderError(RuntimeError):
    """An acquisition closed a cycle in the lock-order graph (potential
    deadlock), or a non-reentrant lock was re-acquired by its holder."""


class LeakError(RuntimeError):
    """Shutdown audit found tracked resources that outlived the cluster
    (``leaks-strict`` mode only)."""


def _modes() -> set:
    return {
        m.strip()
        for m in os.environ.get("RAYDP_TPU_SANITIZE", "").split(",")
        if m.strip()
    }


def donation_check_enabled() -> bool:
    """Read the env each call: tests toggle it; the per-dispatch cost is one
    getenv + substring test, and only when a donated jit actually fires."""
    return "donation" in os.environ.get("RAYDP_TPU_SANITIZE", "")


def lockdep_enabled() -> bool:
    return "lockdep" in _modes()


def leaks_enabled() -> bool:
    modes = _modes()
    return "leaks" in modes or "leaks-strict" in modes


def leaks_strict() -> bool:
    return "leaks-strict" in _modes()


# address-keyed registry of externally-owned host spans: id(base) ->
# (start, end, tag, finalizer). Keyed by the registering object's id with a
# weakref finalizer so a collected buffer frees its span — a stale span would
# indict whatever unrelated allocation lands at that address next.
_external: Dict[int, Tuple[int, int, str]] = {}
_finalizers: Dict[int, Any] = {}


def _ultimate_base(arr) -> Any:
    """Walk the numpy view chain to the owning object — registering the base
    covers every view sliced out of it."""
    seen = 0
    base = arr
    while getattr(base, "base", None) is not None and seen < 64:
        base = base.base
        seen += 1
    return base


def _host_span(arr) -> Optional[Tuple[int, int]]:
    iface = getattr(arr, "__array_interface__", None)
    if not iface:
        return None
    start = iface.get("data", (None,))[0]
    nbytes = getattr(arr, "nbytes", 0)
    if start is None or not nbytes:
        return None
    return (start, start + nbytes)


def note_external_host_buffer(arr, tag: str = "external") -> None:
    """Register ``arr`` (a numpy array or view) as externally-owned host
    memory. No-op unless the donation sanitizer is enabled.

    The registered span is the ultimate base buffer when it is itself an
    ndarray (covering sibling views), else the view's own bytes. The span's
    LIFETIME is tied to ``arr`` — an ndarray is always weakref-able, while a
    view's true owner often is not (orbax leaves sit on ``bytes``), and a
    span that outlives its memory would indict whatever jax allocation lands
    at that address next (observed as a flaky false positive on the
    estimator retry test before this was lifetime-scoped)."""
    if not donation_check_enabled():
        return
    import numpy as np

    if not isinstance(arr, np.ndarray):
        arr = getattr(arr, "__array__", lambda: None)()
        if arr is None:
            return
    base = _ultimate_base(arr)
    span = _host_span(base if isinstance(base, np.ndarray) else arr)
    if span is None:
        return
    key = id(arr)
    if key in _external:
        return
    _external[key] = (span[0], span[1], tag)
    _finalizers[key] = weakref.finalize(arr, _drop_external, key)


def _drop_external(key: int) -> None:
    _external.pop(key, None)
    _finalizers.pop(key, None)


def external_range_count() -> int:
    return len(_external)


def _overlapping_tag(start: int, end: int) -> Optional[str]:
    # snapshot: weakref finalizers (_drop_external) fire at arbitrary
    # bytecode boundaries — a GC'd buffer mid-scan mutated the live dict
    # ("dictionary changed size during iteration", seen in streaming fit)
    for s, e, tag in list(_external.values()):
        if start < e and s < end:
            return tag
    return None


def _leaf_device_spans(leaf):
    """(start, end) spans of a donated leaf's host-visible buffers. Only CPU
    jax can alias host numpy memory; other backends yield nothing."""
    import numpy as np

    if isinstance(leaf, np.ndarray):
        # same base-else-view fallback as registration: a view whose owner
        # is not an ndarray (bytes-backed orbax leaves) must still yield its
        # own span, or donating that exact registered array goes unchecked
        base = _ultimate_base(leaf)
        span = _host_span(base if isinstance(base, np.ndarray) else leaf)
        if span is not None:
            yield span
        return
    shards = getattr(leaf, "addressable_shards", None)
    if shards is None:
        return
    for shard in shards:
        data = getattr(shard, "data", None)
        try:
            ptr = data.unsafe_buffer_pointer()
        except Exception:  # raydp-lint: disable=swallowed-exceptions (deleted/donated/remote buffer: nothing to check)
            continue  # deleted/donated/remote buffer: nothing to check
        yield (ptr, ptr + getattr(data, "nbytes", 0))


def guard_donated_args(donated_leaves, label: str = "jit") -> None:
    """Raise :class:`DonationAliasError` if any leaf of the donated
    arguments overlaps a registered externally-owned host span."""
    if not _external:
        return
    import jax

    if jax.default_backend() != "cpu":
        return  # zero-copy host aliasing is a CPU-backend hazard
    for leaf in donated_leaves:
        for start, end in _leaf_device_spans(leaf):
            tag = _overlapping_tag(start, end)
            if tag is not None:
                shape = getattr(leaf, "shape", "?")
                dtype = getattr(leaf, "dtype", "?")
                raise DonationAliasError(
                    f"donated argument of {label} (leaf shape={shape} "
                    f"dtype={dtype}) aliases externally-owned "
                    f"host memory ({tag}, span 0x{start:x}-0x{end:x}): on CPU "
                    "jax, device_put/jnp.asarray zero-copy host numpy "
                    "buffers, and donating the alias lets XLA reuse memory "
                    "it does not own (the PR 2 streaming-NaN class). Stage "
                    "through an owned copy first: "
                    "jnp.array(device_put(x, sharding), copy=True)."
                )


def _check_args(donated: Tuple[int, ...], name: str, args) -> None:
    if not _external or not donation_check_enabled():
        return
    import jax

    leaves = []
    for i in donated:
        if i < len(args):
            leaves.extend(jax.tree_util.tree_leaves(args[i]))
    guard_donated_args(leaves, label=name)


class _CheckedCompiled:
    """AOT executable (``jit(...).lower(...).compile()``) with the same
    pre-dispatch check — the scan/stream runners dispatch through compiled
    executables, not the jit wrapper, and must not dodge the sanitizer."""

    def __init__(self, compiled, donated: Tuple[int, ...], name: str):
        self._compiled = compiled
        self._donated = donated
        self._name = name

    def __call__(self, *args, **kwargs):
        _check_args(self._donated, self._name, args)
        return self._compiled(*args, **kwargs)

    def __getattr__(self, attr):
        return getattr(self._compiled, attr)


class _CheckedLowered:
    def __init__(self, lowered, donated: Tuple[int, ...], name: str):
        self._lowered = lowered
        self._donated = donated
        self._name = name

    def compile(self, *args, **kwargs):
        return _CheckedCompiled(
            self._lowered.compile(*args, **kwargs), self._donated, self._name
        )

    def __getattr__(self, attr):
        return getattr(self._lowered, attr)


def checked_jit(fn, donate_argnums=(), label: Optional[str] = None, **jit_kwargs):
    """``jax.jit`` plus the pre-dispatch donation-aliasing check.

    With nothing donated this IS ``jax.jit(fn, ...)``. Donating jits get a
    thin wrapper whose check short-circuits per call on "no registered
    spans / sanitizer disabled" — the env is read at DISPATCH time (not
    baked in at build), so a jit built before ``RAYDP_TPU_SANITIZE`` was
    set is still covered. The check also rides through the AOT chain
    (``.lower(...).compile()(...)``)."""
    import jax

    jitted = jax.jit(fn, donate_argnums=donate_argnums, **jit_kwargs)
    if isinstance(donate_argnums, int):
        donate_argnums = (donate_argnums,)
    if not donate_argnums:
        return jitted
    name = label or getattr(fn, "__name__", "jit")
    donated = tuple(donate_argnums)

    def checked(*args, **kwargs):
        _check_args(donated, name, args)
        return jitted(*args, **kwargs)

    checked.__wrapped__ = jitted  # tests/debuggers can reach the raw jit
    checked.lower = lambda *a, **kw: _CheckedLowered(
        jitted.lower(*a, **kw), donated, name
    )
    return checked


# ---------------------------------------------------------------------------
# lockdep: runtime lock-order sanitizer (RAYDP_TPU_SANITIZE=lockdep)
# ---------------------------------------------------------------------------
#
# Classic lockdep (Linux): lock ORDER, not lock OWNERSHIP, is the invariant.
# Locks are keyed by NAME (a lock class — every _ReduceLauncher._lock shares
# one node, like lockdep's per-class keys), so one run that acquires A→B and
# a later run that acquires B→A is caught even though no two threads ever
# actually interleaved into the deadlock. Reentrancy and self-deadlock are
# judged by lock IDENTITY (two instances of one class are distinct locks).

_RLOCK_TYPE = type(threading.RLock())

_graph_lock = threading.Lock()  # plain, never instrumented: guards the graph
_lock_edges: Dict[Tuple[str, str], Dict[str, str]] = {}  # guarded-by: _graph_lock
_lock_adj: Dict[str, set] = {}  # guarded-by: _graph_lock
_tls_lockdep = threading.local()


def _held_stack() -> List[list]:
    """This thread's held locks: [name, lock_id, count] entries, in
    acquisition order."""
    stack = getattr(_tls_lockdep, "stack", None)
    if stack is None:
        stack = _tls_lockdep.stack = []
    return stack


def _format_site(skip_innermost: int = 2, limit: int = 8) -> str:
    import traceback

    frames = traceback.format_stack()[:-skip_innermost]
    return "".join(frames[-limit:])


def _find_path(src: str, dst: str) -> Optional[List[str]]:  # guarded-by: _graph_lock held
    """Shortest src ⇝ dst path in the order graph (caller holds _graph_lock)."""
    if src == dst:
        return [src]
    prev: Dict[str, str] = {}
    queue = [src]
    while queue:
        node = queue.pop(0)
        for nxt in _lock_adj.get(node, ()):
            if nxt in prev or nxt == src:
                continue
            prev[nxt] = node
            if nxt == dst:
                path = [dst]
                while path[-1] != src:
                    path.append(prev[path[-1]])
                return list(reversed(path))
            queue.append(nxt)
    return None


def _before_acquire(
    name: str, lock: Any, reentrant: bool, blocking: bool = True
) -> None:
    """Order check + edge recording, BEFORE delegating to the real acquire:
    if this acquisition would deadlock, the caller gets a stack instead of a
    hang, and the edge is in the graph for other threads even if we block."""
    if getattr(_tls_lockdep, "busy", False):
        return  # re-entered from lockdep's own error path
    held = _held_stack()
    for ent in held:
        if ent[1] == id(lock):
            if not reentrant and blocking:
                raise LockOrderError(
                    f"self-deadlock: thread {threading.current_thread().name!r} "
                    f"re-acquiring non-reentrant lock '{name}' it already "
                    f"holds\n  at:\n{_format_site()}"
                )
            # RLock reentry — or a NON-blocking probe of a plain lock by its
            # own holder, which legally returns False (threading.Condition's
            # _is_owned fallback probes exactly this way on a plain Lock):
            # either way, no new ordering information
            return
    if not held:
        return  # first lock of the chain: nothing to order against
    _tls_lockdep.busy = True
    try:
        error: Optional[str] = None
        with _graph_lock:
            for ent in held:
                holder = ent[0]
                if holder == name or (holder, name) in _lock_edges:
                    continue  # same lock class or edge already known
                back_path = _find_path(name, holder)
                if back_path is not None:
                    cycle = " -> ".join(back_path + [name])
                    first = _lock_edges.get((back_path[0], back_path[1])) if len(back_path) > 1 else None
                    prior = (
                        f"  reverse edge {back_path[0]} -> {back_path[1]} first "
                        f"recorded on thread {first['thread']!r} at:\n{first['stack']}"
                        if first
                        else ""
                    )
                    error = (
                        f"lock-order inversion: thread "
                        f"{threading.current_thread().name!r} acquiring "
                        f"'{name}' while holding '{holder}' closes the cycle "
                        f"{cycle}\n  this acquisition at:\n{_format_site(3)}"
                        f"{prior}"
                    )
                    break
                _lock_edges[(holder, name)] = {
                    "stack": _format_site(3),
                    "thread": threading.current_thread().name,
                }
                _lock_adj.setdefault(holder, set()).add(name)
        if error is not None:
            # metrics OUTSIDE _graph_lock: the registry's own lock is
            # instrumented, and counter creation re-enters this machinery
            try:
                from raydp_tpu.obs import metrics as _metrics

                _metrics.counter("sanitize.lock_order_violations").inc()
            except Exception:  # raydp-lint: disable=swallowed-exceptions (obs unavailable must not mask the LockOrderError)
                pass
            raise LockOrderError(error)
    finally:
        _tls_lockdep.busy = False


def _after_acquire(name: str, lock: Any) -> None:
    held = _held_stack()
    for ent in held:
        if ent[1] == id(lock):
            ent[2] += 1
            return
    held.append([name, id(lock), 1])


def _on_release(lock: Any) -> None:
    """Unconditional (runs even with lockdep off, so toggling the env while
    a lock is held can never strand a stale held-entry)."""
    stack = getattr(_tls_lockdep, "stack", None)
    if not stack:
        return
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][1] == id(lock):
            stack[i][2] -= 1
            if stack[i][2] == 0:
                del stack[i]
            return


class InstrumentedLock:
    """Lock proxy carrying a lockdep name. With ``lockdep`` off this is pure
    delegation (one env dict lookup per acquire); with it on, every acquire
    runs the order check above. ``threading.Condition(proxy)`` works: the
    Condition binds the PROXY's acquire/release (``with cond:`` is tracked)
    while its wait-path ``_release_save``/``_acquire_restore``/``_is_owned``
    resolve through ``__getattr__`` to the raw lock — a Condition over a
    named lock is the SAME lockdep node, which is exactly right (they are
    the same mutex; the head's ``actor_state_cond`` wraps ``head.lock``).
    Over a plain ``Lock`` (no ``_is_owned``), Condition's ownership
    fallback probes ``acquire(False)`` from the holding thread — legal, and
    distinguished from a real self-deadlock by ``blocking``."""

    def __init__(self, name: str, lock: Any):
        self._name = name
        self._lock = lock
        self._reentrant = isinstance(lock, _RLOCK_TYPE)

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if lockdep_enabled():
            _before_acquire(self._name, self._lock, self._reentrant, blocking)
        if timeout == -1:
            # let each lock type apply its OWN no-timeout default:
            # Lock/RLock spell it -1 but Semaphore spells it None, and
            # forwarding -1 to a Semaphore turns a blocking acquire into an
            # instantly-expired try-acquire
            ok = self._lock.acquire(blocking)
        else:
            ok = self._lock.acquire(blocking, timeout)
        if ok and lockdep_enabled():
            _after_acquire(self._name, self._lock)
        return ok

    def release(self) -> None:
        _on_release(self._lock)
        self._lock.release()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, attr: str):
        return getattr(self._lock, attr)

    def __repr__(self) -> str:
        return f"<InstrumentedLock {self._name} over {self._lock!r}>"


def named_lock(name: str, lock: Any = None) -> InstrumentedLock:
    """Wrap ``lock`` (default: a fresh ``threading.Lock``) in the lockdep
    proxy under ``name``. Name by lock CLASS, not instance
    (``"planner.reduce_launcher"``, not one name per launcher): ordering
    discipline is a property of the code, and per-class keys let one
    instance's history convict another instance's inversion."""
    if lock is None:
        lock = threading.Lock()
    return InstrumentedLock(name, lock)


def reset_lockdep() -> None:
    """Drop the recorded order graph and THIS thread's held-set (tests, and
    zygote-forked children whose parent recorded edges that are meaningless
    in the child)."""
    with _graph_lock:
        _lock_edges.clear()
        _lock_adj.clear()
    _tls_lockdep.stack = []


def lock_order_edges() -> List[Tuple[str, str]]:
    """The recorded acquisition-order edges (introspection/tests)."""
    with _graph_lock:
        return sorted(_lock_edges)


# ---------------------------------------------------------------------------
# leaks: shutdown resource audit (RAYDP_TPU_SANITIZE=leaks[,leaks-strict])
# ---------------------------------------------------------------------------
#
# Two precision tiers. Shm segments and spill files are tracked EXACTLY
# (create/unlink hooks in the store + cluster.common), so a leaked segment is
# named, attributable, and — in leaks-strict mode — fatal. Threads and fds
# are counted as deltas against the startup baseline and reported as gauges
# only: library internals (jax, pyarrow) open fds and park daemon threads at
# unpredictable times, and indicting them by count would make the audit cry
# wolf. The audit double-checks tracked entries against the filesystem:
# another process may legitimately have unlinked a segment this process
# created (the head unlinks driver blocks at shutdown).

_leak_lock = threading.Lock()  # plain: leaf lock inside sanitize internals
_baseline: Optional[Dict[str, int]] = None  # guarded-by: _leak_lock
_tracked_blocks: Dict[str, Tuple[str, str]] = {}  # guarded-by: _leak_lock


def _fd_count() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1  # non-procfs platform: fd audit degrades to "unknown"


def snapshot_baseline() -> None:
    """Record this process's resource floor. Called at cluster init /
    attach and at worker main — each call re-baselines, so a driver that
    runs several init/shutdown cycles audits each cycle against its own
    start, not the first one's."""
    if not leaks_enabled():
        return
    global _baseline
    with _leak_lock:
        _baseline = {
            "fds": _fd_count(),
            "threads": len(threading.enumerate()),
        }


def track_block(shm_name: str, path: str, kind: str = "shm") -> None:
    """A native-store segment (``kind="shm"``) or spill file
    (``kind="spill"``) was created by THIS process; audited at shutdown."""
    if not leaks_enabled():
        return
    with _leak_lock:
        _tracked_blocks[shm_name] = (kind, path)


def untrack_block(shm_name: str) -> None:
    # racy emptiness probe ON PURPOSE: with leaks off the dict is always
    # empty and every unlink skips the lock; a stale read just takes the lock
    # raydp-lint: disable=guarded-by (lock-free fast path; pop below is locked)
    if not _tracked_blocks:
        return
    with _leak_lock:
        _tracked_blocks.pop(shm_name, None)


def leak_report() -> Dict[str, Any]:
    """Current inventory vs the baseline. ``shm``/``spill`` list tracked
    blocks whose backing file still exists (stale entries for blocks some
    other process unlinked are dropped, not reported); ``fds``/``threads``
    are deltas (0 when no baseline or unknowable); ``pending_spans`` is the
    obs ring-buffer depth (spans recorded but never shipped)."""
    with _leak_lock:
        items = list(_tracked_blocks.items())
        baseline = dict(_baseline) if _baseline else None
    leaked: Dict[str, List[str]] = {"shm": [], "spill": []}
    stale: List[str] = []
    for name, (kind, path) in items:
        if os.path.exists(path):
            leaked.setdefault(kind, []).append(name)
        else:
            stale.append(name)
    if stale:
        with _leak_lock:
            for name in stale:
                _tracked_blocks.pop(name, None)
    fds = threads = 0
    if baseline is not None:
        now_fds = _fd_count()
        if now_fds >= 0 and baseline["fds"] >= 0:
            fds = max(0, now_fds - baseline["fds"])
        threads = max(0, len(threading.enumerate()) - baseline["threads"])
    pending_spans = 0
    try:
        from raydp_tpu.obs import tracing as _tracing

        pending_spans = len(_tracing._buffer)
    except Exception:  # raydp-lint: disable=swallowed-exceptions (obs optional in minimal processes)
        pass
    return {
        "shm": sorted(leaked["shm"]),
        "spill": sorted(leaked["spill"]),
        "fds": fds,
        "threads": threads,
        "pending_spans": pending_spans,
    }


def audit_leaks(label: str = "shutdown") -> Dict[str, Any]:
    """The teardown audit: export ``sanitize.leaked_*`` gauges, log any
    named leak, raise :class:`LeakError` in ``leaks-strict`` mode. Wired
    into ``cluster.shutdown()`` and the worker's graceful exit; safe to call
    repeatedly (gauges, not counters — a re-audit overwrites, it does not
    double-count)."""
    if not leaks_enabled():
        return {}
    report = leak_report()
    try:
        from raydp_tpu.obs import metrics as _metrics

        _metrics.gauge("sanitize.leaked_shm_segments").set(len(report["shm"]))
        _metrics.gauge("sanitize.leaked_spill_files").set(len(report["spill"]))
        _metrics.gauge("sanitize.leaked_fds").set(report["fds"])
        _metrics.gauge("sanitize.leaked_threads").set(report["threads"])
        _metrics.gauge("sanitize.pending_spans").set(report["pending_spans"])
    except Exception:  # raydp-lint: disable=swallowed-exceptions (obs unavailable must not break shutdown)
        pass
    if report["shm"] or report["spill"]:
        try:
            from raydp_tpu.obs import log as _obs_log

            _obs_log.warning(
                "resource leak at teardown",
                label=label,
                shm=report["shm"][:20],
                spill=report["spill"][:20],
            )
        except Exception:  # raydp-lint: disable=swallowed-exceptions (obs unavailable must not break shutdown)
            pass
        if leaks_strict():
            raise LeakError(
                f"{label}: {len(report['shm'])} shm segment(s) and "
                f"{len(report['spill'])} spill file(s) outlived teardown: "
                f"{(report['shm'] + report['spill'])[:20]}"
            )
    return report
