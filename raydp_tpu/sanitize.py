"""Runtime donation-aliasing sanitizer (``RAYDP_TPU_SANITIZE=donation``).

The ASan/TSan-style counterpart of the static ``donation-aliasing`` lint
rule (tools/analyze): on CPU jax, ``jax.device_put``/``jnp.asarray``
zero-copy suitably-aligned numpy arrays, so a device array staged from an
externally-owned host buffer (orbax restore results, Arrow ``to_numpy``
views, reusable staging buffers) ALIASES memory jax does not own. Donating
such an array (``donate_argnums``) lets XLA scribble over it — the PR 2
"streaming NaN" use-after-free, which corrupted restored params silently and
took 8 repro rounds on a 2-core box to pin down.

The sanitizer turns that silent corruption into an immediate, attributable
error:

- staging sites register the host buffers they do not own
  (:func:`note_external_host_buffer` — wired into the estimator's checkpoint
  restore and ``jax_io.SegmentUploader``);
- :func:`checked_jit` wraps ``jax.jit`` and, before each dispatch, verifies
  no donated argument's device buffer overlaps a registered external range
  (``unsafe_buffer_pointer`` per addressable shard vs the registered
  ``__array_interface__`` spans), raising :class:`DonationAliasError`
  instead of corrupting params.

Default OFF: with the env var unset, registration is a no-op and the
per-dispatch check short-circuits on its first comparison (the env is read
at DISPATCH time, so a jit built before the var was set is still covered
once it is). Tier-1 tests enable it (tests/conftest.py), so any future
staging path that re-introduces the hazard fails loudly in CI rather than
as a flake. Registered ranges are dropped
automatically when the registering array is garbage collected (a freed range
must not indict an unrelated later allocation at the same address).
"""

from __future__ import annotations

import os
import weakref
from typing import Any, Dict, Optional, Tuple

__all__ = [
    "DonationAliasError",
    "donation_check_enabled",
    "note_external_host_buffer",
    "checked_jit",
    "guard_donated_args",
    "external_range_count",
]


class DonationAliasError(RuntimeError):
    """A donated jit argument aliases externally-owned host memory."""


def donation_check_enabled() -> bool:
    """Read the env each call: tests toggle it; the per-dispatch cost is one
    getenv + substring test, and only when a donated jit actually fires."""
    return "donation" in os.environ.get("RAYDP_TPU_SANITIZE", "")


# address-keyed registry of externally-owned host spans: id(base) ->
# (start, end, tag, finalizer). Keyed by the registering object's id with a
# weakref finalizer so a collected buffer frees its span — a stale span would
# indict whatever unrelated allocation lands at that address next.
_external: Dict[int, Tuple[int, int, str]] = {}
_finalizers: Dict[int, Any] = {}


def _ultimate_base(arr) -> Any:
    """Walk the numpy view chain to the owning object — registering the base
    covers every view sliced out of it."""
    seen = 0
    base = arr
    while getattr(base, "base", None) is not None and seen < 64:
        base = base.base
        seen += 1
    return base


def _host_span(arr) -> Optional[Tuple[int, int]]:
    iface = getattr(arr, "__array_interface__", None)
    if not iface:
        return None
    start = iface.get("data", (None,))[0]
    nbytes = getattr(arr, "nbytes", 0)
    if start is None or not nbytes:
        return None
    return (start, start + nbytes)


def note_external_host_buffer(arr, tag: str = "external") -> None:
    """Register ``arr`` (a numpy array or view) as externally-owned host
    memory. No-op unless the donation sanitizer is enabled.

    The registered span is the ultimate base buffer when it is itself an
    ndarray (covering sibling views), else the view's own bytes. The span's
    LIFETIME is tied to ``arr`` — an ndarray is always weakref-able, while a
    view's true owner often is not (orbax leaves sit on ``bytes``), and a
    span that outlives its memory would indict whatever jax allocation lands
    at that address next (observed as a flaky false positive on the
    estimator retry test before this was lifetime-scoped)."""
    if not donation_check_enabled():
        return
    import numpy as np

    if not isinstance(arr, np.ndarray):
        arr = getattr(arr, "__array__", lambda: None)()
        if arr is None:
            return
    base = _ultimate_base(arr)
    span = _host_span(base if isinstance(base, np.ndarray) else arr)
    if span is None:
        return
    key = id(arr)
    if key in _external:
        return
    _external[key] = (span[0], span[1], tag)
    _finalizers[key] = weakref.finalize(arr, _drop_external, key)


def _drop_external(key: int) -> None:
    _external.pop(key, None)
    _finalizers.pop(key, None)


def external_range_count() -> int:
    return len(_external)


def _overlapping_tag(start: int, end: int) -> Optional[str]:
    for s, e, tag in _external.values():
        if start < e and s < end:
            return tag
    return None


def _leaf_device_spans(leaf):
    """(start, end) spans of a donated leaf's host-visible buffers. Only CPU
    jax can alias host numpy memory; other backends yield nothing."""
    import numpy as np

    if isinstance(leaf, np.ndarray):
        # same base-else-view fallback as registration: a view whose owner
        # is not an ndarray (bytes-backed orbax leaves) must still yield its
        # own span, or donating that exact registered array goes unchecked
        base = _ultimate_base(leaf)
        span = _host_span(base if isinstance(base, np.ndarray) else leaf)
        if span is not None:
            yield span
        return
    shards = getattr(leaf, "addressable_shards", None)
    if shards is None:
        return
    for shard in shards:
        data = getattr(shard, "data", None)
        try:
            ptr = data.unsafe_buffer_pointer()
        except Exception:  # raydp-lint: disable=swallowed-exceptions (deleted/donated/remote buffer: nothing to check)
            continue  # deleted/donated/remote buffer: nothing to check
        yield (ptr, ptr + getattr(data, "nbytes", 0))


def guard_donated_args(donated_leaves, label: str = "jit") -> None:
    """Raise :class:`DonationAliasError` if any leaf of the donated
    arguments overlaps a registered externally-owned host span."""
    if not _external:
        return
    import jax

    if jax.default_backend() != "cpu":
        return  # zero-copy host aliasing is a CPU-backend hazard
    for leaf in donated_leaves:
        for start, end in _leaf_device_spans(leaf):
            tag = _overlapping_tag(start, end)
            if tag is not None:
                shape = getattr(leaf, "shape", "?")
                dtype = getattr(leaf, "dtype", "?")
                raise DonationAliasError(
                    f"donated argument of {label} (leaf shape={shape} "
                    f"dtype={dtype}) aliases externally-owned "
                    f"host memory ({tag}, span 0x{start:x}-0x{end:x}): on CPU "
                    "jax, device_put/jnp.asarray zero-copy host numpy "
                    "buffers, and donating the alias lets XLA reuse memory "
                    "it does not own (the PR 2 streaming-NaN class). Stage "
                    "through an owned copy first: "
                    "jnp.array(device_put(x, sharding), copy=True)."
                )


def _check_args(donated: Tuple[int, ...], name: str, args) -> None:
    if not _external or not donation_check_enabled():
        return
    import jax

    leaves = []
    for i in donated:
        if i < len(args):
            leaves.extend(jax.tree_util.tree_leaves(args[i]))
    guard_donated_args(leaves, label=name)


class _CheckedCompiled:
    """AOT executable (``jit(...).lower(...).compile()``) with the same
    pre-dispatch check — the scan/stream runners dispatch through compiled
    executables, not the jit wrapper, and must not dodge the sanitizer."""

    def __init__(self, compiled, donated: Tuple[int, ...], name: str):
        self._compiled = compiled
        self._donated = donated
        self._name = name

    def __call__(self, *args, **kwargs):
        _check_args(self._donated, self._name, args)
        return self._compiled(*args, **kwargs)

    def __getattr__(self, attr):
        return getattr(self._compiled, attr)


class _CheckedLowered:
    def __init__(self, lowered, donated: Tuple[int, ...], name: str):
        self._lowered = lowered
        self._donated = donated
        self._name = name

    def compile(self, *args, **kwargs):
        return _CheckedCompiled(
            self._lowered.compile(*args, **kwargs), self._donated, self._name
        )

    def __getattr__(self, attr):
        return getattr(self._lowered, attr)


def checked_jit(fn, donate_argnums=(), label: Optional[str] = None, **jit_kwargs):
    """``jax.jit`` plus the pre-dispatch donation-aliasing check.

    With nothing donated this IS ``jax.jit(fn, ...)``. Donating jits get a
    thin wrapper whose check short-circuits per call on "no registered
    spans / sanitizer disabled" — the env is read at DISPATCH time (not
    baked in at build), so a jit built before ``RAYDP_TPU_SANITIZE`` was
    set is still covered. The check also rides through the AOT chain
    (``.lower(...).compile()(...)``)."""
    import jax

    jitted = jax.jit(fn, donate_argnums=donate_argnums, **jit_kwargs)
    if isinstance(donate_argnums, int):
        donate_argnums = (donate_argnums,)
    if not donate_argnums:
        return jitted
    name = label or getattr(fn, "__name__", "jit")
    donated = tuple(donate_argnums)

    def checked(*args, **kwargs):
        _check_args(donated, name, args)
        return jitted(*args, **kwargs)

    checked.__wrapped__ = jitted  # tests/debuggers can reach the raw jit
    checked.lower = lambda *a, **kw: _CheckedLowered(
        jitted.lower(*a, **kw), donated, name
    )
    return checked
