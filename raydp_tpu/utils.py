"""Shared utilities.

Capability parity with the reference's ``python/raydp/utils.py`` (memory-size
parsing at :125-146, ``random_split`` at :67-83, ``divide_blocks`` block->rank
partitioning with oversampling at :149-222), re-designed for this framework:
blocks are Arrow record batches feeding per-host ``jax.Array`` shards, so the
partitioner's invariant — every rank sees exactly the same number of samples,
achieved by oversampling rather than dropping — is what keeps a multi-host
``pjit`` step from deadlocking on ragged final batches.
"""

from __future__ import annotations

import atexit
import math
import re
import signal
from typing import Dict, List, Sequence, Tuple

import numpy as np

_MEMORY_UNITS = {
    "": 1,
    "K": 1 << 10,
    "M": 1 << 20,
    "G": 1 << 30,
    "T": 1 << 40,
    "P": 1 << 50,
}

_MEMORY_RE = re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*([KMGTP]?)I?B?\s*$", re.IGNORECASE)


def parse_memory_size(memory_size) -> int:
    """Parse a human-readable memory size ("500M", "2GB", "1.5g", 1024) to bytes."""
    if isinstance(memory_size, (int, float)) and not isinstance(memory_size, bool):
        return int(memory_size)
    match = _MEMORY_RE.match(str(memory_size))
    if not match:
        raise ValueError(f"cannot parse memory size: {memory_size!r}")
    number, unit = match.groups()
    return int(float(number) * _MEMORY_UNITS[unit.upper()])


def memory_size_string(num_bytes: int) -> str:
    """Exact inverse of :func:`parse_memory_size`, for logs and config echo."""
    num_bytes = int(num_bytes)
    for unit in ("P", "T", "G", "M", "K"):
        size = _MEMORY_UNITS[unit]
        if num_bytes >= size and num_bytes % size == 0:
            return f"{num_bytes // size}{unit}B"
    return str(num_bytes)


def register_exit_handler(func) -> None:
    """Run ``func`` once at interpreter exit or on SIGTERM/SIGINT (reference utils.py:61-64)."""
    done = False

    def _once():
        nonlocal done
        if not done:
            done = True
            func()

    atexit.register(_once)

    def _handler(signum, frame):  # noqa: ARG001 - signal signature
        _once()
        raise SystemExit(128 + signum)

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)


def normalize_weights(weights: Sequence[float]) -> List[float]:
    weights = [float(w) for w in weights]
    if any(w < 0 for w in weights) or sum(weights) <= 0:
        raise ValueError(f"weights must be non-negative and sum > 0: {weights}")
    total = sum(weights)
    return [w / total for w in weights]


def random_split(df, weights: Sequence[float], seed: int | None = None):
    """Randomly split an ETL DataFrame by normalized ``weights``.

    Parity: reference ``random_split`` (utils.py:67-83) delegating to Spark's
    ``randomSplit``; here the DataFrame engine implements the split natively.
    """
    from raydp_tpu.etl.dataframe import DataFrame  # local import: keep utils light

    if not isinstance(df, DataFrame):
        raise TypeError(
            f"random_split expects a raydp_tpu DataFrame, got {type(df).__name__}"
        )
    return df.random_split(weights, seed=seed)


def df_type_check(df) -> bool:
    """True if ``df`` is an ETL DataFrame this framework can train from."""
    from raydp_tpu.etl.dataframe import DataFrame

    if isinstance(df, DataFrame):
        return True
    raise TypeError(
        f"type {type(df)} is not supported; expected raydp_tpu.etl.DataFrame"
    )


# Each (block, offset) pair is packed into one int64: the low 32 bits address a
# row within a block, matching the reference's BLOCK_SIZE_BIT=32 (utils.py:31).
BLOCK_SIZE_BIT = 32
_BLOCK_OFFSET_MASK = (1 << BLOCK_SIZE_BIT) - 1


def pack_index(block_index: int, row_offset: int) -> int:
    return (block_index << BLOCK_SIZE_BIT) | row_offset


def unpack_index(packed: int) -> Tuple[int, int]:
    return packed >> BLOCK_SIZE_BIT, packed & _BLOCK_OFFSET_MASK


def divide_blocks(
    blocks: Sequence[int],
    world_size: int,
    shuffle: bool = False,
    shuffle_seed: int | None = None,
) -> Dict[int, List[Tuple[int, int]]]:
    """Assign data blocks to ranks so every rank gets the same sample count.

    ``blocks`` holds the row count of each block. Returns ``{rank: [(block_index,
    rows_to_take), ...]}`` where ``sum(rows_to_take)`` is identical for every
    rank, so a global batch reshaped onto the ``data`` mesh axis always has a
    static per-rank shape. A rank reads a *prefix* of each assigned block; ranks
    that come up short top up by re-reading prefixes of randomly chosen blocks
    (oversampling). As in the reference, the tail of the block that straddles a
    rank's quota boundary is not read during that epoch — pass ``shuffle=True``
    with a fresh ``shuffle_seed`` per epoch to vary which rows those are.

    Capability parity: reference ``divide_blocks`` (utils.py:149-222) — blocks
    are striped round-robin across ranks after optional shuffle, short ranks top
    up from random blocks.
    """
    blocks = list(blocks)
    if len(blocks) < world_size:
        raise ValueError(
            f"not enough blocks ({len(blocks)}) to divide over world_size={world_size}"
        )
    if any(b <= 0 for b in blocks):
        raise ValueError("every block must contain at least one row")

    num_blocks_per_rank = math.ceil(len(blocks) / world_size)
    samples_per_rank = math.ceil(sum(blocks) / world_size)
    total_slots = num_blocks_per_rank * world_size

    # Pad the index list cyclically so striping is even, then stripe.
    order = list(range(len(blocks)))
    order += order[: total_slots - len(order)]
    # unseeded shuffle must actually vary between calls (epochs)
    rng = np.random.default_rng(shuffle_seed)
    if shuffle:
        rng.shuffle(order)

    results: Dict[int, List[Tuple[int, int]]] = {}
    for rank in range(world_size):
        assigned = order[rank:total_slots:world_size]
        taken = 0
        selected: List[Tuple[int, int]] = []

        def take(block_index: int) -> None:
            nonlocal taken
            want = min(blocks[block_index], samples_per_rank - taken)
            if want > 0:
                selected.append((block_index, want))
                taken += want

        for block_index in assigned:
            take(block_index)
            if taken == samples_per_rank:
                break
        while taken < samples_per_rank:  # top up by oversampling random blocks
            take(int(rng.choice(order)))

        results[rank] = selected
    return results


def expand_block_selection(
    selection: List[Tuple[int, int]], blocks: Sequence[int]
) -> np.ndarray:
    """Expand a rank's ``divide_blocks`` selection into packed (block, row) indices."""
    out = []
    for block_index, count in selection:
        if count > blocks[block_index]:
            raise ValueError(
                f"selection takes {count} rows from block {block_index} "
                f"of size {blocks[block_index]}"
            )
        rows = np.arange(count, dtype=np.int64)
        out.append((np.int64(block_index) << BLOCK_SIZE_BIT) | rows)
    return np.concatenate(out) if out else np.empty(0, dtype=np.int64)
