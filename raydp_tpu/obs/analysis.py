"""Critical-path analysis over span graphs: where did the wall time go?

Perfetto shows the timeline; this module answers the question a perf PR has
to answer — *which* segments of the critical path a query (or serve
request) actually spent its wall time in, by category:

- ``dispatch`` — driver→executor stage dispatch, batch dispatch, plan work
- ``queue``    — admission/batch queues (serve queue_wait, tenant DRR waits)
- ``compute``  — executor task compute, replica inference, estimator steps
- ``rpc``      — control-plane round trips, block registration/emit
- ``decode``   — Arrow→numpy reads and wire decode
- ``recovery`` — lineage re-execution / healing
- ``driver``   — planner/driver self time between stages (the gap owner)

The algorithm is a **last-finisher chain**: starting from the root span's
end, repeatedly pick the child whose (clipped) end is latest, recurse into
it, and continue leftward from its start. Intervals covered by no child are
attributed to the owning span itself and reported as **stalls** — the
"widest stall" list is the first thing to read when a query is slower than
its compute. Leaf spans carrying the planner's per-stage phase args
(``server_seconds`` / ``read_s`` / ``compute_s`` / ``emit_s``) are split
into synthetic dispatch/decode/compute/rpc segments, so the attribution is
fine-grained even when executor-side spans were not shipped (tracing off —
``last_query_stats``' collector records are enough).

Every interval of the root lands in exactly ONE segment, so the category
totals sum to the root's wall time; ``attributed_frac`` reports the share
that landed in named non-root-self segments (the acceptance gate).

Consumers: ``raydp_tpu.explain_last_query()`` (the session's last query,
collector records + head-shipped executor spans when tracing is on) and
``tools/trace_analyze.py`` (any exported Perfetto JSON).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

# ordered (substring, category) rules; first match wins. Substrings, not
# prefixes: span names arrive namespaced ("etl.stage", "serve.queue_wait").
_CATEGORY_RULES: Tuple[Tuple[str, str], ...] = (
    ("queue_wait", "queue"),
    ("admission", "queue"),
    ("lineage", "recovery"),
    ("recovery", "recovery"),
    ("heal", "recovery"),
    # decode-serving spans before the generic Arrow-decode rule: the
    # "decode" substring would otherwise misfile the whole serving plane
    ("serve.stream.failover", "recovery"),
    ("serve.decode.prefill", "compute"),
    ("serve.decode.step", "compute"),
    ("serve.decode", "compute"),
    ("serve.stream", "dispatch"),
    ("decode", "decode"),
    ("read", "decode"),
    ("compute", "compute"),
    ("replica_infer", "compute"),
    ("replica_compile", "compute"),
    ("compile", "compile"),
    ("estimator.step", "compute"),
    ("estimator.epoch", "compute"),
    ("executor.task", "compute"),
    ("task.run", "compute"),
    ("emit", "rpc"),
    ("head.", "rpc"),
    ("rpc", "rpc"),
    ("obs_ingest", "rpc"),
    ("flush", "rpc"),
    ("batch_form", "dispatch"),
    ("serve.batch", "dispatch"),
    ("serve.dispatch", "dispatch"),
    ("dispatch", "dispatch"),
    ("etl.stage", "dispatch"),
    ("serve.request", "queue"),
    ("etl.query", "driver"),
    ("respond", "rpc"),
)


def categorize(name: str) -> str:
    for needle, category in _CATEGORY_RULES:
        if needle in name:
            return category
    # fall back to the name's first dotted component — still a NAMED
    # segment ("serve", "store", ...), never a silent "other"
    return name.split(".", 1)[0] or "other"


class _Node:
    __slots__ = ("record", "start", "end", "children")

    def __init__(self, record: dict):
        self.record = record
        self.start = int(record.get("ts", 0))
        self.end = self.start + int(record.get("dur", 0))
        self.children: List["_Node"] = []


def _build(records: List[dict]) -> Dict[str, _Node]:
    nodes: Dict[str, _Node] = {}
    for record in records:
        if record.get("ph") == "i" or not record.get("id"):
            continue  # instants have no extent to attribute
        node = _Node(record)
        prev = nodes.get(record["id"])
        if prev is None or node.end - node.start > prev.end - prev.start:
            nodes[record["id"]] = node
    for node in nodes.values():
        parent = node.record.get("parent")
        if parent and parent in nodes and nodes[parent] is not node:
            nodes[parent].children.append(node)
    for node in nodes.values():
        node.children.sort(key=lambda n: n.start)
    return nodes


def pick_root(records: List[dict], root_name: Optional[str] = None,
              trace: Optional[str] = None) -> Optional[dict]:
    """The span to attribute: the longest span named ``root_name`` (when
    given), else the longest parentless span — of ``trace`` when given."""
    best = None
    ids = {r.get("id") for r in records}
    for record in records:
        if record.get("ph") == "i":
            continue
        if trace and record.get("trace") != trace:
            continue
        if root_name is not None:
            if record.get("name") != root_name:
                continue
        elif record.get("parent") and record.get("parent") in ids:
            continue
        if best is None or record.get("dur", 0) > best.get("dur", 0):
            best = record
    return best


def _phase_split(node: _Node, lo: int, hi: int) -> Optional[List[dict]]:
    """Split a leaf stage span into synthetic segments from its phase args
    (dispatch envelope around the server's read/compute/emit window)."""
    args = node.record.get("args") or {}
    step_split = _step_phase_split(node, args, lo, hi)
    if step_split is not None:
        return step_split
    phases = [
        ("decode", float(args.get("read_s", 0.0))),
        ("compute", float(args.get("compute_s", 0.0))),
        ("rpc", float(args.get("emit_s", 0.0))),
    ]
    server_s = float(args.get("server_seconds", 0.0))
    if server_s <= 0.0 or all(v <= 0.0 for _, v in phases):
        return None
    total_us = hi - lo
    server_us = min(int(server_s * 1e6), total_us)
    name = node.record.get("name", "span")
    segments: List[dict] = []
    cursor = lo + (total_us - server_us)
    if cursor > lo:
        segments.append(_segment(node, lo, cursor, "dispatch",
                                 f"{name}:dispatch"))
    phase_sum = sum(v for _, v in phases) or 1.0
    for label, seconds in phases:
        if seconds <= 0.0:
            continue
        width = int(server_us * (seconds / phase_sum))
        if width <= 0:
            continue
        segments.append(_segment(node, cursor, min(cursor + width, hi),
                                 label, f"{name}:{label}"))
        cursor += width
    if cursor < hi:
        segments.append(_segment(node, cursor, hi, "compute",
                                 f"{name}:server"))
    return segments


def _step_phase_split(node: _Node, args: dict, lo: int,
                      hi: int) -> Optional[List[dict]]:
    """Split a leaf EPOCH span by the step profiler's phase totals
    (``ingest_s``/``h2d_s``/``compute_s``/``sync_s`` args, obs/profiler.py)
    into the compute-plane categories — ``explain_last_fit`` gets the same
    fine-grained attribution queries get from the stage phase args. Time
    the phases don't cover stays the epoch's own (named) category.

    Gated on the step profiler's OWN keys (``ingest_s``/``h2d_s``/
    ``sync_s``): ``compute_s`` alone must not claim a planner stage span,
    whose read/compute/emit split belongs to the server-phase arm."""
    if not any(k in args for k in ("ingest_s", "h2d_s", "sync_s")):
        return None
    phases = [
        ("ingest", float(args.get("ingest_s", 0.0))),
        ("h2d", float(args.get("h2d_s", 0.0))),
        ("compute", float(args.get("compute_s", 0.0))),
        ("sync", float(args.get("sync_s", 0.0))),
    ]
    covered_s = sum(seconds for _, seconds in phases)
    if covered_s <= 0.0:
        return None
    total_us = hi - lo
    covered_us = min(int(covered_s * 1e6), total_us)
    scale = covered_us / (covered_s * 1e6)
    name = node.record.get("name", "span")
    segments: List[dict] = []
    cursor = lo
    for label, seconds in phases:
        if seconds <= 0.0:
            continue
        width = int(seconds * 1e6 * scale)
        if width <= 0:
            continue
        segments.append(_segment(node, cursor, min(cursor + width, hi),
                                 label, f"{name}:{label}"))
        cursor += width
    if cursor < hi:
        # epoch time outside the measured phases (shuffle, bookkeeping):
        # the epoch's own category — named, honest about coverage
        segments.append(_segment(node, cursor, hi, categorize(name),
                                 f"{name}:overhead"))
    return segments


def _segment(node: _Node, lo: int, hi: int, category: str,
             label: Optional[str] = None) -> dict:
    return {
        "name": label or node.record.get("name", "span"),
        "category": category,
        "proc": node.record.get("proc", ""),
        "start_us": lo,
        "dur_s": max(0, hi - lo) / 1e6,
    }


def attribute(records: List[dict], root_name: Optional[str] = None,
              root_id: Optional[str] = None,
              trace: Optional[str] = None, top_k: int = 5) -> dict:
    """Critical-path wall-time attribution for one span tree (see module
    docstring). Returns ``{root, trace, total_s, segments, by_category,
    stalls, attributed_frac}``; raises ValueError when no root is found."""
    nodes = _build(records)
    root_record = (
        nodes[root_id].record if root_id and root_id in nodes
        else pick_root(records, root_name, trace)
    )
    if root_record is None or root_record.get("id") not in nodes:
        raise ValueError(
            "no root span found"
            + (f" (root_name={root_name!r})" if root_name else "")
        )
    root = nodes[root_record["id"]]
    segments: List[dict] = []
    stalls: List[dict] = []

    def walk(node: _Node, lo: int, hi: int) -> None:
        """Attribute (lo, hi) — a sub-interval of ``node`` — walking the
        last-finisher chain of its children right-to-left."""
        if hi <= lo:
            return
        kids = [c for c in node.children if c.start < hi and c.end > lo]
        if not kids:
            split = _phase_split(node, lo, hi)
            if split:
                segments.extend(split)
            else:
                segments.append(
                    _segment(node, lo, hi,
                             categorize(node.record.get("name", "")))
                )
            return
        cursor = hi
        remaining = list(kids)
        while cursor > lo and remaining:
            best = None
            best_end = lo
            for child in remaining:
                eff_end = min(child.end, cursor)
                if eff_end <= lo or child.start >= eff_end:
                    continue
                if best is None or eff_end > best_end or (
                    eff_end == best_end and child.start < best.start
                ):
                    best = child
                    best_end = eff_end
            if best is None:
                break
            remaining.remove(best)
            if best_end < cursor:
                # nothing ran here (on this subtree): the owning span's own
                # time — a STALL worth naming when it is wide
                gap = _segment(node, best_end, cursor,
                               _self_category(node),
                               f"{node.record.get('name', 'span')}:self")
                segments.append(gap)
                stalls.append({
                    "owner": node.record.get("name", "span"),
                    "proc": node.record.get("proc", ""),
                    "start_us": best_end,
                    "dur_s": gap["dur_s"],
                    "after": best.record.get("name", "span"),
                })
            walk(best, max(best.start, lo), best_end)
            cursor = max(best.start, lo)
        if cursor > lo:
            segments.append(
                _segment(node, lo, cursor, _self_category(node),
                         f"{node.record.get('name', 'span')}:self")
            )

    walk(root, root.start, root.end)
    segments.sort(key=lambda s: s["start_us"])
    total_s = max(root.end - root.start, 1) / 1e6
    by_category: Dict[str, float] = {}
    self_s = 0.0
    other_s = 0.0
    for segment in segments:
        by_category[segment["category"]] = (
            by_category.get(segment["category"], 0.0) + segment["dur_s"]
        )
        if segment["name"].endswith(":self"):
            self_s += segment["dur_s"]
        if segment["category"] == "other":
            other_s += segment["dur_s"]
    stalls.sort(key=lambda s: s["dur_s"], reverse=True)
    return {
        "root": root.record.get("name", "span"),
        "trace": root.record.get("trace"),
        "root_id": root.record.get("id"),
        "total_s": total_s,
        "segments": segments,
        "by_category": dict(
            sorted(by_category.items(), key=lambda kv: kv[1], reverse=True)
        ),
        "stalls": stalls[: int(top_k)],
        # share of wall time attributed to NAMED critical-path segments
        # (everything but the "other" fallback — owner self-gaps are named
        # too: a stage's gather stall is "dispatch", inter-stage driver
        # time is "driver"; the acceptance gate reads this)
        "attributed_frac": max(0.0, 1.0 - other_s / total_s),
        # the stricter split: wall time inside span bodies / phase splits
        # vs owner self-gaps (the stalls) — how much of the path is WORK
        "work_frac": max(0.0, 1.0 - self_s / total_s),
    }


def _self_category(node: _Node) -> str:
    name = node.record.get("name", "")
    if name == "etl.query":
        return "driver"
    return categorize(name)


def format_report(report: dict) -> str:
    """Human rendering of an ``attribute()`` report (what
    ``tools/trace_analyze.py`` prints)."""
    lines = [
        f"critical path of {report['root']} "
        f"(trace {report.get('trace')}): {report['total_s'] * 1e3:.2f} ms",
        f"attributed to named segments: {report['attributed_frac']:.1%} "
        f"(work {report.get('work_frac', 0.0):.1%}, "
        f"stalls {1.0 - report.get('work_frac', 0.0):.1%})",
        "by category:",
    ]
    for category, seconds in report["by_category"].items():
        share = seconds / report["total_s"] if report["total_s"] else 0.0
        lines.append(
            f"  {category:<10} {seconds * 1e3:9.2f} ms  {share:6.1%}"
        )
    if report["stalls"]:
        lines.append(f"widest stalls (top {len(report['stalls'])}):")
        for stall in report["stalls"]:
            lines.append(
                f"  {stall['dur_s'] * 1e3:9.2f} ms in {stall['owner']} "
                f"after {stall['after']} [{stall['proc']}]"
            )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the session-facing entry point
# ---------------------------------------------------------------------------


def explain_last_query(session=None, top_k: int = 5) -> dict:
    """Attribute the active session's LAST query's wall time along its
    critical path. Works with tracing OFF (the planner's collector records
    carry the driver-side spans plus per-stage phase args); with tracing ON
    the head's shipped spans for the same trace id enrich the graph with
    executor/task-level detail. Returns the ``attribute()`` report with a
    rendered ``text`` field."""
    if session is None:
        from raydp_tpu.etl.session import active_session

        session = active_session()
    if session is None:
        raise RuntimeError("no active session (init_etl first)")
    planner = getattr(session, "_planner", None) or getattr(
        session, "planner", None
    )
    records = list(getattr(planner, "last_query_records", []) or [])
    if not records:
        raise RuntimeError("no query has run on this session yet")
    root = pick_root(records, "etl.query")
    if root is not None:
        trace = root.get("trace")
        from raydp_tpu.obs.tracing import enabled

        if enabled() and trace:
            try:
                from raydp_tpu.cluster import api as cluster_api
                from raydp_tpu.obs.tracing import flush

                flush()
                dump = cluster_api.head_rpc("obs_dump", timeout=30.0)
                known = {r.get("id") for r in records}
                for record in dump.get("spans", []):
                    if record.get("trace") == trace and record.get("id") not in known:
                        records.append(record)
            except Exception:  # raydp-lint: disable=swallowed-exceptions (enrichment is best-effort; the collector records alone attribute the query)
                pass
    report = attribute(records, root_name="etl.query", top_k=top_k)
    report["text"] = format_report(report)
    return report


# ---------------------------------------------------------------------------
# the decode arm: stream TTFT / time-per-token decomposition
# ---------------------------------------------------------------------------

# phase -> category, for the by_category rollup (mirrors _CATEGORY_RULES'
# vocabulary so trace_analyze and explain_last_stream speak the same names)
_STREAM_PHASE_CATEGORY = {
    "queue": "queue",
    "kv_alloc": "compute",
    "prefill": "compute",
    "dispatch": "dispatch",
    "step_compute": "compute",
    "admission_churn": "queue",
    "drain": "dispatch",
    "stall": "other",
}


def explain_stream(client_record: dict,
                   engine_record: Optional[dict] = None,
                   top_k: int = 5) -> dict:
    """Decompose one streamed generation's wall time from the engine-kept
    stream record — no spans required, so this works with tracing OFF
    (the ``explain_last_query``/``explain_last_fit`` contract).

    TTFT splits into queue wait -> KV alloc -> prefill compute -> dispatch
    (driver-side RPC/poll remainder — a NAMED category, exactly as in
    ``attribute()``); steady-state splits into step compute -> admission
    churn (other streams' prefills stalling the loop) -> drain (the
    client's steady window minus the ENGINE's: RPC/poll wire time after
    the engine emitted, measurable because both sides stamp durations) ->
    stall (the engine-side residual no phase explains). ``attributed_frac``
    mirrors
    ``attribute()``'s convention: 1 - the "other" share, where only the
    stall residual is "other"; ``work_frac`` is the stricter share covered
    by ENGINE-MEASURED phases (queue + kv_alloc + prefill + step_compute +
    churn) — remainders excluded, honest about what was not measured."""
    client = dict(client_record or {})
    engine = dict(engine_record or {})
    total_s = float(client.get("wall_s") or engine.get("wall_s") or 0.0)
    ttft_s = client.get("ttft_s")
    if ttft_s is None:
        ttft_s = engine.get("ttft_s")
    ttft_s = float(ttft_s or 0.0)
    ttft_s = min(ttft_s, total_s) if total_s else ttft_s

    queue_s = float(engine.get("queue_s") or 0.0)
    kv_alloc_s = float(engine.get("kv_alloc_s") or 0.0)
    prefill_s = float(engine.get("prefill_s") or 0.0)
    step_s = float(engine.get("step_compute_s") or 0.0)
    churn_s = float(engine.get("churn_s") or 0.0)

    dispatch_s = max(0.0, ttft_s - queue_s - kv_alloc_s - prefill_s)
    steady_s = max(0.0, total_s - ttft_s)
    engine_steady_s = engine.get("steady_s")
    if engine_steady_s is not None:
        # both sides stamp their own steady window as durations: the
        # client's window minus the engine's is the poll/RPC drain after
        # the engine emitted — wire time, charged to dispatch, not stall
        engine_steady_s = min(float(engine_steady_s), steady_s)
        drain_s = max(0.0, steady_s - engine_steady_s)
        # round-to-round charging can overshoot the emit-to-emit steady
        # window by fractions of a round — clamp so parts never exceed
        # the whole
        step_s = min(step_s, max(0.0, engine_steady_s - churn_s))
        stall_s = max(0.0, engine_steady_s - step_s - churn_s)
    else:
        drain_s = 0.0
        stall_s = max(0.0, steady_s - step_s - churn_s)

    phases = {
        "queue": queue_s,
        "kv_alloc": kv_alloc_s,
        "prefill": prefill_s,
        "dispatch": dispatch_s,
        "step_compute": step_s,
        "admission_churn": churn_s,
        "drain": drain_s,
        "stall": stall_s,
    }
    by_category: Dict[str, float] = {}
    for phase, seconds in phases.items():
        category = _STREAM_PHASE_CATEGORY[phase]
        by_category[category] = by_category.get(category, 0.0) + seconds

    measured_s = queue_s + kv_alloc_s + prefill_s + step_s + churn_s
    attributed = (
        max(0.0, 1.0 - stall_s / total_s) if total_s > 0 else 0.0
    )
    work_frac = min(1.0, measured_s / total_s) if total_s > 0 else 0.0

    tokens = int(client.get("tokens") or engine.get("tokens") or 0)
    tpot_ms = (steady_s * 1e3 / (tokens - 1)) if tokens > 1 else None
    report = {
        "root": "serve.stream",
        "stream_id": client.get("stream_id") or engine.get("stream_id"),
        "deployment": client.get("deployment"),
        "trace": client.get("trace") or engine.get("trace"),
        "total_s": total_s,
        "ttft_s": ttft_s,
        "ttft_ms": ttft_s * 1e3,
        "tpot_ms": tpot_ms,
        "tokens": tokens,
        "prompt_tokens": engine.get("prompt_tokens"),
        "steps": engine.get("steps"),
        "failovers": int(client.get("failovers") or 0),
        "error": client.get("error") or engine.get("error"),
        "good_tokens": engine.get("good_tokens"),
        "late_tokens": engine.get("late_tokens"),
        "phases": phases,
        "by_category": dict(
            sorted(by_category.items(), key=lambda kv: kv[1], reverse=True)
        ),
        "attributed_frac": attributed,
        "work_frac": work_frac,
        "engine_record": bool(engine_record),
    }
    report["text"] = format_stream_report(report)
    return report


def format_stream_report(report: dict) -> str:
    """Human rendering of an ``explain_stream`` report."""
    phases = report["phases"]
    tokens = report.get("tokens") or 0
    header = (
        f"decode stream {report.get('stream_id')} on "
        f"{report.get('deployment') or '?'}: "
        f"{report['total_s'] * 1e3:.2f} ms wall, {tokens} tokens, "
        f"{report.get('failovers', 0)} failovers"
    )
    ttft_line = (
        f"ttft {report['ttft_ms']:.2f} ms = "
        f"queue {phases['queue'] * 1e3:.2f}"
        f" + kv_alloc {phases['kv_alloc'] * 1e3:.2f}"
        f" + prefill {phases['prefill'] * 1e3:.2f}"
        f" + dispatch {phases['dispatch'] * 1e3:.2f}"
    )
    steady_ms = max(0.0, report["total_s"] - report["ttft_s"]) * 1e3
    steady_line = (
        f"steady {steady_ms:.2f} ms = "
        f"step_compute {phases['step_compute'] * 1e3:.2f}"
        f" + admission_churn {phases['admission_churn'] * 1e3:.2f}"
        f" + drain {phases['drain'] * 1e3:.2f}"
        f" + stall {phases['stall'] * 1e3:.2f}"
    )
    if report.get("tpot_ms") is not None:
        steady_line += f"  ({report['tpot_ms']:.2f} ms/token)"
    lines = [
        header,
        ttft_line,
        steady_line,
        f"attributed to named phases: {report['attributed_frac']:.1%} "
        f"(engine-measured {report.get('work_frac', 0.0):.1%})",
    ]
    if not report.get("engine_record"):
        lines.append(
            "NOTE: no engine-side stream record (replica restarted or "
            "record evicted) — only client-side timings above"
        )
    if report.get("error"):
        lines.append(f"error: {report['error']}")
    return "\n".join(lines)
