"""Process-local metrics registry: counters, gauges, histograms.

Always on (the instruments are dict updates — far cheaper than any call site
they sit in: RPCs, block writes, dispatch batches). Each process accumulates
locally; snapshots ride to the head with every trace flush and the driver
merges them via ``cluster.dump_metrics()``.

Metric names are dotted strings; see docs/observability.md for the table of
names the runtime emits.
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict


class Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def snapshot(self):
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-set value, with an OPT-IN high-watermark mode: call
    ``set_watermark`` instead of ``set`` and the snapshot additionally
    carries ``max`` — the peak ever set — which the time-series layer fans
    out as a ``<name>.max`` series (the memory plane's watermark gauges).
    Plain ``set`` leaves the snapshot byte-identical to the old shape."""

    __slots__ = ("value", "_max")

    def __init__(self):
        self.value = 0.0
        self._max = None  # armed by the first set_watermark

    def set(self, value: float) -> None:
        self.value = float(value)

    def set_watermark(self, value: float) -> None:
        value = float(value)
        self.value = value
        if self._max is None or value > self._max:
            self._max = value

    def snapshot(self):
        if self._max is None:
            return {"type": "gauge", "value": self.value}
        return {"type": "gauge", "value": self.value, "max": self._max}


class Histogram:
    """count/sum/min/max summary plus bounded-reservoir quantiles.

    The summary fields answer "how many, how much, how bad" without
    per-observation storage; p50/p99 come from a fixed-size uniform
    reservoir (algorithm R) so SLO gauges — the serving plane's latency
    histograms foremost — get tail shape in O(1) memory. The reservoir is
    OFF until the first ``observe`` (no allocation for the many histograms
    that exist only so dump_metrics carries their keys), and the pre-existing
    snapshot fields are unchanged for old readers — ``p50``/``p99`` are
    purely additive keys."""

    __slots__ = ("count", "sum", "min", "max", "_reservoir")

    RESERVOIR_SIZE = 512

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._reservoir = None  # allocated on first observe

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        reservoir = self._reservoir
        if reservoir is None:
            reservoir = self._reservoir = []
        if len(reservoir) < self.RESERVOIR_SIZE:
            reservoir.append(value)
        else:
            # uniform replacement keeps every past observation equally
            # likely to be resident; like the other instruments this is
            # lock-free — a racing observe's worst case is one lost sample
            slot = random.randrange(self.count)
            if slot < self.RESERVOIR_SIZE:
                reservoir[slot] = value

    def quantile(self, q: float):
        """Nearest-rank quantile over the resident reservoir (exact while
        count <= RESERVOIR_SIZE, a uniform-sample estimate beyond). None
        before the first observation."""
        reservoir = self._reservoir
        if not reservoir:
            return None
        ordered = sorted(reservoir)
        rank = min(len(ordered) - 1, int(q * len(ordered)))
        return ordered[rank]

    def snapshot(self):
        if not self.count:
            return {"type": "histogram", "count": 0, "sum": 0.0}
        return {
            "type": "histogram",
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": self.sum / self.count,
            "p50": self.quantile(0.50),
            "p99": self.quantile(0.99),
        }


class Registry:
    """The per-process registry. Instruments are created on first use and
    live for the process; lookups are one dict hit under a lock (creation
    only — the instrument methods themselves are lock-free, fine for
    float-add races whose worst case is a lost increment)."""

    def __init__(self):
        from raydp_tpu.sanitize import named_lock

        self._lock = named_lock("obs.metrics_registry")
        self._instruments: Dict[str, Any] = {}

    def _get(self, name: str, cls):
        inst = self._instruments.get(name)
        if inst is None:
            with self._lock:
                inst = self._instruments.setdefault(name, cls())
        if not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as {type(inst).__name__}"
            )
        return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._instruments.items())
        return {name: inst.snapshot() for name, inst in items}

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()


metrics = Registry()
