"""Analytic compute cost model: FLOPs accounting, device peaks, MFU.

Until PR 15 this lived as one-shot code inside ``bench.py``
(``lm_train_flops_per_step``, ``_device_peak_flops``, the r05 roofline) —
which meant MFU existed only while a bench ran, and ROADMAP item 2's
"re-run the roofline probe on real hardware" required carrying a script
around. This module is the library version the cluster carries:

- **analytic FLOPs** for the model families the repo ships
  (:func:`lm_train_flops_per_step`, :func:`mlp_train_flops_per_step`) —
  matmul-only accounting, fwd+bwd as 3x forward, the convention every
  BENCH_r* MFU number was computed with;
- **measured FLOPs** from XLA's own cost analysis
  (:func:`step_flops_from_compiled`) — what the estimator's live MFU gauge
  uses, since a fit's step function is arbitrary user code the analytic
  tables can't know. The two accountings agree to within the optimizer /
  elementwise overhead XLA counts and the analytic tables deliberately
  ignore (``fit_profile_probe`` cross-checks them; docs/observability.md
  "Compute observatory");
- **peak FLOP/s** per device (:func:`device_peak_flops`): the TPU bf16
  table, an env override (``RAYDP_TPU_PEAK_FLOPS``) for exotic backends,
  and a NOMINAL cpu estimate (cores × 3 GHz × 16 f32 lanes) so the MFU
  gauge exists on dev boxes too — explicitly approximate, labeled
  ``peak_source`` so nobody mistakes a CPU MFU for a measured roofline.

One FLOPs accounting, bit-identical numbers in ``bench.py`` and the live
``estimator.mfu`` gauge — both import THIS module.

Stdlib + jax-on-demand only: importable before (or without) jax.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence, Tuple

PEAK_FLOPS_ENV = "RAYDP_TPU_PEAK_FLOPS"

# bf16 peak FLOP/s per jax device, matched by substring of device_kind.
# v2/v3 expose one device per CORE (half a chip); v4+ one per chip.
TPU_PEAK_FLOPS: Tuple[Tuple[str, float], ...] = (
    ("v6", 918e12),  # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),  # v5e / "v5 lite"
    ("v4", 275e12),
    ("v3", 61.5e12),
    ("v2", 22.5e12),
)

# nominal per-core CPU f32 peak: 3 GHz × (8-wide FMA = 16 flops/cycle).
# Deliberately crude — the point of a CPU MFU is trend lines on dev boxes,
# not a roofline claim (peak_source says "nominal-cpu").
_CPU_NOMINAL_PER_CORE = 3.0e9 * 16


def device_peak_flops(device: Any = None) -> dict:
    """``{kind, peak, peak_source}`` for ``device`` (default: the first
    jax device). ``peak`` is None when the device kind is unknown and no
    override is set; ``peak_source`` is one of ``tpu-table`` / ``env`` /
    ``nominal-cpu`` / ``unknown``."""
    override = os.environ.get(PEAK_FLOPS_ENV)
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", str(device))
    if override:
        return {"kind": kind, "peak": float(override), "peak_source": "env"}
    low = kind.lower()
    for sub, peak in TPU_PEAK_FLOPS:
        if sub in low:
            return {"kind": kind, "peak": peak, "peak_source": "tpu-table"}
    if "cpu" in low:
        cores = os.cpu_count() or 1
        return {
            "kind": kind,
            "peak": cores * _CPU_NOMINAL_PER_CORE,
            "peak_source": "nominal-cpu",
        }
    return {"kind": kind, "peak": None, "peak_source": "unknown"}


# ---------------------------------------------------------------------------
# analytic FLOPs (matmul-only; train = 3x forward — the BENCH convention)
# ---------------------------------------------------------------------------


def lm_train_flops_per_step(batch: int, seq: int, d_model: int,
                            num_layers: int, vocab: int) -> int:
    """Analytic matmul FLOPs of one TransformerLM training step (fwd+bwd,
    no remat): per token per layer 24*d^2 (qkv 6d^2, proj 2d^2, mlp 16d^2)
    plus causal attention 2*d*(T+1) (QK^T + AV at average context (T+1)/2),
    plus the d*V lm_head; backward costs 2x forward."""
    per_token = num_layers * (24 * d_model**2 + 2 * d_model * (seq + 1))
    per_token += 2 * d_model * vocab
    return 3 * batch * seq * per_token


def lm_nonattn_flops_per_step(batch: int, seq: int, d_model: int,
                              num_layers: int, vocab: int) -> int:
    """The step's FLOPs with attention as identity — the roofline
    decomposition's other arm (attention FLOPs = total - this)."""
    return 3 * batch * seq * (
        num_layers * 24 * d_model**2 + 2 * d_model * vocab
    )


def lm_decode_flops_per_token(d_model: int, num_layers: int, vocab: int,
                              context: int) -> int:
    """Analytic matmul FLOPs to decode ONE token with ``context`` tokens of
    KV behind it (forward only — serving runs no backward): per layer
    24*d^2 dense matmuls plus 4*d*context attention (QK^T and AV each read
    the full cache), plus the d*V lm_head. The capacity planner's per-token
    roofline arm (tools/capacity_plan.py)."""
    per_token = num_layers * (24 * d_model**2 + 4 * d_model * int(context))
    per_token += 2 * d_model * vocab
    return int(per_token)


def lm_prefill_flops(prompt: int, d_model: int, num_layers: int,
                     vocab: int) -> int:
    """Forward-only matmul FLOPs of one prefill pass over ``prompt``
    tokens: the train accounting's forward third (causal attention at
    average context (prompt+1)/2) — bounds the TTFT compute floor."""
    per_token = num_layers * (
        24 * d_model**2 + 2 * d_model * (int(prompt) + 1)
    )
    per_token += 2 * d_model * vocab
    return int(prompt) * per_token


def mlp_train_flops_per_step(batch: int, layer_dims: Sequence[int]) -> int:
    """Analytic matmul FLOPs of one dense-MLP training step: forward is
    2*B*d_in*d_out per layer, backward costs 2x forward (grad wrt inputs
    AND weights) — bias adds / activations / optimizer elementwise work
    excluded by convention, exactly like the LM accounting."""
    dims = list(layer_dims)
    fwd = sum(2 * batch * a * b for a, b in zip(dims[:-1], dims[1:]))
    return 3 * fwd


# ---------------------------------------------------------------------------
# measured FLOPs: XLA cost analysis of a lowered/compiled step
# ---------------------------------------------------------------------------


def step_flops_from_compiled(compiled: Any) -> Optional[float]:
    """Total FLOPs XLA attributes to one execution of ``compiled`` (an AOT
    ``jax.stages.Compiled`` or anything exposing ``cost_analysis()``).
    Returns None when the backend doesn't report — callers must treat
    this as "unknown", never zero."""
    try:
        cost = compiled.cost_analysis()
    except Exception:  # raydp-lint: disable=swallowed-exceptions (cost analysis is backend-optional; unknown is a valid answer)
        return None
    # jax has returned both a dict and a 1-element list of dicts over time
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None
    flops = cost.get("flops")
    if flops is None or flops <= 0:
        return None
    return float(flops)


def step_flops_abstract(fn: Any, *args) -> Optional[float]:
    """FLOPs of one call of ``fn`` at ``args``'s shapes — args may be
    ``jax.ShapeDtypeStruct`` pytrees (nothing is materialized). Used by the
    segment-scanned fit paths: XLA's cost analysis counts a ``lax.scan``
    BODY once regardless of trip count, so the compiled segment's number
    cannot be divided by steps — the single-step function is lowered
    abstractly instead (one bounded extra compile per fit, served by the
    persistent compilation cache on repeats)."""
    import jax

    try:
        return step_flops_from_compiled(jax.jit(fn).lower(*args).compile())
    except Exception:  # raydp-lint: disable=swallowed-exceptions (an unloweable step degrades to an unknown flops count, not a failed fit)
        return None


def step_flops_from_jitted(jitted: Any, *args) -> Optional[float]:
    """FLOPs of one call of a jitted function at ``args``'s shapes, via
    ``lower().compile().cost_analysis()`` — jax caches the compile, so on
    an already-dispatched jit this costs one trace, not one compile."""
    lower = getattr(jitted, "lower", None)
    if lower is None:
        return None
    try:
        return step_flops_from_compiled(lower(*args).compile())
    except Exception:  # raydp-lint: disable=swallowed-exceptions (an unloweable wrapper degrades to an unknown flops count, not a failed fit)
        return None


def mfu(model_flops_per_sec: Optional[float],
        peak_flops: Optional[float]) -> Optional[float]:
    """Model FLOPs utilization; None when either side is unknown."""
    if not model_flops_per_sec or not peak_flops:
        return None
    return model_flops_per_sec / peak_flops


# ---------------------------------------------------------------------------
# cross-host placement (ISSUE 18)
# ---------------------------------------------------------------------------

WIRE_GBPS_ENV = "RAYDP_TPU_WIRE_GBPS"
# nominal host-to-host wire bandwidth: 10 Gb/s ≈ 1.25 GB/s. Deliberately a
# planning constant, not a measurement — placement scoring only needs the
# RELATIVE cost of moving each host's bytes, and the env override exists
# for clusters whose fabric is genuinely different.
_WIRE_BYTES_PER_S_DEFAULT = 1.25e9


def wire_bytes_per_s() -> float:
    try:
        gbps = float(os.environ.get(WIRE_GBPS_ENV, "") or 10.0)
    except ValueError:
        gbps = 10.0
    return gbps * 1e9 / 8.0


def exchange_placement(bytes_by_host: dict) -> Tuple[Optional[str], dict]:
    """Score reduce/exchange placement per candidate host: the estimated
    seconds of wire transfer if the task runs THERE (every byte not already
    on that host crosses the wire at the nominal bandwidth). Returns
    ``(best_host, {host: est_transfer_s})`` — best is the host holding the
    most input bytes, with deterministic (host-name) tie-breaking so two
    planners given the same map score the same placement. Empty input
    scores to ``(None, {})``."""
    if not bytes_by_host:
        return None, {}
    bw = wire_bytes_per_s()
    total = sum(bytes_by_host.values())
    scores = {
        host: (total - local) / bw for host, local in bytes_by_host.items()
    }
    best = min(scores, key=lambda h: (scores[h], str(h)))
    return best, scores
