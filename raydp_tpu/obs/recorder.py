"""Always-on flight recorder: the evidence that survives the crash.

Two halves, matching where the evidence can actually live:

- **Process half** (every process, always on, near-free): a bounded ring of
  recent structured-log records (``note_log`` is called by
  ``obs.logging``; one deque append per line) that ships WITH every
  ``obs_ingest`` flush — spans and metrics snapshots already ride that
  frame, so a process's recent history reaches the head continuously. A
  SIGKILLed executor's last dispatch flushed unthrottled (PR 2), so its
  final spans/logs are on the head when it dies.
- **Head half** (:class:`FlightRecorder`): per-process rings of the last
  N spans, last N log records, and a ~10s tail of metrics snapshots —
  SEPARATE from the global trace deque, so a chatty co-tenant evicting the
  trace ring never evicts a victim's final moments. On executor / replica /
  service death (and on demand: unrecovered queries, sanitizer findings)
  the head assembles a **crash dossier**: the victim's rings as shipped,
  the head's actor table, the per-tenant accounting snapshot, and the
  lockdep order graph when armed — one JSON file in a configurable dir
  (``obs.dossier_dir`` conf / ``RAYDP_TPU_DOSSIER_DIR``, default
  ``<session_dir>/dossiers``), bounded to :data:`MAX_DOSSIER_FILES` newest.

Stdlib only; the head and ``python -S`` workers both import this.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

DOSSIER_DIR_ENV = "RAYDP_TPU_DOSSIER_DIR"

# per-process head-side ring capacities: small enough that hundreds of
# processes stay cheap, large enough to hold a victim's last dispatches
SPAN_RING = 512
LOG_RING = 256
METRICS_TAIL_S = 10.0
METRICS_TAIL_CAP = 32

MAX_DOSSIER_FILES = 32

# head-side rings for processes not heard from in this long are dropped
# (swept during note_ingest): actor churn on a long-lived cluster must not
# grow recorder memory without bound. Generous vs the seconds between a
# victim's last flush and its death event — dossier assembly always finds
# a fresh victim's rings.
PROC_RETENTION_S = 600.0
_RETENTION_SWEEP_EVERY = 128

# ---------------------------------------------------------------------------
# process half: recent-log ring, shipped with each flush
# ---------------------------------------------------------------------------

_log_ring: "collections.deque" = collections.deque(maxlen=LOG_RING)
# plain (never instrumented) lock: note_log sits under obs.logging, which
# error paths call with arbitrary other locks held — this must stay a
# self-contained leaf that only ever guards the deque
_log_lock = threading.Lock()


def note_log(level: str, role: str, message: str, fields: Dict[str, Any]) -> None:
    """Record one structured-log line in the process flight ring (called by
    ``obs.logging`` on every emit; one short lock acquire per line — log
    lines are rare next to spans/metrics)."""
    record = {
        "ts": time.time(),
        "level": level,
        "role": role,
        "message": message,
        "fields": {k: repr(v)[:200] for k, v in fields.items()},
    }
    with _log_lock:
        _log_ring.append(record)


def drain_logs() -> List[dict]:
    """Remove and return the recent-log ring (the flush ship point); records
    shipped once live on in the HEAD's per-process ring."""
    with _log_lock:
        out = list(_log_ring)
        _log_ring.clear()
    return out


def recent_logs() -> List[dict]:
    with _log_lock:
        return list(_log_ring)


def requeue_logs(logs: List[dict]) -> None:
    """Put drained log records back UNDER anything logged since the drain
    (a failed flush must not lose the ring) — newest-biased like the span
    re-buffer, bounded by the ring's own capacity. Atomic under the ring
    lock: lines logged DURING the failed flush (likely describing the very
    incident) must not be clobbered by the requeue."""
    if not logs:
        return
    with _log_lock:
        combined = logs + list(_log_ring)
        _log_ring.clear()
        _log_ring.extend(combined[-(_log_ring.maxlen or 1):])


# ---------------------------------------------------------------------------
# head half: per-process rings + dossier assembly
# ---------------------------------------------------------------------------


class _ProcFlight:
    __slots__ = ("role", "spans", "logs", "metrics_tail", "last_seen")

    def __init__(self, role: str):
        self.role = role
        self.spans: collections.deque = collections.deque(maxlen=SPAN_RING)
        self.logs: collections.deque = collections.deque(maxlen=LOG_RING)
        # (ts, cumulative snapshot) — pruned to the trailing tail window
        self.metrics_tail: collections.deque = collections.deque(
            maxlen=METRICS_TAIL_CAP
        )
        self.last_seen = 0.0


class FlightRecorder:
    """Head-side recorder; fed from ``handle_obs_ingest``, read by dossier
    assembly. Its lock is a LEAF: taken briefly for ring updates/snapshots,
    never around I/O or another lock."""

    def __init__(self):
        from raydp_tpu.sanitize import named_lock

        self._lock = named_lock("obs.flight", threading.Lock())
        self._procs: Dict[str, _ProcFlight] = {}  # guarded-by: self._lock
        self._dossiers_written = 0  # guarded-by: self._lock
        self._ingests = 0  # guarded-by: self._lock

    def note_ingest(self, proc_key: str, role: str, spans: List[dict],
                    snapshot: Optional[dict], logs: Optional[List[dict]],
                    ts: Optional[float] = None) -> None:
        ts = time.time() if ts is None else ts
        with self._lock:
            flight = self._procs.get(proc_key)
            if flight is None:
                flight = self._procs[proc_key] = _ProcFlight(role)
            flight.last_seen = ts
            if spans:
                flight.spans.extend(spans)
            if logs:
                flight.logs.extend(logs)
            if snapshot:
                flight.metrics_tail.append((ts, snapshot))
                while (
                    flight.metrics_tail
                    and ts - flight.metrics_tail[0][0] > METRICS_TAIL_S
                ):
                    flight.metrics_tail.popleft()
            self._ingests += 1
            if self._ingests % _RETENTION_SWEEP_EVERY == 0:
                cutoff = ts - PROC_RETENTION_S
                for key in [
                    k for k, f in self._procs.items() if f.last_seen < cutoff
                ]:
                    del self._procs[key]

    def proc_keys(self) -> List[str]:
        with self._lock:
            return list(self._procs)

    def _snapshot_proc(self, proc_key: str) -> Optional[dict]:
        with self._lock:
            flight = self._procs.get(proc_key)
            if flight is None:
                return None
            return {
                "proc": proc_key,
                "role": flight.role,
                "last_seen": flight.last_seen,
                "spans": list(flight.spans),
                "logs": list(flight.logs),
                "metrics_tail": [
                    {"ts": ts, "metrics": snap}
                    for ts, snap in flight.metrics_tail
                ],
            }

    def find_victim_keys(self, needle: str) -> List[str]:
        """Process keys whose role or key mention ``needle`` (an actor id,
        a pid string) — how a death event maps onto the rings."""
        needle = str(needle)
        with self._lock:
            return [
                key for key, flight in self._procs.items()
                if needle in key or needle in flight.role
            ]

    # -- dossiers --------------------------------------------------------

    def assemble(self, reason: str, victim_keys: Optional[List[str]] = None,
                 victim: Optional[dict] = None,
                 head_state: Optional[dict] = None) -> dict:
        """Build the dossier dict. ``head_state`` (actor table, tenant
        accounting, ...) is collected by the caller — the head snapshots it
        under ITS lock; this method only reads the flight rings."""
        from raydp_tpu import sanitize

        rings = []
        for key in victim_keys or []:
            snap = self._snapshot_proc(key)
            if snap is not None:
                rings.append(snap)
        dossier = {
            "format": "raydp-crash-dossier-v1",
            "reason": reason,
            "ts": time.time(),
            "victim": victim or {},
            "victim_rings": rings,
            "head": head_state or {},
            "known_procs": self.proc_keys(),
        }
        decode = _decode_sections(rings)
        if decode:
            dossier["decode"] = decode
        if sanitize.lockdep_enabled():
            dossier["lock_order_graph"] = [
                list(edge) for edge in sanitize.lock_order_edges()
            ]
        return dossier

    def write(self, dossier: dict, out_dir: str) -> Optional[str]:
        """Serialize one dossier to ``out_dir`` (created on demand), pruning
        to the :data:`MAX_DOSSIER_FILES` newest PER REASON — routine
        intentional kills (scale-in churn, session stops) must never evict
        a genuine crash's evidence, which is the whole point of the
        recorder. Best-effort by design: a full disk must not take the head
        down with the actor."""
        try:
            os.makedirs(out_dir, exist_ok=True)
            with self._lock:
                # locked, so concurrent dossier writers (several deaths in
                # one event) get distinct sequence numbers — a same-second
                # filename collision would os.replace one victim's evidence
                # away silently
                self._dossiers_written += 1
                seq = self._dossiers_written
            stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
            reason_slug = _slug(dossier.get("reason", "event"))
            name = f"dossier-{stamp}-{seq:04d}-{reason_slug}.json"
            path = os.path.join(out_dir, name)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(dossier, f, indent=1, default=str)
            os.replace(tmp, path)
            existing = sorted(
                entry for entry in os.listdir(out_dir)
                if entry.startswith("dossier-")
                and entry.endswith(f"-{reason_slug}.json")
            )
            for stale in existing[:-MAX_DOSSIER_FILES]:
                try:
                    os.unlink(os.path.join(out_dir, stale))
                except OSError:  # raydp-lint: disable=swallowed-exceptions (a racing prune already removed it)
                    pass
            return path
        except OSError:
            from raydp_tpu import obs

            obs.log.warning(
                "crash dossier write failed", exc_info=True, dir=out_dir
            )
            return None


def _decode_sections(rings: List[dict]) -> List[dict]:
    """Lift each victim ring's newest decode-engine state note (the ~1/s
    ``serve.decode.state`` log the engine loop emits: in-flight streams with
    tokens emitted + KV lengths, queue depth, page-table summary) plus the
    latest ``serve.decode.*`` / ``serve.kv.*`` gauges from its metrics tail
    into a top-level ``decode`` dossier section — the first thing to read
    after a mid-decode replica death. Empty list when no ring ever decoded
    (the dossier then omits the section entirely)."""
    sections: List[dict] = []
    for ring in rings:
        state = None
        for record in reversed(ring.get("logs") or []):
            if record.get("message") == "serve.decode.state":
                state = {
                    "ts": record.get("ts"),
                    "fields": record.get("fields") or {},
                }
                break
        gauges: Dict[str, Any] = {}
        tail = ring.get("metrics_tail") or []
        if tail:
            newest = tail[-1].get("metrics") or {}
            for name, snap in newest.items():
                if name.startswith(("serve.decode.", "serve.kv.")):
                    gauges[name] = snap
        if state is not None or gauges:
            sections.append({
                "proc": ring.get("proc"),
                "role": ring.get("role"),
                "state": state,
                "metrics": gauges,
            })
    return sections


def _slug(text: str) -> str:
    return "".join(
        ch if (ch.isalnum() or ch in "-_") else "-" for ch in str(text)
    )[:48] or "event"


def list_dossiers(out_dir: str) -> List[str]:
    """Dossier files in ``out_dir``, oldest first (tooling/CI helper)."""
    try:
        return sorted(
            os.path.join(out_dir, entry) for entry in os.listdir(out_dir)
            if entry.startswith("dossier-") and entry.endswith(".json")
        )
    except OSError:
        return []
