"""Cluster-wide observability: tracing, metrics, structured logging, export.

One instrumentation plane for the whole runtime (SURVEY §5: the reference
defers everything to the Ray/Spark dashboards; we own the runtime, so we own
the telemetry). Three pieces:

- **Tracing** (`obs.span` / `obs.instant`): lightweight spans buffered in a
  per-process ring buffer and shipped to the head, with trace/span ids
  propagated inside control-plane RPC frames so one query or one ``fit()``
  yields a single causally-linked trace across driver, head, agents and
  executors. Disabled by default (``RAYDP_TPU_TRACE=1`` enables shipping);
  the disabled fast path is one branch per span.
- **Metrics** (`obs.metrics`): an always-on process-local registry of
  counters/gauges/histograms (RPC latency, store bytes, dispatch batches,
  task retries, streaming idle, estimator step/compile time), pushed to the
  head with each trace flush and queryable via ``cluster.dump_metrics()``.
- **Export** (`obs.export_trace`): writes Chrome-trace/Perfetto JSON — one
  track per process/actor, spans plus instant events for retries/restarts/
  fusion decisions. ``last_query_stats`` and estimator timings are derived
  from the SAME spans, not parallel hand-rolled timers.

This module is import-light by design (stdlib only): it is imported by the
zygote and by ``python -S`` worker processes.
"""

from __future__ import annotations

from raydp_tpu.obs.logging import get_logger, log
from raydp_tpu.obs.metrics import metrics
from raydp_tpu.obs.tracing import (
    collect,
    current_context,
    current_sinks,
    enabled,
    flush,
    flush_throttled,
    instant,
    mint_context,
    record_span,
    set_process_role,
    span,
    use_context,
    use_sinks,
    with_context,
)

__all__ = [
    "collect",
    "current_context",
    "current_sinks",
    "enabled",
    "explain_last_query",
    "export_trace",
    "flush",
    "flush_throttled",
    "get_logger",
    "instant",
    "log",
    "metrics",
    "mint_context",
    "profile_fit",
    "query_local_series",
    "record_span",
    "sample_memory",
    "set_process_role",
    "span",
    "use_context",
    "use_sinks",
    "with_context",
]


def export_trace(path: str) -> str:
    """Write the collected cluster trace as Chrome-trace/Perfetto JSON.
    Lazy import: export touches the cluster API, which span/metric call
    sites inside the cluster layer itself must never pull in at import."""
    from raydp_tpu.obs.export import export_trace as _export

    return _export(path)


def dump_metrics() -> dict:
    from raydp_tpu.obs.export import dump_metrics as _dump

    return _dump()


def explain_last_query(session=None, top_k: int = 5) -> dict:
    """Critical-path wall-time attribution of the active session's last
    query (obs/analysis.py). Lazy import: the analyzer touches the session
    layer, which obs call sites inside it must never pull in at import."""
    from raydp_tpu.obs.analysis import explain_last_query as _explain

    return _explain(session=session, top_k=top_k)


def query_local_series(name: str, window_s: float = 60.0, labels=None):
    """This process's windowed time-series mirror (obs/timeseries.py) —
    what in-process controllers read; ``cluster.query_metrics`` is the
    cluster-wide (head TSDB) flavor."""
    from raydp_tpu.obs.timeseries import query_local

    return query_local(name, window_s, labels)


def profile_fit(steps: int = 16, out_dir=None, jax_trace: bool = True):
    """Arm a bounded fit capture window (obs/profiler.py): the jax deep
    trace covers the first ``steps`` train steps, the span capture the
    whole ``with`` body. Lazy import: the profiler touches jax on demand."""
    from raydp_tpu.obs.profiler import profile_fit as _profile_fit

    return _profile_fit(steps=steps, out_dir=out_dir, jax_trace=jax_trace)


def sample_memory(force: bool = False):
    """Sample this process's memory watermark plane now (obs/profiler.py);
    normally rides every telemetry flush tick automatically."""
    from raydp_tpu.obs.profiler import sample_memory as _sample

    return _sample(force=force)
