"""Structured logging for runtime processes.

Replaces bare ``print``/``traceback.print_exc`` diagnostics in the cluster
layer: every line carries a wall timestamp, the process role, and the actor
id, so crash output interleaved from dozens of processes in the session dir's
log files is attributable. Stdlib-only and import-light (the zygote and
``python -S`` workers load this).

Usage::

    from raydp_tpu import obs
    obs.log.error("actor init failed", exc_info=True)
    obs.log.info("respawning", actor_id=aid, incarnation=2)
"""

from __future__ import annotations

import os
import sys
import time
import traceback


class StructuredLogger:
    """Writes ``ts level [role actor] message key=value...`` lines to stderr
    (the per-process ``.err`` files the spawner already redirects there)."""

    def __init__(self, role: str = ""):
        self._role = role

    def _emit(self, level: str, message: str, exc_info: bool, fields: dict) -> None:
        from raydp_tpu.obs.tracing import process_role

        role = self._role or process_role()
        actor = os.environ.get("RAYDP_TPU_ACTOR_ID", "")
        try:
            # flight recorder (obs/recorder.py): every structured line also
            # lands in the process's bounded ring and ships with the next
            # telemetry flush, so a crash dossier carries the victim's last
            # log lines, not just its spans
            from raydp_tpu.obs.recorder import note_log

            note_log(level, role, message, fields)
        except Exception:  # raydp-lint: disable=swallowed-exceptions (logging must never fail because the recorder could not import mid-teardown)
            pass
        ts = time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime())
        parts = [ts, level, f"[{role}" + (f" {actor}" if actor else "") + "]", message]
        if fields:
            parts.append(" ".join(f"{k}={v!r}" for k, v in fields.items()))
        line = " ".join(parts)
        if exc_info:
            line += "\n" + traceback.format_exc().rstrip()
        try:
            sys.stderr.write(line + "\n")
            sys.stderr.flush()
        except (OSError, ValueError):  # raydp-lint: disable=swallowed-exceptions (a closed stderr at teardown must never raise)
            pass  # a closed stderr at teardown must never raise

    def info(self, message: str, exc_info: bool = False, **fields) -> None:
        self._emit("INFO", message, exc_info, fields)

    def warning(self, message: str, exc_info: bool = False, **fields) -> None:
        self._emit("WARN", message, exc_info, fields)

    def error(self, message: str, exc_info: bool = False, **fields) -> None:
        self._emit("ERROR", message, exc_info, fields)

    def exception(self, message: str, **fields) -> None:
        self._emit("ERROR", message, True, fields)


log = StructuredLogger()


def get_logger(role: str) -> StructuredLogger:
    return StructuredLogger(role)
