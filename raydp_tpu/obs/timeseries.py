"""Windowed time-series over the metrics registry: the live half of the
telemetry plane.

The PR 2 metrics plane is pull-at-the-end: ``dump_metrics()`` returns
lifetime cumulative snapshots with no time dimension. This module adds the
time axis without touching a single instrument call site: every
``obs_ingest`` flush already carries a process's cumulative registry
snapshot, and a :class:`SeriesStore` turns successive snapshots into bounded
per-``(metric, labels)`` point rings —

- **counters** keep their cumulative value per point (the Prometheus
  convention; ``windowed()`` computes the delta over a window),
- **gauges** keep the sampled value,
- **histograms** fan out into ``<name>.count`` / ``<name>.sum`` (cumulative)
  plus ``<name>.p50`` / ``<name>.p99`` gauge series from the reservoir
  snapshot — the shape SLO controllers want.

Labels are derived from the metric name and the shipping process:
``tenant.<ns>.<metric>`` series normalize to name ``tenant.<metric>`` with a
``tenant="<ns>"`` label (one series family across tenants, the per-tenant
axis queryable), and every series carries ``role`` (driver/head/worker/...)
plus ``proc`` (``role:pid``) so per-process and per-role reads both work.

Two deployments of the same store:

- the **head TSDB** (one per cluster, fed by every process's flushes) backs
  the Prometheus scrape endpoint (:class:`ScrapeServer` — stdlib TCP, one
  exposition-format response per connection) and the ``obs_query_series``
  head op behind ``cluster.query_metrics(name, window_s)``;
- a **process-local mirror** (``local_store``, fed by this process's own
  ``flush()``) gives in-process controllers — the serve autoscaler foremost
  — the same windowed signal without an RPC per tick (``query_local``).

Stdlib only; importable by ``python -S`` workers and the head.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

# points kept per series: at the ~1s flush cadence this is ~10 minutes of
# history — enough for any windowed controller read or scrape, bounded
# regardless of how chatty the cluster is
DEFAULT_POINTS_CAP = 600

# a process flushing faster than this (executors flush per dispatch) does
# not grow the rings faster: extra snapshots within the interval are folded
# into the latest point instead of appended
MIN_POINT_INTERVAL_S = 0.25

# series whose newest point is older than this are dropped (swept
# opportunistically during ingest): a long-lived cluster with executor /
# replica / tenant churn mints new per-proc label sets continuously, and
# without retention the store — and every scrape response — would grow
# monotonically with each dead pid
SERIES_RETENTION_S = 900.0
_RETENTION_SWEEP_EVERY = 256  # ingests between sweeps


def split_labels(name: str, role: str, proc_key: str) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    """(series name, sorted label items) for one raw metric name.

    ``tenant.<ns>.<metric>`` becomes (``tenant.<metric>``,
    ``tenant=<ns>``); every series carries ``role`` (the class part of the
    process role — ``worker:actor-ab12`` ships as role ``worker``) and
    ``proc`` (the full ``role:pid`` key, the per-process axis)."""
    labels = {"role": role.split(":", 1)[0] or "proc", "proc": proc_key}
    if name.startswith("tenant.") and name.count(".") >= 2:
        _, ns, rest = name.split(".", 2)
        if rest and ns != "":
            name = f"tenant.{rest}"
            labels["tenant"] = ns
    return name, tuple(sorted(labels.items()))


class _Series:
    __slots__ = ("name", "labels", "kind", "points")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 kind: str, cap: int):
        self.name = name
        self.labels = labels
        self.kind = kind  # "counter" | "gauge"
        self.points: collections.deque = collections.deque(maxlen=cap)

    def add(self, ts: float, value: float, fold: bool) -> None:
        if self.kind == "counter" and self.points and value < self.points[-1][1]:
            # counters are monotone by construction, so a LOWER incoming
            # value is a stale snapshot that lost the ingest race (two
            # flushes from one process interleaving after the RPC) — drop
            # it rather than write a non-monotone point that would corrupt
            # windowed deltas; a genuine registry reset self-heals once the
            # counter catches back up
            return
        if fold and self.points and ts - self.points[-1][0] < MIN_POINT_INTERVAL_S:
            self.points[-1] = (self.points[-1][0], value)
        else:
            self.points.append((ts, value))


class SeriesStore:
    """Bounded ring TSDB keyed ``(metric, labels)``; see module docstring."""

    def __init__(self, points_cap: int = DEFAULT_POINTS_CAP):
        from raydp_tpu.sanitize import named_lock

        self._lock = named_lock("obs.timeseries", threading.Lock())
        self._cap = int(points_cap)
        self._series: Dict[Tuple[str, tuple], _Series] = {}  # guarded-by: self._lock
        self._ingests = 0

    # -- write side ------------------------------------------------------

    def ingest(self, proc_key: str, role: str, snapshot: Dict[str, dict],
               ts: Optional[float] = None) -> None:
        """Fold one process's cumulative registry snapshot into the rings.
        Cheap: one dict walk; histogram snapshots fan out into 4 scalar
        series. Thread-safe (flush paths from any thread may land here)."""
        if not snapshot:
            return
        ts = time.time() if ts is None else ts
        flat: List[Tuple[str, str, float]] = []
        for raw_name, snap in snapshot.items():
            kind = snap.get("type")
            if kind == "counter":
                flat.append((raw_name, "counter", float(snap.get("value", 0.0))))
            elif kind == "gauge":
                flat.append((raw_name, "gauge", float(snap.get("value", 0.0))))
                if snap.get("max") is not None:
                    # high-watermark gauges (memory plane) fan a .max peak
                    # series out alongside the live value
                    flat.append((f"{raw_name}.max", "gauge",
                                 float(snap["max"])))
            elif kind == "histogram":
                flat.append((f"{raw_name}.count", "counter",
                             float(snap.get("count", 0))))
                flat.append((f"{raw_name}.sum", "counter",
                             float(snap.get("sum", 0.0))))
                # max is the watermark axis (per-step H2D spikes, memory
                # highs): a gauge series like the quantiles
                for q in ("p50", "p99", "max"):
                    if snap.get(q) is not None:
                        flat.append((f"{raw_name}.{q}", "gauge",
                                     float(snap[q])))
        with self._lock:
            self._ingests += 1
            for raw_name, kind, value in flat:
                name, labels = split_labels(raw_name, role, proc_key)
                key = (name, labels)
                series = self._series.get(key)
                if series is None:
                    series = self._series[key] = _Series(
                        name, labels, kind, self._cap
                    )
                series.add(ts, value, fold=True)
            if self._ingests % _RETENTION_SWEEP_EVERY == 0:
                cutoff = ts - SERIES_RETENTION_S
                for key in [
                    k for k, s in self._series.items()
                    if not s.points or s.points[-1][0] < cutoff
                ]:
                    del self._series[key]

    # -- read side -------------------------------------------------------

    def query(self, name: str, window_s: float = 60.0,
              labels: Optional[Dict[str, str]] = None) -> List[dict]:
        """Every series matching ``name`` (and the label filter), with its
        points clipped to the trailing window plus derived values: ``last``
        (newest point), and for counters ``delta`` (increase over the
        window — the rate numerator controllers want)."""
        cutoff = time.time() - float(window_s)
        out: List[dict] = []
        with self._lock:
            # points are copied UNDER the lock: a concurrent ingest appending
            # to a deque mid-iteration would raise (and lose the read)
            matches = [
                (s, list(s.points))
                for (n, _), s in self._series.items() if n == name
            ]
        for series, points in matches:
            lab = dict(series.labels)
            if labels and any(lab.get(k) != v for k, v in labels.items()):
                continue
            pts = [(ts, v) for ts, v in points if ts >= cutoff]
            if not pts:
                continue
            entry = {
                "name": series.name,
                "labels": lab,
                "type": series.kind,
                "points": pts,
                "last": pts[-1][1],
            }
            if series.kind == "counter":
                entry["delta"] = pts[-1][1] - pts[0][1]
            out.append(entry)
        return out

    def windowed(self, name: str, window_s: float = 60.0,
                 labels: Optional[Dict[str, str]] = None) -> dict:
        """One aggregate across all matching series: ``delta`` summed for
        counters, ``last`` summed and ``max`` over per-series maxima for
        gauges — the single-number read a controller wants."""
        series = self.query(name, window_s, labels)
        agg = {"series": len(series), "delta": 0.0, "last": 0.0, "max": None}
        for entry in series:
            agg["delta"] += entry.get("delta", 0.0)
            agg["last"] += entry["last"]
            peak = max(v for _, v in entry["points"])
            agg["max"] = peak if agg["max"] is None else max(agg["max"], peak)
        return agg

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted({n for (n, _) in self._series})

    # -- Prometheus exposition ------------------------------------------

    def prometheus_text(self) -> str:
        """The newest point of every series in the Prometheus text
        exposition format (one scrape = the cluster's live state). Series
        names are prefixed ``raydp_`` with dots mapped to underscores;
        counters get the conventional ``_total`` suffix."""
        with self._lock:
            series = [
                (s, s.points[-1]) for s in self._series.values() if s.points
            ]
        lines: List[str] = []
        seen_types: set = set()
        for s, newest in sorted(series, key=lambda e: (e[0].name, e[0].labels)):
            prom = "raydp_" + _prom_name(s.name)
            if s.kind == "counter":
                prom += "_total"
            if prom not in seen_types:
                seen_types.add(prom)
                lines.append(f"# TYPE {prom} {s.kind}")
            label_str = ",".join(
                f'{_prom_name(k)}="{_prom_escape(v)}"' for k, v in s.labels
            )
            ts, value = newest
            lines.append(
                f"{prom}{{{label_str}}} {value:.10g} {int(ts * 1000)}"
            )
        return "\n".join(lines) + "\n"


def _prom_name(name: str) -> str:
    return "".join(
        ch if (ch.isalnum() or ch == "_") else "_" for ch in name
    )


def _prom_escape(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def parse_prometheus_text(text: str) -> Dict[str, Dict[tuple, float]]:
    """Parse the exposition format back into
    ``{metric: {sorted-label-items: value}}`` — the test/tooling half of the
    round trip (scrape → parse → compare against ``dump_metrics``)."""
    out: Dict[str, Dict[tuple, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            label_str, tail = rest.split("}", 1)
            labels = {}
            for part in _split_labels_text(label_str):
                if not part:
                    continue
                k, v = part.split("=", 1)
                labels[k] = v.strip('"').replace('\\"', '"').replace("\\\\", "\\")
            fields = tail.split()
        else:
            fields = line.split()
            name = fields[0]
            fields = fields[1:]
            labels = {}
        if not fields:
            continue
        out.setdefault(name, {})[tuple(sorted(labels.items()))] = float(fields[0])
    return out


def _split_labels_text(label_str: str) -> List[str]:
    parts, depth_quote, cur = [], False, []
    for ch in label_str:
        if ch == '"' and (not cur or cur[-1] != "\\"):
            depth_quote = not depth_quote
        if ch == "," and not depth_quote:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


# ---------------------------------------------------------------------------
# scrape endpoint: one stdlib TCP socket serving the exposition text
# ---------------------------------------------------------------------------


class ScrapeServer:
    """A minimal HTTP/1.0 responder over a plain TCP socket: every
    connection gets one ``200 text/plain`` response holding
    ``store.prometheus_text()`` and is closed — exactly the contract a
    Prometheus scraper (or ``curl``) needs, with no http.server import in
    the head's hot path. Default bind is loopback; conf ``obs.scrape_port``
    picks the port (0 = ephemeral, reported back to the session)."""

    def __init__(self, store: SeriesStore, port: int = 0,
                 host: str = "127.0.0.1",
                 extra_text_fn=None):
        import socket as _socket

        self._store = store
        self._extra_text_fn = extra_text_fn
        self._sock = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        self._sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(8)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name="obs-scrape", daemon=True
        )
        self._thread.start()

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by close()
            # one short-lived thread per connection: a silent client (port
            # scanner, half-open probe) blocking in recv for its 5s timeout
            # must not head-of-line-block a real scraper on its interval
            threading.Thread(
                target=self._respond, args=(conn,),
                name="obs-scrape-conn", daemon=True,
            ).start()

    def _respond(self, conn) -> None:
        try:
            conn.settimeout(5.0)
            # drain the request head (we serve one document regardless
            # of the path, so the contents only need to be consumed)
            try:
                conn.recv(4096)
            except OSError:  # raydp-lint: disable=swallowed-exceptions (a scraper that connects and says nothing still gets the document)
                pass
            body = self._store.prometheus_text()
            if self._extra_text_fn is not None:
                try:
                    body += self._extra_text_fn()
                except Exception:  # raydp-lint: disable=swallowed-exceptions (extra text is best-effort; the core exposition must still serve)
                    pass
            payload = body.encode("utf-8")
            head = (
                "HTTP/1.0 200 OK\r\n"
                "Content-Type: text/plain; version=0.0.4\r\n"
                f"Content-Length: {len(payload)}\r\n"
                "Connection: close\r\n\r\n"
            )
            conn.sendall(head.encode("ascii") + payload)
        except OSError:  # raydp-lint: disable=swallowed-exceptions (a scraper hanging up mid-response is its problem, not the head's)
            pass
        finally:
            try:
                conn.close()
            except OSError:  # raydp-lint: disable=swallowed-exceptions (double-close race on a reset connection)
                pass

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:  # raydp-lint: disable=swallowed-exceptions (already closed)
            pass
        self._thread.join(timeout=2.0)


def scrape(host: str, port: int, timeout: float = 5.0) -> str:
    """Fetch one exposition document from a scrape endpoint (test/tool
    helper; any HTTP client works too)."""
    import socket as _socket

    with _socket.create_connection((host, int(port)), timeout=timeout) as sock:
        sock.sendall(b"GET /metrics HTTP/1.0\r\n\r\n")
        chunks = []
        while True:
            got = sock.recv(65536)
            if not got:
                break
            chunks.append(got)
    raw = b"".join(chunks).decode("utf-8", "replace")
    if "\r\n\r\n" in raw:
        return raw.split("\r\n\r\n", 1)[1]
    return raw


# ---------------------------------------------------------------------------
# process-local mirror: the in-process consumers' windowed view
# ---------------------------------------------------------------------------

# fed by tracing.flush() with this process's own snapshot, so controllers
# (serve autoscaler, tenancy policies) read the same windowed series a
# scrape of the head would show — one signal, two transports
local_store = SeriesStore()


def ingest_local(snapshot: Dict[str, dict]) -> None:
    import os

    from raydp_tpu.obs.tracing import process_role

    role = process_role()
    local_store.ingest(f"{role}:{os.getpid()}", role, snapshot)


def query_local(name: str, window_s: float = 60.0,
                labels: Optional[Dict[str, str]] = None) -> List[dict]:
    return local_store.query(name, window_s, labels)


def windowed_local(name: str, window_s: float = 60.0,
                   labels: Optional[Dict[str, str]] = None) -> dict:
    return local_store.windowed(name, window_s, labels)
