"""Span API: per-process ring buffer + trace-context propagation.

A span is a plain dict (picklable, cheap): ``{name, ts, dur, pid, tid, proc,
trace, id, parent, args}`` with ``ts``/``dur`` in microseconds of wall time
(``time.time_ns`` — one comparable timeline across processes on a machine;
multi-host traces carry each host's clock, see docs/observability.md).

Two consumers, decoupled:

- **collectors** (thread-local, always available): ``with collect() as got:``
  captures every span finished on this thread — the planner derives
  ``last_query_stats`` from these, so query stats work with tracing OFF.
- **the ring buffer** (process-global, gated on ``RAYDP_TPU_TRACE``):
  finished spans buffer here and ship to the head on ``flush()`` / atexit /
  buffer pressure. With tracing disabled and no collector installed,
  ``span()`` returns a shared no-op after ONE branch — the hot-path cost the
  ISSUE budget allows.

Context: ``(trace_id, span_id)`` pairs travel thread-locally; ``span()``
parents under the current context and installs itself for its body. RPC
clients attach the current context to outgoing frames (common.rpc /
ActorHandle) and servers adopt it around the handled call, so causality
crosses process boundaries without any span caring.
"""

from __future__ import annotations

import atexit
import collections
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

TRACE_ENV = "RAYDP_TPU_TRACE"
BUFFER_ENV = "RAYDP_TPU_TRACE_BUFFER"

_enabled = os.environ.get(TRACE_ENV, "0") not in ("", "0", "false", "False")
_buffer_cap = int(os.environ.get(BUFFER_ENV, "8192") or "8192")

from raydp_tpu.sanitize import named_lock as _named_lock

_tls = threading.local()
_buf_lock = _named_lock("obs._buf_lock")
_buffer: "collections.deque" = collections.deque(maxlen=_buffer_cap)
_dropped = 0  # spans evicted from the ring before a flush shipped them

# what this process calls itself in the trace (one Perfetto track per proc)
_role: str = "driver"


def set_process_role(role: str) -> None:
    """Label this process's track ("head" / "agent" / "worker:<actor-id>" /
    "zygote"); the driver default stands when nothing claims otherwise."""
    global _role
    _role = role


def process_role() -> str:
    # a worker process that never called set_process_role still labels
    # itself from its spawn environment
    global _role
    if _role == "driver":
        actor_id = os.environ.get("RAYDP_TPU_ACTOR_ID")
        if actor_id:
            _role = f"worker:{actor_id}"
    return _role


def enabled() -> bool:
    """Is trace shipping on? (Collectors work either way.)"""
    return _enabled


def set_enabled(value: bool) -> None:
    """Test/bench hook; prefer setting RAYDP_TPU_TRACE before process start
    so spawned actors inherit it."""
    global _enabled
    _enabled = bool(value)


def reinit_for_process(role: str) -> None:
    """Reset per-process tracing state after fork/exec into a new runtime
    role. Zygote-forked workers inherit the ZYGOTE's enablement and buffer;
    the session that requested the fork decides tracing (its env rode in
    with the fork request), so re-read the environment and start clean."""
    global _enabled, _dropped
    set_process_role(role)
    _enabled = os.environ.get(TRACE_ENV, "0") not in ("", "0", "false", "False")
    with _buf_lock:
        _buffer.clear()
    _dropped = 0


def _collectors() -> List[list]:
    got = getattr(_tls, "collectors", None)
    if got is None:
        got = _tls.collectors = []
    return got


def current_context() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) the next span parents under, or None."""
    return getattr(_tls, "ctx", None)


def _set_context(ctx: Optional[Tuple[str, str]]) -> None:
    _tls.ctx = ctx


class use_context:
    """Adopt a remote caller's (trace_id, span_id) for a code region — the
    server half of cross-process propagation."""

    def __init__(self, ctx: Optional[Tuple[str, str]]):
        self._ctx = tuple(ctx) if ctx else None
        self._saved: Optional[Tuple[str, str]] = None

    def __enter__(self):
        self._saved = current_context()
        if self._ctx is not None:
            _set_context(self._ctx)
        return self

    def __exit__(self, *exc):
        _set_context(self._saved)


def with_context(ctx, fn, *args, **kwargs):
    """Run ``fn`` under ``ctx`` — for handing the caller's trace context to
    worker-pool threads (thread-locals don't cross threads)."""
    with use_context(ctx):
        return fn(*args, **kwargs)


class _NoopSpan:
    """Shared do-nothing span for the disabled fast path."""

    __slots__ = ()
    duration = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NOOP = _NoopSpan()


class Span:
    __slots__ = ("name", "args", "trace", "id", "parent", "_t0", "_ts",
                 "duration", "_saved_ctx", "_ship")

    def __init__(self, name: str, args: Dict[str, Any], ship: bool):
        self.name = name
        self.args = args
        ctx = current_context()
        if ctx is None:
            self.trace = uuid.uuid4().hex[:16]
            self.parent = None
        else:
            self.trace, self.parent = ctx
        self.id = uuid.uuid4().hex[:16]
        self._ship = ship
        self._saved_ctx = ctx
        self.duration = 0.0
        self._ts = time.time_ns() // 1000
        self._t0 = time.perf_counter()

    def set(self, **attrs) -> "Span":
        self.args.update(attrs)
        return self

    def __enter__(self) -> "Span":
        _set_context((self.trace, self.id))
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration = time.perf_counter() - self._t0
        _set_context(self._saved_ctx)
        if exc_type is not None:
            self.args.setdefault("error", exc_type.__name__)
        record = {
            "name": self.name,
            "ts": self._ts,
            "dur": int(self.duration * 1e6),
            "pid": os.getpid(),
            "tid": threading.get_ident() % 1_000_000,
            "proc": process_role(),
            "trace": self.trace,
            "id": self.id,
            "parent": self.parent,
            "args": self.args,
        }
        for sink in _collectors():
            sink.append(record)
        if self._ship:
            _buffer_append(record)
        return False


def span(name: str, **attrs):
    """Start a span. Context-manager; ``with obs.span("etl.stage", n=4) as s``.
    Disabled + no collector → shared no-op (one branch)."""
    if not _enabled and not getattr(_tls, "collectors", None):
        return _NOOP
    return Span(name, attrs, _enabled)


def instant(name: str, **attrs) -> None:
    """A zero-duration marker event (task retry, actor restart, fusion
    decision). Same gating as span()."""
    if not _enabled and not getattr(_tls, "collectors", None):
        return
    record = {
        "name": name,
        "ts": time.time_ns() // 1000,
        "dur": 0,
        "ph": "i",
        "pid": os.getpid(),
        "tid": threading.get_ident() % 1_000_000,
        "proc": process_role(),
        "trace": (current_context() or (uuid.uuid4().hex[:16],))[0],
        "id": uuid.uuid4().hex[:16],
        "parent": (current_context() or (None, None))[1],
        "args": attrs,
    }
    for sink in _collectors():
        sink.append(record)
    if _enabled:
        _buffer_append(record)


def record_span(
    name: str,
    ts_us: int,
    dur_us: int,
    trace: str,
    span_id: Optional[str] = None,
    parent: Optional[str] = None,
    **attrs,
) -> dict:
    """Emit a span RECORD for an interval measured elsewhere — the serving
    plane's request-path spans are assembled from per-request timestamps
    AFTER the request resolves (a live ``span()`` context manager cannot
    straddle the admission queue, the batch, and the dispatcher thread).
    Same consumers as ``Span.__exit__``: collectors and, when shipping is
    on, the ring buffer. Returns the record (its ``id`` links children)."""
    record = {
        "name": name,
        "ts": int(ts_us),
        "dur": max(0, int(dur_us)),
        "pid": os.getpid(),
        "tid": threading.get_ident() % 1_000_000,
        "proc": process_role(),
        "trace": trace,
        "id": span_id or uuid.uuid4().hex[:16],
        "parent": parent,
        "args": attrs,
    }
    for sink in _collectors():
        sink.append(record)
    if _enabled:
        _buffer_append(record)
    return record


def mint_context() -> Tuple[str, str]:
    """A fresh (trace_id, span_id) pair for a root minted out-of-band (the
    serve request path samples requests at admission and emits their spans
    at resolution via ``record_span``)."""
    return uuid.uuid4().hex[:16], uuid.uuid4().hex[:16]


def current_sinks() -> List[list]:
    """This thread's active collector sinks — capture them before handing
    work to a helper thread, and re-install there with ``use_sinks`` so the
    helper's spans still land in the same query's stats."""
    return list(_collectors())


class use_sinks:
    """Adopt another thread's collector sinks for a code region (the
    collector half of cross-THREAD propagation; ``use_context`` is the
    trace-id half). Appends are GIL-atomic, so two threads sharing a sink
    list interleave records without corruption."""

    def __init__(self, sinks: List[list]):
        self._sinks = list(sinks)

    def __enter__(self):
        _collectors().extend(self._sinks)
        return self

    def __exit__(self, *exc):
        got = _collectors()
        for sink in self._sinks:
            for i in range(len(got) - 1, -1, -1):
                if got[i] is sink:
                    del got[i]
                    break


class collect:
    """Capture every span/instant finished on THIS thread into a list —
    the local-stats consumer (planner query stats, task phase timing).
    Nesting composes: inner collectors see only their own region."""

    def __init__(self):
        self.records: List[dict] = []

    def __enter__(self) -> List[dict]:
        _collectors().append(self.records)
        return self.records

    def __exit__(self, *exc):
        # remove by IDENTITY: list.remove matches by equality and two empty
        # sink lists compare equal — nested collectors would detach each
        # other's sinks
        sinks = _collectors()
        for i in range(len(sinks) - 1, -1, -1):
            if sinks[i] is self.records:
                del sinks[i]
                break


_flush_inflight = threading.Event()


def _buffer_append(record: dict) -> None:
    global _dropped
    start_flush = False
    with _buf_lock:
        if len(_buffer) == _buffer.maxlen:
            _dropped += 1
        _buffer.append(record)
        if len(_buffer) >= (_buffer.maxlen or 1) // 2:
            # pressure flush on a background thread: a filling ring must not
            # stall the instrumented call site, nor silently drop — and at
            # most one flusher runs at a time
            start_flush = not _flush_inflight.is_set()
            if start_flush:
                _flush_inflight.set()
    if start_flush:
        threading.Thread(target=_pressure_flush, daemon=True).start()


def _pressure_flush() -> None:
    try:
        flush()
    finally:
        _flush_inflight.clear()


def drain_local() -> List[dict]:
    """Remove and return this process's buffered spans (flush/export path)."""
    with _buf_lock:
        out = list(_buffer)
        _buffer.clear()
    return out


def dropped_count() -> int:
    return _dropped


def flush() -> bool:
    """Ship buffered spans + the metrics snapshot to the head. Safe to call
    anywhere: no cluster, no session, or a dead head all degrade to keeping
    the spans local (they are re-buffered for the next attempt). The head
    process itself ingests directly — no RPC to self."""
    global _dropped
    from raydp_tpu.obs.metrics import metrics

    # memory watermark plane: every flush tick samples this process's
    # rss / shm-namespace / device bytes + pressure into the registry
    # FIRST, so the snapshot shipped below carries fresh mem.* gauges
    # (self-throttled to ~1s inside sample_memory; never raises)
    try:
        from raydp_tpu.obs.profiler import sample_memory

        sample_memory()
    except Exception:  # raydp-lint: disable=swallowed-exceptions (the memory sampler must never block a telemetry flush)
        pass

    spans = drain_local()
    snapshot = metrics.snapshot()
    if not spans and not snapshot:
        return True
    # the process-local time-series mirror rides the same tick: in-process
    # controllers (serve autoscaler, tenancy policies) get the identical
    # windowed signal a head scrape would show
    try:
        from raydp_tpu.obs import timeseries as _ts

        _ts.ingest_local(snapshot)
    except Exception:  # raydp-lint: disable=swallowed-exceptions (the local mirror must never block shipping to the head)
        pass
    # flight-recorder log ring: shipped alongside spans/metrics so the head
    # holds every process's recent log lines for crash dossiers
    from raydp_tpu.obs import recorder as _recorder

    logs = _recorder.drain_logs()
    proc = {"pid": os.getpid(), "role": process_role(), "dropped": _dropped}
    try:
        # the head's direct-ingest hook comes FIRST: the head process has
        # neither an initialized cluster API nor RAYDP_TPU_SESSION in its
        # env, so the cluster guard below would otherwise fail every head
        # flush and park head spans in the (smaller) process ring forever
        ingest = _local_ingest
        if ingest is not None:
            ingest(proc=proc, spans=spans, metrics_snapshot=snapshot,
                   logs=logs)
            return True
        from raydp_tpu.cluster import api as cluster_api

        if not cluster_api.is_initialized() and not os.environ.get(
            "RAYDP_TPU_SESSION"
        ):
            raise RuntimeError("no cluster")
        cluster_api.head_rpc(
            "obs_ingest", proc=proc, spans=spans,
            metrics_snapshot=snapshot, logs=logs, timeout=10.0,
        )
        return True
    except Exception:
        with _buf_lock:
            # re-buffer into the space left, preferring the NEWEST of the
            # failed batch (appendleft on a full deque would silently evict
            # from the right — i.e. drop spans recorded DURING the failed
            # flush); anything that doesn't fit is counted as dropped
            space = (_buffer.maxlen or 0) - len(_buffer)
            kept = spans[-space:] if space > 0 else []
            _dropped += len(spans) - len(kept)
            for record in reversed(kept):
                _buffer.appendleft(record)
        _recorder.requeue_logs(logs)
        return False


_last_flush = 0.0


def flush_throttled(min_interval: float = 0.5) -> None:
    """flush() at most every ``min_interval`` seconds — the per-dispatch
    ship point for processes that may be SIGKILLed (executors), cheap enough
    to call on every task. Runs with tracing OFF too: the metrics registry
    is always on, and its snapshots reach ``dump_metrics()`` this way."""
    global _last_flush
    now = time.monotonic()
    if now - _last_flush >= min_interval:
        _last_flush = now
        flush()


# set by the head process so its own spans skip the RPC loopback
_local_ingest = None


def set_local_ingest(fn) -> None:
    global _local_ingest
    _local_ingest = fn


@atexit.register
def _flush_at_exit() -> None:  # pragma: no cover - exit path
    if _enabled:
        try:
            flush()
        except Exception:  # raydp-lint: disable=swallowed-exceptions (atexit flush: the logging plane may already be gone)
            pass
