"""Trace + metrics export: head aggregation → Chrome-trace/Perfetto JSON.

``export_trace(path)`` flushes this process, pulls everything the head has
collected (every process ships its ring buffer there), merges the driver's
local view, and writes the Chrome trace-event format Perfetto loads directly
(https://ui.perfetto.dev → open file): complete events (``ph: "X"`` with
``ts``/``dur`` in microseconds), instant events (``ph: "i"``), and process-
name metadata events so each runtime process gets a labeled track.

Works degraded with no cluster running: exports the local buffer only.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List


def _gather(drain: bool = True) -> Dict[str, Any]:
    """Everything observable right now: head-aggregated spans/metrics merged
    with this process's local leftovers. ``drain=False`` (the metrics-only
    callers) leaves unshipped spans IN the local ring — a metrics read must
    never destroy trace data a later export would have written."""
    from raydp_tpu.obs.metrics import metrics
    from raydp_tpu.obs.tracing import drain_local, flush, process_role

    flush()  # best-effort: puts the local buffer on the head when possible
    spans: List[dict] = []
    proc_metrics: Dict[str, dict] = {}
    try:
        from raydp_tpu.cluster import api as cluster_api

        if cluster_api.is_initialized() or os.environ.get("RAYDP_TPU_SESSION"):
            dump = cluster_api.head_rpc("obs_dump", timeout=30.0)
            spans.extend(dump.get("spans", []))
            proc_metrics.update(dump.get("metrics", {}))
    except Exception:  # raydp-lint: disable=swallowed-exceptions (no cluster (or dead head): local-only export below)
        pass  # no cluster (or a dead head): local-only export below
    if drain:
        spans.extend(drain_local())  # anything the flush could not ship
    local_key = f"{process_role()}:{os.getpid()}"
    snapshot = metrics.snapshot()
    if snapshot:
        proc_metrics.setdefault(local_key, snapshot)
    return {"spans": spans, "metrics": proc_metrics}


def export_trace(path: str) -> str:
    """Write the Perfetto-loadable trace; returns ``path``. Required keys per
    event: ``ph/ts/pid/tid/name`` (the round-trip test asserts them)."""
    gathered = _gather()
    events: List[dict] = []
    # display pids are synthesized per (role, os-pid) pair: two processes on
    # DIFFERENT hosts can share an OS pid, and worker/agent roles carry a
    # unique discriminator (actor id / node ip) — keying on the pair keeps
    # each process on its own labeled Perfetto track
    proc_track: Dict[tuple, int] = {}
    for record in gathered["spans"]:
        os_pid = int(record.get("pid", 0))
        proc = str(record.get("proc", "proc"))
        track_key = (proc, os_pid)
        if track_key not in proc_track:
            proc_track[track_key] = len(proc_track) + 1
            events.append(
                {
                    "ph": "M",
                    "name": "process_name",
                    "pid": proc_track[track_key],
                    "tid": 0,
                    "ts": 0,
                    "args": {"name": f"{proc} (pid {os_pid})"},
                }
            )
        pid = proc_track[track_key]
        args = dict(record.get("args") or {})
        args["trace_id"] = record.get("trace")
        args["span_id"] = record.get("id")
        if record.get("parent"):
            args["parent_id"] = record["parent"]
        event = {
            "ph": record.get("ph", "X"),
            "name": str(record.get("name", "span")),
            "ts": int(record.get("ts", 0)),
            "pid": pid,
            "tid": int(record.get("tid", 0)),
            "cat": str(record.get("name", "span")).split(".", 1)[0],
            "args": args,
        }
        if event["ph"] == "X":
            event["dur"] = int(record.get("dur", 0))
        else:
            event["s"] = "p"  # process-scoped instant
        events.append(event)
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"metrics": gathered["metrics"]},
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def dump_metrics() -> Dict[str, dict]:
    """Merged ``{"<role>:<pid>": {metric: snapshot}}`` across every process
    that has flushed, plus this process's live registry."""
    return _gather(drain=False)["metrics"]
