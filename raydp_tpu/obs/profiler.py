"""Compute observatory: step profiler, capture windows, memory watermarks.

PR 14 made the control plane observable; this module watches the COMPUTE
plane — the half of the system ROADMAP item 2's kernel work will be
measured with (the xprof/JAX-profiler role in the TPU ecosystem, the
Dapper-style complement to request tracing). Three pieces:

- **Step profiler** (:class:`StepPhaseRecorder`): always-on per-step phase
  decomposition of a fit — host decode/ingest wait, H2D upload, jitted
  compute, device sync — feeding ``estimator.step.{ingest,h2d,compute,
  sync}_ms`` histograms into the PR 14 TSDB (scrapeable mid-fit). The
  instruments are the registry's lock-free histograms; overhead is gated
  ≤5% on the fit step p50 in perf_smoke (``fit_profile_probe``), and
  ``RAYDP_TPU_STEP_PROFILER=0`` turns the recorder into a shared no-op.
- **Capture window** (:class:`CaptureWindow` / :func:`profile_fit`): an
  on-demand deep capture — wraps ``jax.profiler`` start/stop_trace when
  the backend supports it, and ALWAYS collects the obs span records of the
  wrapped region (span-only capture is the CPU fallback, never a failure).
  Artifacts land under :func:`artifacts_dir` (gitignored ``artifacts/``).
- **Memory watermark plane** (:func:`sample_memory`): per-process RSS,
  /dev/shm namespace live bytes, device live-array bytes, and a
  ``mem.pressure`` fraction — sampled on the existing obs flush ticks (the
  tracing layer calls :func:`sample_memory` before every snapshot ship),
  recorded as high-watermark gauges so the TSDB carries both the live
  value and the peak (``mem.rss_bytes`` / ``mem.rss_bytes.max`` series).
  Crash dossiers attach the per-process ``mem.*`` tails; the elasticity
  and serve-autoscaler controllers read ``mem.pressure`` before growing.

Stdlib-only at import (jax strictly on demand, and NEVER imported by the
memory sampler — a ``python -S`` worker without jax must flush cleanly).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from raydp_tpu.obs.metrics import metrics

STEP_PROFILER_ENV = "RAYDP_TPU_STEP_PROFILER"
ARTIFACTS_DIR_ENV = "RAYDP_TPU_ARTIFACTS_DIR"
JAX_PROFILER_ENV = "RAYDP_TPU_JAX_PROFILER"

STEP_PHASES = ("ingest", "h2d", "compute", "sync")

_step_profiler_on = os.environ.get(STEP_PROFILER_ENV, "1") not in (
    "0", "false", "False"
)


def step_profiler_enabled() -> bool:
    return _step_profiler_on


def set_step_profiler(on: bool) -> None:
    """Bench/test hook (the ``fit_profile_probe`` A/B arm); prefer the env
    var so spawned processes agree."""
    global _step_profiler_on
    _step_profiler_on = bool(on)


def artifacts_dir(*sub: str) -> str:
    """The gitignored artifact root (``artifacts/`` or
    ``RAYDP_TPU_ARTIFACTS_DIR``), with optional subdirs, created on
    demand — bench traces, profiler captures, and tool outputs all land
    here instead of littering the repo root."""
    root = os.environ.get(ARTIFACTS_DIR_ENV, "artifacts")
    path = os.path.join(root, *sub) if sub else root
    os.makedirs(path, exist_ok=True)
    return path


# ---------------------------------------------------------------------------
# step profiler
# ---------------------------------------------------------------------------


class _NoopRecorder:
    """Shared do-nothing recorder for the disabled arm: the per-step call
    sites stay branch-free (one attr call, two pass statements)."""

    __slots__ = ()
    enabled = False
    steps = 0

    def note(self, phase: str, seconds: float, steps: int = 1) -> None:
        pass

    def totals(self) -> Dict[str, float]:
        return {}


_NOOP_RECORDER = _NoopRecorder()


class StepPhaseRecorder:
    """Accumulates one fit's per-step phase decomposition.

    ``note(phase, seconds, steps)`` charges ``seconds`` of wall time to a
    phase across ``steps`` train steps: the per-step loop calls it once per
    step, the segment-scanned paths once per segment with ``steps=S`` (the
    histogram then records the per-step average for that segment — the
    honest granularity when S steps ride one dispatch). Instruments are
    resolved ONCE (the per-step hot path is a float add + a lock-free
    histogram observe)."""

    __slots__ = ("enabled", "steps", "_totals", "_hists")

    def __init__(self):
        self.enabled = True
        self.steps = 0
        self._totals = {phase: 0.0 for phase in STEP_PHASES}
        self._hists = {
            phase: metrics.histogram(f"estimator.step.{phase}_ms")
            for phase in STEP_PHASES
        }

    def note(self, phase: str, seconds: float, steps: int = 1) -> None:
        if seconds < 0.0:
            seconds = 0.0
        self._totals[phase] += seconds
        if phase == "compute":
            self.steps += steps
        self._hists[phase].observe(seconds / max(steps, 1) * 1000.0)

    def totals(self) -> Dict[str, float]:
        return dict(self._totals)


def step_recorder() -> Any:
    """A fresh recorder for one fit — or the shared no-op when the step
    profiler is off."""
    return StepPhaseRecorder() if _step_profiler_on else _NOOP_RECORDER


# ---------------------------------------------------------------------------
# capture window (on-demand deep profile)
# ---------------------------------------------------------------------------

_capture_lock = threading.Lock()
_armed_capture: Optional["CaptureWindow"] = None


def armed_capture() -> Optional["CaptureWindow"]:
    """The capture window the next (or current) fit should feed, if any —
    the estimator's step paths poll this once per fit."""
    return _armed_capture


class CaptureWindow:
    """On-demand deep capture of a compute region.

    Two modes share one class:

    - ``steps=None`` (the serve replica's ``profile()``): the window brackets
      the ``with`` body — jax trace starts at enter, stops at exit.
    - ``steps=N`` (``session.profile_fit``): the window ARMS itself; the
      estimator's step paths call :meth:`begin_steps` at the first step and
      :meth:`note_step` per step, and the jax trace stops after N steps
      while the fit runs on — a bounded capture of a steady-state slice.

    Either way the obs span records of the window are collected on the
    entering thread (span-only capture — the guaranteed floor when
    ``jax.profiler`` is unavailable, disabled via ``RAYDP_TPU_JAX_PROFILER=0``,
    or the backend refuses to trace) and written to
    ``<out_dir>/spans.json`` at exit. ``result()`` summarizes."""

    def __init__(self, steps: Optional[int] = None,
                 out_dir: Optional[str] = None, jax_trace: bool = True):
        from raydp_tpu.obs import tracing

        self.steps = int(steps) if steps else None
        self.out_dir = out_dir or os.path.join(
            artifacts_dir("profiles"), time.strftime("%Y%m%dT%H%M%S")
        )
        self._want_jax = bool(jax_trace) and os.environ.get(
            JAX_PROFILER_ENV, "1"
        ) not in ("0", "false", "False")
        self._collector = tracing.collect()
        self.records: List[dict] = []
        self.jax_trace_dir: Optional[str] = None
        self._jax_active = False
        self._budget_done = False  # step budget exhausted: stay stopped
        self._seen_steps = 0
        self.path: Optional[str] = None

    # -- jax trace half --------------------------------------------------

    def _start_jax(self) -> None:
        if not self._want_jax or self._jax_active:
            return
        try:
            import jax

            trace_dir = os.path.join(self.out_dir, "jax_trace")
            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
            self._jax_active = True
            self.jax_trace_dir = trace_dir
        except Exception:  # raydp-lint: disable=swallowed-exceptions (no jax / backend refuses to trace: span-only capture is the documented fallback)
            self._want_jax = False

    def _stop_jax(self) -> None:
        if not self._jax_active:
            return
        self._jax_active = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:  # raydp-lint: disable=swallowed-exceptions (a failed stop must not discard the span capture)
            self.jax_trace_dir = None

    # -- fit-step protocol (driven by the estimator) ---------------------

    def begin_steps(self) -> None:
        """First train step of the captured fit reached: start the deep
        trace (bounded by ``steps``). Called before EVERY dispatch by the
        segment paths — once the budget is spent this must stay a no-op,
        or the trace would restart/stop around every remaining segment."""
        if self.steps is not None and not self._budget_done:
            self._start_jax()

    def note_step(self, n: int = 1) -> None:
        if self.steps is None:
            return
        self._seen_steps += n
        if self._seen_steps >= self.steps and not self._budget_done:
            self._budget_done = True
            self._stop_jax()

    # -- context manager -------------------------------------------------

    def __enter__(self) -> "CaptureWindow":
        global _armed_capture
        with _capture_lock:
            if _armed_capture is not None:
                raise RuntimeError("another profiler capture is active")
            _armed_capture = self
        self.records = self._collector.__enter__()
        if self.steps is None:
            self._start_jax()
        return self

    def __exit__(self, *exc) -> bool:
        global _armed_capture
        self._stop_jax()
        self._collector.__exit__(*exc)
        with _capture_lock:
            if _armed_capture is self:
                _armed_capture = None
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(self.out_dir, "spans.json")
            with open(path, "w") as f:
                json.dump(self.records, f, default=str)
            self.path = path
        except OSError:  # raydp-lint: disable=swallowed-exceptions (a full disk must not fail the profiled fit; the records stay in memory)
            self.path = None
        return False

    def result(self) -> dict:
        return {
            "out_dir": self.out_dir,
            "spans_path": self.path,
            "span_records": len(self.records),
            "jax_trace_dir": self.jax_trace_dir,
            "steps_captured": self._seen_steps if self.steps else None,
        }


def profile_fit(steps: int = 16, out_dir: Optional[str] = None,
                jax_trace: bool = True) -> CaptureWindow:
    """Arm a bounded fit capture::

        with session.profile_fit(steps=32) as cap:
            estimator.fit_on_etl(df)
        print(cap.result())

    The deep (jax) trace covers the first ``steps`` train steps; the span
    capture covers the whole window."""
    return CaptureWindow(steps=steps, out_dir=out_dir, jax_trace=jax_trace)


def capture(out_dir: Optional[str] = None,
            jax_trace: bool = True) -> CaptureWindow:
    """Bracket-style capture (no step budget): used by the serve replica's
    ``profile()`` and any tool that wants one region deep-traced."""
    return CaptureWindow(steps=None, out_dir=out_dir, jax_trace=jax_trace)


# ---------------------------------------------------------------------------
# fit attribution (the analyzer over the fit span tree)
# ---------------------------------------------------------------------------


def explain_fit(records: List[dict], top_k: int = 5) -> dict:
    """Critical-path attribution of one fit's span records (the PR 14
    analyzer over the ``estimator.fit`` tree: epoch/compile/eval children,
    epoch leaves phase-split by the step profiler's ingest/h2d/compute/sync
    args). ``JaxEstimator.explain_last_fit()`` is the instance-method
    spelling."""
    from raydp_tpu.obs.analysis import attribute, format_report

    report = attribute(records, root_name="estimator.fit", top_k=top_k)
    report["text"] = format_report(report)
    return report


# ---------------------------------------------------------------------------
# memory watermark plane
# ---------------------------------------------------------------------------

MEM_SAMPLE_MIN_INTERVAL_S = 1.0

_mem_lock = threading.Lock()
_last_mem_sample = 0.0
_page_size = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _read_rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _page_size
    except (OSError, ValueError, IndexError):
        try:
            import resource

            # ru_maxrss is the PEAK (KB on linux) — an acceptable stand-in
            # where /proc is absent; the watermark gauge makes peak vs live
            # explicit either way
            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:  # raydp-lint: disable=swallowed-exceptions (no rss source on this platform: the series is simply absent)
            return None


def _shm_live_bytes() -> Optional[int]:
    """Live bytes of this node's /dev/shm namespace (segments are named
    ``rtpu-<ns>-<id>``; an empty namespace owns the un-prefixed pool)."""
    ns = os.environ.get("RAYDP_TPU_SHM_NS", "")
    prefix = f"rtpu-{ns}-" if ns else "rtpu-"
    total = 0
    try:
        with os.scandir("/dev/shm") as entries:
            for entry in entries:
                if not entry.name.startswith(prefix):
                    continue
                try:
                    total += entry.stat().st_size
                except OSError:  # raydp-lint: disable=swallowed-exceptions (segment unlinked mid-scan)
                    continue
    except OSError:
        return None
    return total


def _device_live_bytes() -> Optional[int]:
    """Device live-array bytes — ONLY when jax is already imported (the
    sampler must never be the thing that drags jax into a worker)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        stats = jax.devices()[0].memory_stats() or {}
        in_use = stats.get("bytes_in_use")
        if in_use is not None:
            return int(in_use)
    except Exception:  # raydp-lint: disable=swallowed-exceptions (backend without memory stats: fall through to live_arrays)
        pass
    try:
        return int(sum(a.nbytes for a in jax.live_arrays()))
    except Exception:  # raydp-lint: disable=swallowed-exceptions (no live-array introspection on this backend either)
        return None


def _mem_pressure() -> Optional[float]:
    """Host memory pressure in [0, 1]: 1 - MemAvailable/MemTotal."""
    try:
        total = avail = None
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = float(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    avail = float(line.split()[1])
                if total is not None and avail is not None:
                    break
        if not total or avail is None:
            return None
        return max(0.0, min(1.0, 1.0 - avail / total))
    except (OSError, ValueError, IndexError):
        return None


def sample_memory(force: bool = False) -> Optional[dict]:
    """Sample this process's memory plane into the registry (high-watermark
    gauges ``mem.{rss,shm,device}_bytes`` + ``mem.pressure``). Rides every
    obs flush tick (tracing.flush calls this first), self-throttled to
    :data:`MEM_SAMPLE_MIN_INTERVAL_S`; returns the sample dict, or None
    when throttled."""
    global _last_mem_sample
    now = time.monotonic()
    with _mem_lock:
        if not force and now - _last_mem_sample < MEM_SAMPLE_MIN_INTERVAL_S:
            return None
        _last_mem_sample = now
    sample: Dict[str, float] = {}
    rss = _read_rss_bytes()
    if rss is not None:
        sample["rss_bytes"] = float(rss)
        metrics.gauge("mem.rss_bytes").set_watermark(rss)
    shm = _shm_live_bytes()
    if shm is not None:
        sample["shm_bytes"] = float(shm)
        metrics.gauge("mem.shm_bytes").set_watermark(shm)
    device = _device_live_bytes()
    if device is not None:
        sample["device_bytes"] = float(device)
        metrics.gauge("mem.device_bytes").set_watermark(device)
    pressure = _mem_pressure()
    if pressure is not None:
        sample["pressure"] = pressure
        metrics.gauge("mem.pressure").set_watermark(pressure)
    return sample


def current_mem_pressure(window_s: float = 10.0) -> float:
    """The controllers' read of host memory pressure: the max over this
    process's recent windowed ``mem.pressure`` series with the live gauge
    as the freshness floor (the serve autoscaler and the elasticity policy
    consult this before growing a pool)."""
    sample_memory()
    live = metrics.gauge("mem.pressure").value
    try:
        from raydp_tpu.obs import timeseries as _ts

        windowed = _ts.windowed_local("mem.pressure", window_s=window_s)
        if windowed["series"] and windowed["max"] is not None:
            return max(live, windowed["max"])
    except Exception:  # raydp-lint: disable=swallowed-exceptions (the live gauge alone is a valid pressure read)
        pass
    return live
