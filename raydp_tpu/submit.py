"""``raydp-tpu-submit`` — CLI job submission.

Parity: the reference's ``bin/raydp-submit`` (reference bin/raydp-submit:62-69)
wraps spark-submit so operators pin executor resources and config from the
command line while the application code stays unchanged. Here the submitted
configuration is published to the child process environment; ``init_etl``
treats it as operator overrides (the spark-submit precedence: CLI conf wins
over application conf).

Usage:
    python -m raydp_tpu.submit --num-executors 4 --executor-cores 2 \
        --executor-memory 2G --conf etl.default.parallelism=16 script.py [args]
"""

from __future__ import annotations

import argparse
import json
import os
import runpy
import sys

SUBMIT_ENV = "RAYDP_TPU_SUBMIT_CONF"


def submitted_overrides() -> dict:
    raw = os.environ.get(SUBMIT_ENV)
    return json.loads(raw) if raw else {}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="raydp-tpu-submit", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--num-executors", type=int)
    parser.add_argument("--executor-cores", type=int)
    parser.add_argument("--executor-memory", type=str)
    parser.add_argument(
        "--conf", action="append", default=[], metavar="KEY=VALUE",
        help="extra session config (repeatable)",
    )
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    overrides: dict = {"configs": {}}
    if args.num_executors is not None:
        overrides["num_executors"] = args.num_executors
    if args.executor_cores is not None:
        overrides["executor_cores"] = args.executor_cores
    if args.executor_memory is not None:
        overrides["executor_memory"] = args.executor_memory
    for conf in args.conf:
        if "=" not in conf:
            parser.error(f"--conf expects KEY=VALUE, got {conf!r}")
        key, value = conf.split("=", 1)
        overrides["configs"][key] = value

    os.environ[SUBMIT_ENV] = json.dumps(overrides)
    sys.argv = [args.script] + args.script_args
    runpy.run_path(args.script, run_name="__main__")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
